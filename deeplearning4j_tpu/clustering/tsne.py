"""t-SNE (ref: org.deeplearning4j.plot.BarnesHutTsne, SURVEY D17).

The reference approximates the repulsive term with a Barnes-Hut quadtree in
Java. On an accelerator the O(N²) pairwise kernel is a single fused matmul-
shaped program that outruns pointer-chasing tree code for any N that fits in
HBM — so `theta` is accepted for API parity but the exact objective runs on
the device (documented divergence; same results, better hardware fit).
"""
from __future__ import annotations

from typing import Optional

import numpy as np


def _binary_search_perplexity(D2: np.ndarray, perplexity: float,
                              tol: float = 1e-5, max_iter: int = 50):
    """Row-wise beta search matching the reference's getPairwiseAffinities."""
    n = D2.shape[0]
    P = np.zeros_like(D2)
    target = np.log(perplexity)
    for i in range(n):
        lo, hi = -np.inf, np.inf
        beta = 1.0
        d = np.delete(D2[i], i)
        for _ in range(max_iter):
            p = np.exp(-d * beta)
            s = max(p.sum(), 1e-12)
            H = np.log(s) + beta * float((d * p).sum()) / s
            diff = H - target
            if abs(diff) < tol:
                break
            if diff > 0:
                lo = beta
                beta = beta * 2 if hi == np.inf else (beta + hi) / 2
            else:
                hi = beta
                beta = beta / 2 if lo == -np.inf else (beta + lo) / 2
        row = np.exp(-D2[i] * beta)
        row[i] = 0.0
        P[i] = row / max(row.sum(), 1e-12)
    return P


class BarnesHutTsne:
    """ref API: BarnesHutTsne.Builder()...build(); fit(X); getData()."""

    def __init__(self, n_dims: int = 2, perplexity: float = 30.0,
                 theta: float = 0.5, max_iter: int = 500,
                 learning_rate: float = 200.0, momentum: float = 0.8,
                 seed: int = 0):
        self.n_dims = n_dims
        self.perplexity = perplexity
        self.theta = theta
        self.max_iter = max_iter
        self.learning_rate = learning_rate
        self.momentum = momentum
        self.seed = seed
        self.Y: Optional[np.ndarray] = None

    class Builder:
        def __init__(self):
            self._kw = {}

        def _set(self, k, v):
            self._kw[k] = v
            return self

        def set_max_iter(self, v): return self._set("max_iter", v)
        setMaxIter = set_max_iter
        def theta(self, v): return self._set("theta", v)
        def perplexity(self, v): return self._set("perplexity", v)
        def number_dimension(self, v): return self._set("n_dims", v)
        numberDimension = number_dimension
        def learning_rate(self, v): return self._set("learning_rate", v)
        learningRate = learning_rate
        def seed(self, v): return self._set("seed", v)

        def build(self):
            return BarnesHutTsne(**self._kw)

    def fit(self, X) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        X = np.asarray(X, dtype=np.float32)
        n = X.shape[0]
        perp = min(self.perplexity, (n - 1) / 3.0)
        # gram trick: O(N^2) memory, not O(N^2 * d)
        sq = np.sum(X * X, axis=1)
        D2 = np.maximum(sq[:, None] - 2.0 * (X @ X.T) + sq[None, :], 0.0)
        P = _binary_search_perplexity(D2, perp)
        P = (P + P.T) / (2.0 * n)
        P = np.maximum(P, 1e-12)
        Pj = jnp.asarray(P * 4.0)          # early exaggeration
        rng = np.random.RandomState(self.seed)
        Y = jnp.asarray(rng.randn(n, self.n_dims).astype(np.float32) * 1e-4)

        @jax.jit
        def update(P, Y, V, gains, momentum):
            d2 = jnp.sum((Y[:, None, :] - Y[None, :, :]) ** 2, -1)
            num = 1.0 / (1.0 + d2)
            num = num - jnp.diag(jnp.diag(num))
            Q = jnp.maximum(num / jnp.sum(num), 1e-12)
            PQ = (P - Q) * num
            g = 4.0 * jnp.einsum("ij,ijd->id",
                                 PQ, Y[:, None, :] - Y[None, :, :])
            kl = jnp.sum(P * jnp.log(P / Q))
            # per-dim adaptive gains (van der Maaten's reference dynamics —
            # lr ~200 diverges without them)
            same = (g > 0) == (V > 0)
            gains = jnp.maximum(jnp.where(same, gains * 0.8, gains + 0.2),
                                0.01)
            V = momentum * V - self.learning_rate * gains * g
            Y = Y + V
            Y = Y - jnp.mean(Y, axis=0)
            return Y, V, gains, kl

        V = jnp.zeros_like(Y)
        gains = jnp.ones_like(Y)
        kl = None
        stop_exaggeration = min(100, max(self.max_iter // 2, 1))
        for it in range(self.max_iter):
            if it == stop_exaggeration:
                Pj = Pj / 4.0             # end early exaggeration
            momentum = 0.5 if it < 20 else self.momentum
            Y, V, gains, kl = update(Pj, Y, V, gains, momentum)
        self.Y = np.asarray(Y)
        self.kl_divergence = float(kl)
        return self.Y

    def get_data(self) -> np.ndarray:
        return self.Y

    getData = get_data
