"""Clustering, nearest-neighbor search, manifold learning, graph embeddings
(ref: deeplearning4j-nearestneighbors-parent + deeplearning4j-manifold +
deeplearning4j-graph — SURVEY D17/D18)."""
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.clustering.lsh import RandomProjectionLSH
from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne
from deeplearning4j_tpu.clustering.deepwalk import DeepWalk, GraphFactory

__all__ = ["KMeansClustering", "VPTree", "RandomProjectionLSH",
           "BarnesHutTsne", "DeepWalk", "GraphFactory"]
