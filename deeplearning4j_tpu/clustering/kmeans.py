"""KMeans (ref: org.deeplearning4j.clustering.kmeans.KMeansClustering,
SURVEY D17). Lloyd iterations as one jitted program per step: the (N, K)
distance block is a single MXU matmul, assignment + centroid update are
fused reductions — no per-point Java loops."""
from __future__ import annotations

from typing import List, Optional

import numpy as np


class Point:
    def __init__(self, idx, array):
        self.id = idx
        self.array = np.asarray(array)


class Cluster:
    def __init__(self, center, points):
        self.center = np.asarray(center)
        self.points = points

    def get_center(self):
        return self.center

    getCenter = get_center


class ClusterSet:
    def __init__(self, clusters: List[Cluster]):
        self.clusters = clusters

    def get_clusters(self):
        return self.clusters

    getClusters = get_clusters


class KMeansClustering:
    """ref API: KMeansClustering.setup(k, maxIter, distance) →
    applyTo(points)."""

    def __init__(self, k: int, max_iterations: int = 100,
                 distance: str = "euclidean", seed: int = 0,
                 tol: float = 1e-6):
        self.k = k
        self.max_iterations = max_iterations
        self.distance = distance
        self.seed = seed
        self.tol = tol

    @staticmethod
    def setup(k: int, max_iterations: int = 100,
              distance: str = "euclidean", seed: int = 0) -> "KMeansClustering":
        return KMeansClustering(k, max_iterations, distance, seed)

    def apply_to(self, points) -> ClusterSet:
        import jax
        import jax.numpy as jnp

        X = np.asarray([p.array if isinstance(p, Point) else p
                        for p in points], dtype=np.float32)
        n, d = X.shape
        rng = np.random.RandomState(self.seed)
        # kmeans++ init (ref uses random; ++ strictly improves)
        centers = [X[rng.randint(n)]]
        for _ in range(1, self.k):
            d2 = np.min([((X - c) ** 2).sum(1) for c in centers], axis=0)
            total = d2.sum()
            if total <= 0:      # duplicates / k > distinct points
                centers.append(X[rng.randint(n)])
            else:
                centers.append(X[rng.choice(n, p=d2 / total)])
        C = jnp.asarray(np.stack(centers))
        Xd = jnp.asarray(X)
        cosine = self.distance.lower().startswith("cos")

        @jax.jit
        def step(C):
            if cosine:
                Xn = Xd / (jnp.linalg.norm(Xd, axis=1, keepdims=True) + 1e-12)
                Cn = C / (jnp.linalg.norm(C, axis=1, keepdims=True) + 1e-12)
                dist = 1.0 - Xn @ Cn.T
            else:
                dist = (jnp.sum(Xd * Xd, 1)[:, None]
                        - 2.0 * Xd @ C.T + jnp.sum(C * C, 1)[None, :])
            assign = jnp.argmin(dist, axis=1)
            onehot = jax.nn.one_hot(assign, self.k, dtype=Xd.dtype)
            counts = jnp.maximum(onehot.sum(0), 1.0)
            newC = (onehot.T @ Xd) / counts[:, None]
            # keep empty clusters where they were
            newC = jnp.where((onehot.sum(0) > 0)[:, None], newC, C)
            return newC, assign

        assign = None
        for _ in range(self.max_iterations):
            newC, assign = step(C)
            if float(jnp.max(jnp.abs(newC - C))) < self.tol:
                C = newC
                break
            C = newC
        assign = np.asarray(assign)
        C = np.asarray(C)
        clusters = []
        for ci in range(self.k):
            idx = np.where(assign == ci)[0]
            clusters.append(Cluster(C[ci], [Point(int(i), X[i]) for i in idx]))
        return ClusterSet(clusters)

    applyTo = apply_to
