"""DeepWalk graph embeddings
(ref: org.deeplearning4j.graph.models.deepwalk.DeepWalk + graph.api.*,
SURVEY D18): uniform random walks over the graph feed the same jitted SGNS
trainer as Word2Vec — vertices are "words", walks are "sentences"."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.sentence import CollectionSentenceIterator
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


class Graph:
    """Minimal undirected graph (ref: org.deeplearning4j.graph.graph.Graph)."""

    def __init__(self, num_vertices: int):
        self.n = num_vertices
        self.adj: List[List[int]] = [[] for _ in range(num_vertices)]

    def add_edge(self, a: int, b: int, directed: bool = False):
        self.adj[a].append(b)
        if not directed:
            self.adj[b].append(a)

    addEdge = add_edge

    def num_vertices(self) -> int:
        return self.n

    numVertices = num_vertices

    def get_connected_vertices(self, v: int) -> List[int]:
        return self.adj[v]

    getConnectedVertices = get_connected_vertices


class GraphFactory:
    @staticmethod
    def from_edge_list(num_vertices: int,
                       edges: Sequence[Tuple[int, int]],
                       directed: bool = False) -> Graph:
        g = Graph(num_vertices)
        for a, b in edges:
            g.add_edge(a, b, directed)
        return g


class DeepWalk:
    """ref API: DeepWalk.Builder().vectorSize(d).windowSize(w).build();
    initialize(graph); fit(walk_iterator) — here fit(graph) runs walks
    internally."""

    def __init__(self, vector_size: int = 64, window_size: int = 5,
                 walk_length: int = 40, walks_per_vertex: int = 10,
                 learning_rate: float = 0.025, seed: int = 0,
                 epochs: int = 1, negative: int = 5):
        self.vector_size = vector_size
        self.window_size = window_size
        self.walk_length = walk_length
        self.walks_per_vertex = walks_per_vertex
        self.learning_rate = learning_rate
        self.seed = seed
        self.epochs = epochs
        self.negative = negative
        self._w2v: Optional[Word2Vec] = None

    class Builder:
        def __init__(self):
            self._kw = {}

        def _set(self, k, v):
            self._kw[k] = v
            return self

        def vector_size(self, v): return self._set("vector_size", v)
        vectorSize = vector_size
        def window_size(self, v): return self._set("window_size", v)
        windowSize = window_size
        def walk_length(self, v): return self._set("walk_length", v)
        walkLength = walk_length
        def walks_per_vertex(self, v): return self._set("walks_per_vertex", v)
        walksPerVertex = walks_per_vertex
        def negative_sample(self, v): return self._set("negative", v)
        negativeSample = negative_sample
        def learning_rate(self, v): return self._set("learning_rate", v)
        learningRate = learning_rate
        def seed(self, v): return self._set("seed", v)
        def epochs(self, v): return self._set("epochs", v)

        def build(self):
            return DeepWalk(**self._kw)

    def _walks(self, graph: Graph, rng) -> List[str]:
        sentences = []
        order = np.arange(graph.num_vertices())
        for _ in range(self.walks_per_vertex):
            rng.shuffle(order)
            for start in order:
                walk = [int(start)]
                for _ in range(self.walk_length - 1):
                    nbrs = graph.get_connected_vertices(walk[-1])
                    if not nbrs:
                        break
                    walk.append(int(nbrs[rng.randint(len(nbrs))]))
                sentences.append(" ".join(str(v) for v in walk))
        return sentences

    def fit(self, graph: Graph) -> "DeepWalk":
        rng = np.random.RandomState(self.seed)
        sentences = self._walks(graph, rng)
        self._w2v = Word2Vec(
            layer_size=self.vector_size, window_size=self.window_size,
            min_word_frequency=1, epochs=self.epochs,
            negative=self.negative, learning_rate=self.learning_rate,
            sample=0.0, seed=self.seed,
            iterator=CollectionSentenceIterator(sentences))
        self._w2v.fit()
        return self

    def get_vertex_vector(self, v: int) -> np.ndarray:
        return self._w2v.get_word_vector(str(v))

    getVertexVector = get_vertex_vector

    def similarity(self, a: int, b: int) -> float:
        return self._w2v.similarity(str(a), str(b))

    def verticies_nearest(self, v: int, top_n: int = 5) -> List[int]:
        return [int(w) for w in self._w2v.words_nearest(str(v), top_n)]

    verticesNearest = verticies_nearest
