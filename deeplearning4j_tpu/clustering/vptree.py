"""VP-tree exact nearest neighbors
(ref: org.deeplearning4j.clustering.vptree.VPTree, SURVEY D17)."""
from __future__ import annotations

import heapq
from typing import List, Optional, Tuple

import numpy as np


class _Node:
    __slots__ = ("index", "threshold", "inside", "outside", "bucket")

    def __init__(self, index, threshold=0.0, inside=None, outside=None,
                 bucket=None):
        self.index = index
        self.threshold = threshold
        self.inside = inside
        self.outside = outside
        self.bucket = bucket      # leaf bucket for degenerate splits


class VPTree:
    """Exact metric-tree k-NN (Euclidean or cosine distance)."""

    def __init__(self, items, distance: str = "euclidean", seed: int = 0):
        self.items = np.asarray(items, dtype=np.float32)
        self.distance = distance
        self._cos = distance.lower().startswith("cos")
        if self._cos:
            norm = np.linalg.norm(self.items, axis=1, keepdims=True)
            self._normed = self.items / np.maximum(norm, 1e-12)
        self._rng = np.random.RandomState(seed)
        self.root = self._build(list(range(len(self.items))))

    def _dist_many(self, q: np.ndarray, idx) -> np.ndarray:
        if self._cos:
            # search in sqrt(2-2cos) — Euclidean over normalized vectors, a
            # true metric with the same ranking; 1-cos violates the triangle
            # inequality the pruning bounds rely on (knn converts back)
            qn = q / max(np.linalg.norm(q), 1e-12)
            return np.sqrt(np.maximum(2.0 - 2.0 * (self._normed[idx] @ qn),
                                      0.0))
        diff = self.items[idx] - q
        return np.sqrt(np.sum(diff * diff, axis=1))

    def _build(self, idx: List[int]) -> Optional[_Node]:
        if not idx:
            return None
        if len(idx) == 1:
            return _Node(idx[0])
        vp_pos = self._rng.randint(len(idx))
        vp = idx.pop(vp_pos)
        d = self._dist_many(self.items[vp], idx)
        median = float(np.median(d))
        inside = [i for i, di in zip(idx, d) if di <= median]
        outside = [i for i, di in zip(idx, d) if di > median]
        if not outside and len(inside) > 1:
            # degenerate split (duplicate points / equal distances): store a
            # linear-scan leaf bucket instead of recursing once per point
            return _Node(vp, median, bucket=inside)
        return _Node(vp, median, self._build(inside), self._build(outside))

    def knn(self, query, k: int = 1) -> Tuple[List[int], List[float]]:
        """Indices + distances of the k nearest items (ref: VPTree#search)."""
        q = np.asarray(query, dtype=np.float32)
        heap: List[Tuple[float, int]] = []   # max-heap by -distance
        tau = [np.inf]

        def consider(i, d):
            if len(heap) < k:
                heapq.heappush(heap, (-d, i))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, i))
                tau[0] = -heap[0][0]

        def search(node):
            if node is None:
                return
            d = float(self._dist_many(q, [node.index])[0])
            consider(node.index, d)
            if node.bucket is not None:
                for i, di in zip(node.bucket,
                                 self._dist_many(q, node.bucket)):
                    consider(i, float(di))
                return
            if node.inside is None and node.outside is None:
                return
            if d <= node.threshold:
                search(node.inside)
                if d + tau[0] > node.threshold:
                    search(node.outside)
            else:
                search(node.outside)
                if d - tau[0] <= node.threshold:
                    search(node.inside)

        search(self.root)
        out = sorted(((-nd, i) for nd, i in heap))
        dists = [d for d, _ in out]
        if self._cos:
            dists = [d * d / 2.0 for d in dists]   # back to 1-cos
        return [i for _, i in out], dists

    search = knn
