"""Locality-sensitive hashing for approximate nearest neighbours.

Reference: ``org.deeplearning4j.clustering.lsh.RandomProjectionLSH``
(deeplearning4j-nearestneighbors — SURVEY D17): signed random projections
(SimHash) over a set of hash tables; candidates = points sharing a bucket in
any table, re-ranked by exact distance.

TPU-first: hashing the whole corpus is ONE matmul per table batch
((N, D) @ (D, bits) on the MXU) followed by a bit-pack; queries hash the
same way. Bucket lookup stays on the host (hash maps are not an XLA shape).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


class RandomProjectionLSH:
    """SimHash ANN index (ref API: RandomProjectionLSH(hashLength, numTables,
    dim); #makeIndex, #search)."""

    def __init__(self, hash_length: int = 12, num_tables: int = 4,
                 dim: int = None, seed: int = 0):
        self.hash_length = hash_length
        self.num_tables = num_tables
        self.dim = dim
        self.seed = seed
        self._planes = None          # (T, D, bits)
        self._tables: List[Dict[int, List[int]]] = []
        self._data: np.ndarray = None

    def _hash(self, x: np.ndarray) -> np.ndarray:
        """(N, D) → (T, N) bucket keys via one (N,D)@(D,bits) matmul per
        table (jitted batch on device)."""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def proj(x, planes):
            # (T, N, bits) sign bits in one einsum
            s = jnp.einsum("nd,tdb->tnb", x, planes) >= 0
            weights = jnp.asarray(1 << np.arange(self.hash_length),
                                  jnp.uint32)
            return jnp.sum(s.astype(jnp.uint32) * weights, axis=-1)

        return np.asarray(proj(jnp.asarray(x, jnp.float32),
                               jnp.asarray(self._planes, jnp.float32)))

    def make_index(self, data) -> "RandomProjectionLSH":
        data = np.asarray(data, np.float32)
        n, d = data.shape
        if self.dim is None:
            self.dim = d
        rng = np.random.default_rng(self.seed)
        self._planes = rng.normal(
            size=(self.num_tables, self.dim, self.hash_length))
        self._data = data
        keys = self._hash(data)                       # (T, N)
        self._tables = []
        for t in range(self.num_tables):
            tbl: Dict[int, List[int]] = {}
            for i, k in enumerate(keys[t]):
                tbl.setdefault(int(k), []).append(i)
            self._tables.append(tbl)
        return self

    makeIndex = make_index

    def _candidates(self, q: np.ndarray) -> np.ndarray:
        keys = self._hash(q[None])                    # (T, 1)
        cand = set()
        for t in range(self.num_tables):
            cand.update(self._tables[t].get(int(keys[t, 0]), ()))
        return np.fromiter(cand, dtype=np.int64) if cand else np.zeros(0, np.int64)

    def search(self, query, k: int = 1) -> Tuple[List[int], List[float]]:
        """k approximate nearest neighbours: bucket candidates re-ranked by
        exact euclidean distance (falls back to brute force when the buckets
        are empty — matching the reference's behavior of never returning
        nothing for a valid query)."""
        q = np.asarray(query, np.float32).reshape(-1)
        cand = self._candidates(q)
        if len(cand) == 0:
            cand = np.arange(len(self._data))
        d = np.linalg.norm(self._data[cand] - q[None], axis=1)
        order = np.argsort(d)[:k]
        return [int(cand[i]) for i in order], [float(d[i]) for i in order]

    def bucket(self, query) -> np.ndarray:
        """All candidate indices sharing a bucket with the query (ref:
        #bucket)."""
        return self._candidates(np.asarray(query, np.float32).reshape(-1))
