"""ctypes bindings for the native host-ops library.

The JavaCPP-preset analog (SURVEY N10): a thin binding layer over a flat C
ABI (``src/host_ops.cpp``). The library is built on demand with ``make``
(g++); every function has a pure-numpy fallback so the package works
without a toolchain — ``is_native()`` reports which path is live.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libdl4jtpu_host.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None or _build_failed:
            return _lib
        # always invoke make: it's a no-op when fresh and rebuilds after
        # source edits (stale-.so bugs are silent otherwise)
        try:
            subprocess.run(["make", "-C", _DIR], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            if not os.path.exists(_LIB_PATH):
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            _build_failed = True
            return None
        lib.threshold_encode.restype = ctypes.c_int64
        lib.threshold_encode.argtypes = [
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
        lib.threshold_decode.restype = ctypes.c_int64
        lib.threshold_decode.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_float,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
        lib.csv_count.restype = ctypes.c_int64
        lib.csv_count.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                  ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_int64)]
        lib.csv_parse.restype = ctypes.c_int64
        lib.csv_parse.argtypes = [ctypes.c_char_p, ctypes.c_char,
                                  ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_float),
                                  ctypes.c_int64, ctypes.c_int64]
        lib.shuffle_indices.restype = None
        lib.shuffle_indices.argtypes = [ctypes.POINTER(ctypes.c_int64),
                                        ctypes.c_int64, ctypes.c_uint64]
        _lib = lib
        return _lib


def is_native() -> bool:
    """True when the C++ library is loaded (vs numpy fallback)."""
    return _load() is not None


# -------------------------------------------------------------- threshold
def threshold_encode_host(residual: np.ndarray, threshold: float,
                          capacity: int) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side codec: returns (encoded int32 (capacity+1,), new residual).

    The residual passed in is NOT mutated (a copy is updated), matching the
    jax codec's functional signature.
    """
    res = np.ascontiguousarray(residual, dtype=np.float32).copy()
    flat = res.reshape(-1)
    out = np.zeros(capacity + 1, dtype=np.int32)
    lib = _load()
    if lib is not None:
        lib.threshold_encode(
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            flat.size, ctypes.c_float(threshold),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), capacity)
        return out, res
    # numpy fallback
    hit = np.nonzero(np.abs(flat) >= threshold)[0][:capacity]
    sign = np.sign(flat[hit])
    out[0] = len(hit)
    out[1:1 + len(hit)] = ((hit + 1) * sign).astype(np.int32)
    flat[hit] -= sign.astype(np.float32) * threshold
    return out, res


def threshold_decode_host(encoded: np.ndarray, threshold: float,
                          target: np.ndarray) -> np.ndarray:
    """Accumulate the decoded update into a copy of ``target``."""
    tgt = np.ascontiguousarray(target, dtype=np.float32).copy()
    flat = tgt.reshape(-1)
    enc = np.ascontiguousarray(encoded, dtype=np.int32)
    lib = _load()
    if lib is not None:
        lib.threshold_decode(
            enc.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_float(threshold),
            flat.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), flat.size)
        return tgt
    n = enc[0]
    entries = enc[1:1 + n]
    entries = entries[entries != 0]
    idx = np.abs(entries) - 1
    np.add.at(flat, idx, np.sign(entries).astype(np.float32) * threshold)
    return tgt


# ------------------------------------------------------------------- csv
def csv_read_floats(path: str, delimiter: str = ",",
                    skip_rows: int = 0) -> np.ndarray:
    """Parse a numeric CSV file into a (rows, cols) float32 array; fields
    that fail to parse are NaN. Native fast path with numpy fallback."""
    lib = _load()
    if lib is not None:
        cols = ctypes.c_int64(0)
        rows = lib.csv_count(path.encode(), delimiter.encode(), skip_rows,
                             ctypes.byref(cols))
        if rows < 0:
            raise FileNotFoundError(path)
        out = np.empty((rows, cols.value), dtype=np.float32)
        got = lib.csv_parse(path.encode(), delimiter.encode(), skip_rows,
                            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                            rows, cols.value)
        return out[:got]
    # fallback — skip_rows counts non-blank rows, like the native path
    rows = []
    seen = 0
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            seen += 1
            if seen <= skip_rows:
                continue
            vals = []
            for tok in line.rstrip("\n").split(delimiter):
                try:
                    vals.append(float(tok))
                except ValueError:
                    vals.append(float("nan"))
            rows.append(vals)
    width = max((len(r) for r in rows), default=0)
    out = np.full((len(rows), width), np.nan, dtype=np.float32)
    for i, r in enumerate(rows):
        out[i, :len(r)] = r
    return out


def shuffle_indices(n: int, seed: int = 0) -> np.ndarray:
    """Native Fisher-Yates permutation of [0, n)."""
    idx = np.arange(n, dtype=np.int64)
    lib = _load()
    if lib is not None:
        lib.shuffle_indices(idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                            n, ctypes.c_uint64(seed))
        return idx
    rng = np.random.RandomState(seed & 0x7FFFFFFF)
    rng.shuffle(idx)
    return idx
