"""ctypes binding for the PJRT C-API shim (``src/pjrt_shim.cpp``).

The JavaCPP-preset-for-PJRT analog (SURVEY N5/N10): loads
``libdl4jtpu_pjrt.so`` (built on demand by the package Makefile), which in
turn dlopens any conforming PJRT plugin — ``libtpu.so`` for real TPU
hardware, or any other ``GetPjrtApi``-exporting library — and drives the
full compile/transfer/execute cycle on it from Python with zero Python-level
jax involvement. This is the path a non-Python frontend (the reference's
Java API) would bind against.
"""
from __future__ import annotations

import ctypes
import os
import sysconfig
from typing import Optional, Sequence

import numpy as np

from deeplearning4j_tpu.native import _load as _load_host  # triggers make

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libdl4jtpu_pjrt.so")
_ERRLEN = 4096


def default_tpu_plugin_path() -> Optional[str]:
    """Path of the bundled libtpu PJRT plugin, if installed."""
    p = os.path.join(sysconfig.get_paths()["purelib"], "libtpu", "libtpu.so")
    return p if os.path.exists(p) else None


def _lib() -> ctypes.CDLL:
    _load_host()          # runs make (builds both .so targets)
    if not os.path.exists(_LIB_PATH):
        raise RuntimeError(
            "libdl4jtpu_pjrt.so not built (pjrt_c_api.h unavailable?)")
    lib = ctypes.CDLL(_LIB_PATH)
    lib.nd4j_pjrt_load_plugin.restype = ctypes.c_void_p
    lib.nd4j_pjrt_load_plugin.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                          ctypes.c_int]
    lib.nd4j_pjrt_api_version.restype = ctypes.c_int
    lib.nd4j_pjrt_api_version.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int)]
    lib.nd4j_pjrt_client_create.restype = ctypes.c_void_p
    lib.nd4j_pjrt_client_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                            ctypes.c_int]
    lib.nd4j_pjrt_client_destroy.argtypes = [ctypes.c_void_p]
    lib.nd4j_pjrt_platform_name.restype = ctypes.c_int
    lib.nd4j_pjrt_platform_name.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                            ctypes.c_int]
    lib.nd4j_pjrt_device_count.restype = ctypes.c_int
    lib.nd4j_pjrt_device_count.argtypes = [ctypes.c_void_p]
    lib.nd4j_pjrt_compile.restype = ctypes.c_void_p
    lib.nd4j_pjrt_compile.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p,
        ctypes.c_int64, ctypes.c_char_p, ctypes.c_int]
    lib.nd4j_pjrt_executable_destroy.argtypes = [ctypes.c_void_p]
    lib.nd4j_pjrt_execute_f32.restype = ctypes.c_int
    return lib


def compile_options_bytes() -> bytes:
    """Serialized CompileOptionsProto for a 1-replica/1-partition program."""
    from jax._src.lib import xla_client
    return xla_client.CompileOptions().SerializeAsString()


class PjrtPlugin:
    """A loaded PJRT plugin (its PJRT_Api function table)."""

    def __init__(self, plugin_path: str):
        self._libshim = _lib()
        err = ctypes.create_string_buffer(_ERRLEN)
        self._api = self._libshim.nd4j_pjrt_load_plugin(
            plugin_path.encode(), err, _ERRLEN)
        if not self._api:
            raise RuntimeError(f"PJRT plugin load failed: "
                               f"{err.value.decode(errors='replace')}")
        self.plugin_path = plugin_path

    def api_version(self) -> tuple:
        major = ctypes.c_int()
        minor = ctypes.c_int()
        rc = self._libshim.nd4j_pjrt_api_version(
            self._api, ctypes.byref(major), ctypes.byref(minor))
        if rc != 0:
            raise RuntimeError("api_version failed")
        return major.value, minor.value

    def create_client(self) -> "PjrtClient":
        err = ctypes.create_string_buffer(_ERRLEN)
        client = self._libshim.nd4j_pjrt_client_create(self._api, err, _ERRLEN)
        if not client:
            raise RuntimeError(f"PJRT client create failed: "
                               f"{err.value.decode(errors='replace')}")
        return PjrtClient(self._libshim, client)


class PjrtClient:
    def __init__(self, libshim, client):
        self._libshim = libshim
        self._client = client

    def platform_name(self) -> str:
        buf = ctypes.create_string_buffer(256)
        n = self._libshim.nd4j_pjrt_platform_name(self._client, buf, 256)
        if n < 0:
            raise RuntimeError("platform_name failed")
        return buf.value.decode()

    def device_count(self) -> int:
        return self._libshim.nd4j_pjrt_device_count(self._client)

    def compile_mlir(self, mlir: str,
                     options: Optional[bytes] = None) -> "PjrtExecutable":
        """Compile a StableHLO module (text) into a loaded executable."""
        opts = options if options is not None else compile_options_bytes()
        err = ctypes.create_string_buffer(_ERRLEN)
        code = mlir.encode() if isinstance(mlir, str) else mlir
        exe = self._libshim.nd4j_pjrt_compile(
            self._client, code, len(code), opts, len(opts), err, _ERRLEN)
        if not exe:
            raise RuntimeError(f"PJRT compile failed: "
                               f"{err.value.decode(errors='replace')}")
        return PjrtExecutable(self._libshim, exe)

    def close(self):
        if self._client:
            self._libshim.nd4j_pjrt_client_destroy(self._client)
            self._client = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class PjrtExecutable:
    def __init__(self, libshim, exe):
        self._libshim = libshim
        self._exe = exe

    def execute(self, inputs: Sequence[np.ndarray],
                out_shapes: Sequence[tuple]) -> list:
        """Run on device 0: f32 dense inputs → f32 dense outputs."""
        ins = [np.ascontiguousarray(np.asarray(a, np.float32))
               for a in inputs]
        n_in = len(ins)
        in_data = (ctypes.POINTER(ctypes.c_float) * n_in)(
            *[a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)) for a in ins])
        dims_arrays = [(ctypes.c_int64 * a.ndim)(*a.shape) for a in ins]
        in_dims = (ctypes.POINTER(ctypes.c_int64) * n_in)(*dims_arrays)
        in_ranks = (ctypes.c_int32 * n_in)(*[a.ndim for a in ins])

        outs = [np.empty(s, np.float32) for s in out_shapes]
        n_out = len(outs)
        out_data = (ctypes.POINTER(ctypes.c_float) * n_out)(
            *[o.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
              for o in outs])
        out_elems = (ctypes.c_int64 * n_out)(*[o.size for o in outs])
        err = ctypes.create_string_buffer(_ERRLEN)
        rc = self._libshim.nd4j_pjrt_execute_f32(
            self._exe, in_data, in_dims, in_ranks, n_in,
            out_data, out_elems, n_out, err, _ERRLEN)
        if rc != 0:
            raise RuntimeError(f"PJRT execute failed: "
                               f"{err.value.decode(errors='replace')}")
        return outs

    def close(self):
        if self._exe:
            self._libshim.nd4j_pjrt_executable_destroy(self._exe)
            self._exe = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
