// Host-side native ops for the TPU framework.
//
// Role (SURVEY N5/N9/E1): the reference keeps its runtime-adjacent hot loops
// in C++ (libnd4j's NativeOps C ABI). On TPU the device math belongs to
// XLA/Pallas, but host-side work — the threshold gradient codec used on the
// DCN cross-slice path, and ETL parsing feeding the input pipeline — still
// benefits from native code. This library exposes a flat C ABI consumed via
// ctypes (the JavaCPP-preset analog).
//
// Build: `make` in deeplearning4j_tpu/native (g++ -O3 -fPIC -shared).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <cmath>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Threshold codec (Strom 2015) — format matches kernels/threshold.py:
// out[0] = count, out[1..] = ±(flat_index+1). Returns number encoded.
// Residual is updated in place (encoded mass subtracted).
// ---------------------------------------------------------------------------
int64_t threshold_encode(float* residual, int64_t n, float threshold,
                         int32_t* out, int64_t capacity) {
    int64_t count = 0;
    for (int64_t i = 0; i < n && count < capacity; ++i) {
        float v = residual[i];
        if (v >= threshold) {
            out[1 + count++] = (int32_t)(i + 1);
            residual[i] = v - threshold;
        } else if (v <= -threshold) {
            out[1 + count++] = -(int32_t)(i + 1);
            residual[i] = v + threshold;
        }
    }
    out[0] = (int32_t)count;
    for (int64_t i = 1 + count; i < capacity + 1; ++i) out[i] = 0;
    return count;
}

// Accumulate a decoded buffer into `target` (+= ±threshold per entry).
int64_t threshold_decode(const int32_t* encoded, float threshold,
                         float* target, int64_t n) {
    int32_t count = encoded[0];
    for (int32_t c = 0; c < count; ++c) {
        int32_t e = encoded[1 + c];
        if (e == 0) continue;
        int64_t idx = (e > 0 ? e : -e) - 1;
        if (idx >= n) continue;
        target[idx] += (e > 0 ? threshold : -threshold);
    }
    return count;
}

// ---------------------------------------------------------------------------
// CSV fast path: parse a whole file of delimiter-separated floats.
// Two-phase API: csv_count sizes the output, csv_parse fills it.
// Non-numeric fields parse as NaN (callers handle categorical columns in
// Python — the numeric bulk is the hot part).
// ---------------------------------------------------------------------------
static char* read_file(const char* path, int64_t* out_len) {
    FILE* f = fopen(path, "rb");
    if (!f) return nullptr;
    fseek(f, 0, SEEK_END);
    long len = ftell(f);
    fseek(f, 0, SEEK_SET);
    char* buf = (char*)malloc(len + 1);
    if (!buf) { fclose(f); return nullptr; }
    size_t rd = fread(buf, 1, len, f);
    fclose(f);
    buf[rd] = '\0';
    *out_len = (int64_t)rd;
    return buf;
}

// Returns rows; writes max columns to *cols. -1 on I/O error.
int64_t csv_count(const char* path, char delim, int64_t skip_rows,
                  int64_t* cols) {
    int64_t len;
    char* buf = read_file(path, &len);
    if (!buf) return -1;
    int64_t rows = 0, cur_cols = 1, max_cols = 0, row_i = 0;
    bool line_empty = true;
    for (int64_t i = 0; i < len; ++i) {
        char c = buf[i];
        if (c == '\n') {
            if (!line_empty && row_i >= skip_rows) {
                ++rows;
                if (cur_cols > max_cols) max_cols = cur_cols;
            }
            if (!line_empty) ++row_i;
            cur_cols = 1;
            line_empty = true;
        } else if (c == delim) {
            ++cur_cols;
            line_empty = false;   // a delimiter-only line is a row of NaNs
        } else if (c != '\r' && c != ' ' && c != '\t') {
            line_empty = false;
        }
    }
    if (!line_empty && row_i >= skip_rows) {
        ++rows;
        if (cur_cols > max_cols) max_cols = cur_cols;
    }
    free(buf);
    *cols = max_cols;
    return rows;
}

// Fills out[rows*cols] row-major. Returns rows parsed, -1 on error.
int64_t csv_parse(const char* path, char delim, int64_t skip_rows,
                  float* out, int64_t rows, int64_t cols) {
    int64_t len;
    char* buf = read_file(path, &len);
    if (!buf) return -1;
    int64_t row = 0, row_i = 0;
    char* p = buf;
    char* end = buf + len;
    while (p < end && row < rows) {
        // find line end
        char* nl = (char*)memchr(p, '\n', end - p);
        char* line_end = nl ? nl : end;
        // blank line? (delimiters count as content — matches csv_count)
        bool blank = true;
        for (char* q = p; q < line_end; ++q)
            if (*q != '\r' && *q != ' ' && *q != '\t') { blank = false; break; }
        if (!blank) {
            if (row_i >= skip_rows) {
                // terminate the line so strtof cannot read past it into the
                // next row (e.g. a trailing empty field before '\n')
                char saved = *line_end;
                *line_end = '\0';
                int64_t col = 0;
                char* q = p;
                while (q <= line_end && col < cols) {
                    char* endptr;
                    float v = strtof(q, &endptr);
                    if (endptr == q) v = NAN;   // non-numeric/empty field
                    out[row * cols + col] = v;
                    ++col;
                    // advance to next delimiter
                    char* dq = q;
                    while (dq < line_end && *dq != delim) ++dq;
                    if (dq >= line_end) break;
                    q = dq + 1;
                }
                for (; col < cols; ++col) out[row * cols + col] = NAN;
                *line_end = saved;
                ++row;
            }
            ++row_i;
        }
        if (!nl) break;
        p = nl + 1;
    }
    free(buf);
    return row;
}

// ---------------------------------------------------------------------------
// Fisher-Yates shuffle of row indices (the shuffle-buffer hot loop).
// ---------------------------------------------------------------------------
void shuffle_indices(int64_t* idx, int64_t n, uint64_t seed) {
    uint64_t s = seed ? seed : 0x9E3779B97F4A7C15ULL;
    for (int64_t i = n - 1; i > 0; --i) {
        // splitmix64
        s += 0x9E3779B97F4A7C15ULL;
        uint64_t z = s;
        z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ULL;
        z = (z ^ (z >> 27)) * 0x94D049BB133111EBULL;
        z = z ^ (z >> 31);
        int64_t j = (int64_t)(z % (uint64_t)(i + 1));
        int64_t t = idx[i];
        idx[i] = idx[j];
        idx[j] = t;
    }
}

}  // extern "C"
