// PJRT C-API shim — the nd4j-tpu backend's native runtime layer.
//
// Reference: libnd4j's flat NativeOps C ABI (legacy/NativeOps.h) is the JNI
// surface the Java backends wrap (SURVEY N5); its TPU-native equivalent is
// this shim over the PJRT C API (pjrt_c_api.h): load a PJRT plugin
// (libtpu.so, or any other conforming plugin), create a client, compile an
// MLIR (StableHLO) program, move host buffers, execute, read back. The
// Python binding (native/pjrt.py) plays the JavaCPP-preset role (SURVEY
// N10) over this ABI via ctypes.
//
// Error contract: every entry point that can fail takes (char* err, int
// errlen); on failure it copies a NUL-terminated message and returns
// NULL/-1. No exceptions cross the ABI.

#include <dlfcn.h>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "xla/pjrt/c/pjrt_c_api.h"

namespace {

void set_err(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    std::snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

// Returns true (and fills err) if e is an error; frees e.
bool consume_error(const PJRT_Api* api, PJRT_Error* e, char* err, int errlen,
                   const char* where) {
  if (e == nullptr) return false;
  PJRT_Error_Message_Args margs;
  std::memset(&margs, 0, sizeof(margs));
  margs.struct_size = PJRT_Error_Message_Args_STRUCT_SIZE;
  margs.error = e;
  api->PJRT_Error_Message(&margs);
  set_err(err, errlen, std::string(where) + ": " +
                           std::string(margs.message, margs.message_size));
  PJRT_Error_Destroy_Args dargs;
  std::memset(&dargs, 0, sizeof(dargs));
  dargs.struct_size = PJRT_Error_Destroy_Args_STRUCT_SIZE;
  dargs.error = e;
  api->PJRT_Error_Destroy(&dargs);
  return true;
}

struct ShimClient {
  const PJRT_Api* api;
  PJRT_Client* client;
};

struct ShimExecutable {
  const PJRT_Api* api;
  PJRT_Client* client;
  PJRT_LoadedExecutable* exec;
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- plugin
// dlopen a PJRT plugin and return its PJRT_Api* (NULL + err on failure).
const void* nd4j_pjrt_load_plugin(const char* path, char* err, int errlen) {
  void* handle = dlopen(path, RTLD_NOW | RTLD_LOCAL);
  if (!handle) {
    set_err(err, errlen, std::string("dlopen failed: ") + dlerror());
    return nullptr;
  }
  using GetPjrtApiFn = const PJRT_Api* (*)();
  auto get_api = reinterpret_cast<GetPjrtApiFn>(dlsym(handle, "GetPjrtApi"));
  if (!get_api) {
    set_err(err, errlen,
            std::string("GetPjrtApi symbol not found: ") + dlerror());
    return nullptr;
  }
  const PJRT_Api* api = get_api();
  if (!api) {
    set_err(err, errlen, "GetPjrtApi returned NULL");
    return nullptr;
  }
  return api;
}

int nd4j_pjrt_api_version(const void* api_ptr, int* major, int* minor) {
  auto api = static_cast<const PJRT_Api*>(api_ptr);
  if (!api) return -1;
  *major = api->pjrt_api_version.major_version;
  *minor = api->pjrt_api_version.minor_version;
  return 0;
}

// ---------------------------------------------------------------- client
void* nd4j_pjrt_client_create(const void* api_ptr, char* err, int errlen) {
  auto api = static_cast<const PJRT_Api*>(api_ptr);
  PJRT_Client_Create_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_Create_Args_STRUCT_SIZE;
  if (consume_error(api, api->PJRT_Client_Create(&args), err, errlen,
                    "PJRT_Client_Create")) {
    return nullptr;
  }
  return new ShimClient{api, args.client};
}

void nd4j_pjrt_client_destroy(void* client_ptr) {
  auto sc = static_cast<ShimClient*>(client_ptr);
  if (!sc) return;
  PJRT_Client_Destroy_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_Destroy_Args_STRUCT_SIZE;
  args.client = sc->client;
  sc->api->PJRT_Client_Destroy(&args);
  delete sc;
}

int nd4j_pjrt_platform_name(void* client_ptr, char* buf, int buflen) {
  auto sc = static_cast<ShimClient*>(client_ptr);
  PJRT_Client_PlatformName_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_PlatformName_Args_STRUCT_SIZE;
  args.client = sc->client;
  if (sc->api->PJRT_Client_PlatformName(&args) != nullptr) return -1;
  size_t n = args.platform_name_size;
  if (n + 1 > static_cast<size_t>(buflen)) n = buflen - 1;
  std::memcpy(buf, args.platform_name, n);
  buf[n] = '\0';
  return static_cast<int>(n);
}

int nd4j_pjrt_device_count(void* client_ptr) {
  auto sc = static_cast<ShimClient*>(client_ptr);
  PJRT_Client_AddressableDevices_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  args.client = sc->client;
  if (sc->api->PJRT_Client_AddressableDevices(&args) != nullptr) return -1;
  return static_cast<int>(args.num_addressable_devices);
}

// --------------------------------------------------------------- compile
// mlir: StableHLO module text or bytecode. compile_options: serialized
// CompileOptionsProto bytes (produced by the Python binding).
void* nd4j_pjrt_compile(void* client_ptr, const char* mlir, int64_t mlir_size,
                        const char* compile_options, int64_t options_size,
                        char* err, int errlen) {
  auto sc = static_cast<ShimClient*>(client_ptr);
  PJRT_Program program;
  std::memset(&program, 0, sizeof(program));
  program.struct_size = PJRT_Program_STRUCT_SIZE;
  program.code = const_cast<char*>(mlir);
  program.code_size = static_cast<size_t>(mlir_size);
  static const char kFormat[] = "mlir";
  program.format = kFormat;
  program.format_size = sizeof(kFormat) - 1;

  PJRT_Client_Compile_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_Client_Compile_Args_STRUCT_SIZE;
  args.client = sc->client;
  args.program = &program;
  args.compile_options = compile_options;
  args.compile_options_size = static_cast<size_t>(options_size);
  if (consume_error(sc->api, sc->api->PJRT_Client_Compile(&args), err, errlen,
                    "PJRT_Client_Compile")) {
    return nullptr;
  }
  return new ShimExecutable{sc->api, sc->client, args.executable};
}

void nd4j_pjrt_executable_destroy(void* exec_ptr) {
  auto se = static_cast<ShimExecutable*>(exec_ptr);
  if (!se) return;
  PJRT_LoadedExecutable_Destroy_Args args;
  std::memset(&args, 0, sizeof(args));
  args.struct_size = PJRT_LoadedExecutable_Destroy_Args_STRUCT_SIZE;
  args.executable = se->exec;
  se->api->PJRT_LoadedExecutable_Destroy(&args);
  delete se;
}

// --------------------------------------------------------------- execute
// Single-device execute: n_in f32 dense inputs (data/shape/rank), n_out f32
// outputs copied into caller-provided dense buffers (sized by the caller).
int nd4j_pjrt_execute_f32(void* exec_ptr, const float** in_data,
                          const int64_t* const* in_dims,
                          const int32_t* in_ranks, int32_t n_in,
                          float** out_data, const int64_t* out_elems,
                          int32_t n_out, char* err, int errlen) {
  auto se = static_cast<ShimExecutable*>(exec_ptr);
  const PJRT_Api* api = se->api;

  PJRT_Client_AddressableDevices_Args dev_args;
  std::memset(&dev_args, 0, sizeof(dev_args));
  dev_args.struct_size = PJRT_Client_AddressableDevices_Args_STRUCT_SIZE;
  dev_args.client = se->client;
  if (consume_error(api, api->PJRT_Client_AddressableDevices(&dev_args), err,
                    errlen, "AddressableDevices")) {
    return -1;
  }
  if (dev_args.num_addressable_devices == 0) {
    set_err(err, errlen, "no addressable devices");
    return -1;
  }
  PJRT_Device* device = dev_args.addressable_devices[0];

  // host → device
  std::vector<PJRT_Buffer*> inputs(n_in, nullptr);
  for (int i = 0; i < n_in; ++i) {
    PJRT_Client_BufferFromHostBuffer_Args bargs;
    std::memset(&bargs, 0, sizeof(bargs));
    bargs.struct_size = PJRT_Client_BufferFromHostBuffer_Args_STRUCT_SIZE;
    bargs.client = se->client;
    bargs.data = in_data[i];
    bargs.type = PJRT_Buffer_Type_F32;
    bargs.dims = in_dims[i];
    bargs.num_dims = static_cast<size_t>(in_ranks[i]);
    bargs.host_buffer_semantics =
        PJRT_HostBufferSemantics_kImmutableOnlyDuringCall;
    bargs.device = device;
    if (consume_error(api, api->PJRT_Client_BufferFromHostBuffer(&bargs), err,
                      errlen, "BufferFromHostBuffer")) {
      return -1;
    }
    if (bargs.done_with_host_buffer) {
      PJRT_Event_Await_Args eargs;
      std::memset(&eargs, 0, sizeof(eargs));
      eargs.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
      eargs.event = bargs.done_with_host_buffer;
      api->PJRT_Event_Await(&eargs);
      PJRT_Event_Destroy_Args edargs;
      std::memset(&edargs, 0, sizeof(edargs));
      edargs.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
      edargs.event = bargs.done_with_host_buffer;
      api->PJRT_Event_Destroy(&edargs);
    }
    inputs[i] = bargs.buffer;
  }

  // execute (one device)
  PJRT_ExecuteOptions opts;
  std::memset(&opts, 0, sizeof(opts));
  opts.struct_size = PJRT_ExecuteOptions_STRUCT_SIZE;

  std::vector<PJRT_Buffer*> outs(n_out, nullptr);
  PJRT_Buffer* const* arg_list = inputs.data();
  PJRT_Buffer** out_list = outs.data();
  PJRT_Event* done_event = nullptr;

  PJRT_LoadedExecutable_Execute_Args xargs;
  std::memset(&xargs, 0, sizeof(xargs));
  xargs.struct_size = PJRT_LoadedExecutable_Execute_Args_STRUCT_SIZE;
  xargs.executable = se->exec;
  xargs.options = &opts;
  xargs.argument_lists = &arg_list;
  xargs.num_devices = 1;
  xargs.num_args = static_cast<size_t>(n_in);
  xargs.output_lists = &out_list;
  xargs.device_complete_events = &done_event;
  xargs.execute_device = device;
  int rc = 0;
  if (consume_error(api, api->PJRT_LoadedExecutable_Execute(&xargs), err,
                    errlen, "Execute")) {
    rc = -1;
  }
  if (rc == 0 && done_event) {
    PJRT_Event_Await_Args eargs;
    std::memset(&eargs, 0, sizeof(eargs));
    eargs.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
    eargs.event = done_event;
    if (consume_error(api, api->PJRT_Event_Await(&eargs), err, errlen,
                      "Execute await")) {
      rc = -1;
    }
    PJRT_Event_Destroy_Args edargs;
    std::memset(&edargs, 0, sizeof(edargs));
    edargs.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
    edargs.event = done_event;
    api->PJRT_Event_Destroy(&edargs);
  }

  // device → host
  for (int o = 0; rc == 0 && o < n_out; ++o) {
    PJRT_Buffer_ToHostBuffer_Args targs;
    std::memset(&targs, 0, sizeof(targs));
    targs.struct_size = PJRT_Buffer_ToHostBuffer_Args_STRUCT_SIZE;
    targs.src = outs[o];
    targs.dst = out_data[o];
    targs.dst_size = static_cast<size_t>(out_elems[o]) * sizeof(float);
    if (consume_error(api, api->PJRT_Buffer_ToHostBuffer(&targs), err, errlen,
                      "ToHostBuffer")) {
      rc = -1;
      break;
    }
    if (targs.event) {
      PJRT_Event_Await_Args eargs;
      std::memset(&eargs, 0, sizeof(eargs));
      eargs.struct_size = PJRT_Event_Await_Args_STRUCT_SIZE;
      eargs.event = targs.event;
      if (consume_error(api, api->PJRT_Event_Await(&eargs), err, errlen,
                        "ToHostBuffer await")) {
        rc = -1;
      }
      PJRT_Event_Destroy_Args edargs;
      std::memset(&edargs, 0, sizeof(edargs));
      edargs.struct_size = PJRT_Event_Destroy_Args_STRUCT_SIZE;
      edargs.event = targs.event;
      api->PJRT_Event_Destroy(&edargs);
    }
  }

  // free buffers
  for (PJRT_Buffer* b : inputs) {
    if (!b) continue;
    PJRT_Buffer_Destroy_Args dargs;
    std::memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
    dargs.buffer = b;
    api->PJRT_Buffer_Destroy(&dargs);
  }
  for (PJRT_Buffer* b : outs) {
    if (!b) continue;
    PJRT_Buffer_Destroy_Args dargs;
    std::memset(&dargs, 0, sizeof(dargs));
    dargs.struct_size = PJRT_Buffer_Destroy_Args_STRUCT_SIZE;
    dargs.buffer = b;
    api->PJRT_Buffer_Destroy(&dargs);
  }
  return rc;
}

}  // extern "C"
