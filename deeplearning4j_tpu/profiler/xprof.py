"""Device-level profiling bridge (SURVEY §5.1: "TPU equivalent: jax
profiler → XProf/TensorBoard").

The eager-path ``OpProfiler`` times per-op host dispatch; compiled programs
need the device timeline instead. This wraps ``jax.profiler`` behind the
same start/stop surface the reference exposes through
``Nd4j.getExecutioner().setProfilingConfig`` — traces land in a directory
TensorBoard/XProf can open.
"""
from __future__ import annotations

import contextlib
import os
import time
from typing import Optional


class DeviceProfiler:
    """ref-analog surface: start/stop + annotate (OpProfiler's scoped
    sections, but for the XLA device timeline)."""

    def __init__(self, log_dir: str = "/tmp/dl4j_tpu_profile"):
        self.log_dir = log_dir
        self._active = False

    def start(self):
        import jax

        if self._active:
            return self
        os.makedirs(self.log_dir, exist_ok=True)
        jax.profiler.start_trace(self.log_dir)
        self._active = True
        return self

    def stop(self) -> str:
        import jax

        if self._active:
            jax.profiler.stop_trace()
            self._active = False
        return self.log_dir

    @contextlib.contextmanager
    def trace(self, name: Optional[str] = None):
        """Scoped trace: ``with prof.trace("step"): step(...)``."""
        import jax

        started = not self._active
        if started:
            self.start()
        try:
            with jax.profiler.TraceAnnotation(name or "section"):
                yield self
        finally:
            if started:
                self.stop()

    @staticmethod
    def annotate(name: str):
        """Standalone annotation context (host-side label on the timeline)."""
        import jax

        return jax.profiler.TraceAnnotation(name)


def profile_step(fn, *args, log_dir: str = "/tmp/dl4j_tpu_profile",
                 iters: int = 3):
    """One-shot helper: trace ``iters`` calls of a jitted step; returns
    (last_output, trace_dir, wall_seconds_per_iter)."""
    import jax

    prof = DeviceProfiler(log_dir)
    out = fn(*args)                      # compile outside the trace
    jax.block_until_ready(out)
    prof.start()
    try:
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        wall = (time.perf_counter() - t0) / iters
    finally:
        trace_dir = prof.stop()          # never leave the profiler running
    return out, trace_dir, wall
