"""Eager op profiler + NaN/Inf panic modes.

Reference: ``org.nd4j.linalg.profiler.{OpProfiler,ProfilerConfig}`` with
NAN_PANIC / INF_PANIC modes, and libnd4j's ``Environment::setDebug/Verbose``
(SURVEY J12, 5.1). On TPU, per-op wall time only exists on the *eager* path
(inside jit there are no per-op boundaries — use ``jax.profiler`` traces for
compiled code, and ``jax.config.jax_debug_nans`` for in-jit NaN panics; both
are toggled by :func:`ProfilerConfig.apply`). This profiler instruments the
registry's eager ``exec_op`` dispatch, which is exactly the layer the
reference instrumented.
"""
from __future__ import annotations

import collections
import dataclasses
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops import registry as _registry


@dataclasses.dataclass
class ProfilerConfig:
    """ref: ProfilerConfig builder flags."""
    op_timing: bool = False          # aggregate wall time per op name
    check_for_nan: bool = False      # NAN_PANIC: raise on non-finite output
    check_for_inf: bool = False     # INF_PANIC
    verbose: bool = False            # print each eager op (Environment::setVerbose)

    def apply(self):
        """Also flip the jit-level knobs where they exist (both ways —
        leaving jax_debug_nans on would tax every later jit globally)."""
        jax.config.update("jax_debug_nans", bool(self.check_for_nan))
        return self


@dataclasses.dataclass
class OpStats:
    invocations: int = 0
    total_seconds: float = 0.0

    @property
    def average_ms(self) -> float:
        return (self.total_seconds / self.invocations * 1e3
                if self.invocations else 0.0)


class OpProfiler:
    """Singleton-style profiler over the eager exec_op path
    (ref: OpProfiler#getInstance)."""

    _instance: Optional["OpProfiler"] = None

    def __init__(self):
        self.config = ProfilerConfig()
        self.stats: Dict[str, OpStats] = collections.defaultdict(OpStats)
        self._installed = False
        self._orig_exec = None

    @classmethod
    def get_instance(cls) -> "OpProfiler":
        if cls._instance is None:
            cls._instance = OpProfiler()
        return cls._instance

    getInstance = get_instance

    # ----------------------------------------------------------- lifecycle
    def set_config(self, config: ProfilerConfig):
        self.config = config
        config.apply()
        if (config.op_timing or config.check_for_nan or config.check_for_inf
                or config.verbose):
            self._install()
        else:
            self._uninstall()
        return self

    setConfig = set_config

    def _install(self):
        if self._installed:
            return
        self._orig_exec = _registry.exec_op
        profiler = self

        def profiled_exec(name, *args, **attrs):
            t0 = time.perf_counter() if profiler.config.op_timing else None
            out = profiler._orig_exec(name, *args, **attrs)
            if t0 is not None:
                # eager timing: block on the result like the reference's
                # per-op sync (inside jit this wrapper never runs)
                jax.block_until_ready(out)
                st = profiler.stats[name]
                st.invocations += 1
                st.total_seconds += time.perf_counter() - t0
            if profiler.config.verbose:
                print(f"[op] {name}")
            if profiler.config.check_for_nan or profiler.config.check_for_inf:
                profiler._panic_check(name, out)
            return out

        _registry.exec_op = profiled_exec
        # layers.py did `from registry import exec_op` and holds its own
        # reference — patch that binding too (the only other consumer)
        import deeplearning4j_tpu.nn.conf.layers as layers_mod
        layers_mod.exec_op = profiled_exec
        self._installed = True

    def _uninstall(self):
        if not self._installed:
            return
        _registry.exec_op = self._orig_exec
        import deeplearning4j_tpu.nn.conf.layers as layers_mod
        layers_mod.exec_op = self._orig_exec
        self._installed = False

    def _panic_check(self, name, out):
        # only meaningful on concrete (eager) arrays; traced values skip
        leaves = out if isinstance(out, (tuple, list)) else [out]
        for leaf in leaves:
            if leaf is None or isinstance(leaf, jax.core.Tracer):
                continue
            arr = jnp.asarray(leaf)
            if not jnp.issubdtype(arr.dtype, jnp.floating):
                continue
            if self.config.check_for_nan and bool(jnp.any(jnp.isnan(arr))):
                raise FloatingPointError(
                    f"NAN_PANIC: op {name!r} produced NaN")
            if self.config.check_for_inf and bool(jnp.any(jnp.isinf(arr))):
                raise FloatingPointError(
                    f"INF_PANIC: op {name!r} produced Inf")

    # ------------------------------------------------------------- reports
    def reset(self):
        self.stats.clear()

    def print_results(self) -> str:
        lines = [f"{'op':<28}{'calls':>8}{'total ms':>12}{'avg ms':>10}"]
        for name, st in sorted(self.stats.items(),
                               key=lambda kv: -kv[1].total_seconds):
            lines.append(f"{name:<28}{st.invocations:>8}"
                         f"{st.total_seconds * 1e3:>12.2f}"
                         f"{st.average_ms:>10.3f}")
        out = "\n".join(lines)
        print(out)
        return out

    printResults = print_results
