"""Eager op profiler + NaN/Inf panic modes.

Reference: ``org.nd4j.linalg.profiler.{OpProfiler,ProfilerConfig}`` with
NAN_PANIC / INF_PANIC modes, and libnd4j's ``Environment::setDebug/Verbose``
(SURVEY J12, 5.1). On TPU, per-op wall time only exists on the *eager* path
(inside jit there are no per-op boundaries — use ``jax.profiler`` traces for
compiled code, and ``jax.config.jax_debug_nans`` for in-jit NaN panics; both
are toggled by :func:`ProfilerConfig.apply`). This profiler instruments the
registry's eager ``exec_op`` dispatch, which is exactly the layer the
reference instrumented.

Observability refactor: timings publish into the process-wide metrics
registry (``dl4j_eager_op_seconds{op=...}`` histogram, scrapeable at
``/metrics``); :class:`OpStats` is now a *view* over that series —
``reset()`` re-bases the views, the registry stays cumulative.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.observability import global_registry, on_registry_reset
from deeplearning4j_tpu.observability.registry import Histogram
from deeplearning4j_tpu.ops import registry as _registry


@dataclasses.dataclass
class ProfilerConfig:
    """ref: ProfilerConfig builder flags."""
    op_timing: bool = False          # aggregate wall time per op name
    check_for_nan: bool = False      # NAN_PANIC: raise on non-finite output
    check_for_inf: bool = False     # INF_PANIC
    verbose: bool = False            # print each eager op (Environment::setVerbose)

    def apply(self):
        """Also flip the jit-level knobs where they exist (both ways —
        leaving jax_debug_nans on would tax every later jit globally)."""
        jax.config.update("jax_debug_nans", bool(self.check_for_nan))
        return self


class OpStats:
    """Windowed view over one op's registry series (re-based by reset)."""

    __slots__ = ("_hist", "_n0", "_s0")

    def __init__(self, hist_child):
        self._hist = hist_child
        self._n0 = 0
        self._s0 = 0.0

    def _rebase(self):
        self._n0 = self._hist.count
        self._s0 = self._hist.sum

    @property
    def invocations(self) -> int:
        return self._hist.count - self._n0

    @property
    def total_seconds(self) -> float:
        return self._hist.sum - self._s0

    @property
    def average_ms(self) -> float:
        return (self.total_seconds / self.invocations * 1e3
                if self.invocations else 0.0)


class _StatsView(dict):
    """``profiler.stats[name]`` — lazily binds a view to the op's series."""

    def __init__(self, profiler: "OpProfiler"):
        super().__init__()
        self._profiler = profiler

    def __missing__(self, name: str) -> OpStats:
        st = OpStats(self._profiler._hist.labels(op=name))
        self[name] = st
        return st


class OpProfiler:
    """Singleton-style profiler over the eager exec_op path
    (ref: OpProfiler#getInstance)."""

    _instance: Optional["OpProfiler"] = None

    def __init__(self):
        self.config = ProfilerConfig()
        self._bind()
        self._installed = False
        self._orig_exec = None

    def _bind(self):
        self._hist = global_registry().histogram(
            "dl4j_eager_op_seconds",
            "per-op wall time on the eager exec_op dispatch path "
            "(OpProfiler op_timing mode)", label_names=("op",))
        if not self._hist._enabled:
            # DL4J_TPU_METRICS=0 silences the EXPORT, not this explicitly
            # opted-into tool: fall back to a private (unscraped) series so
            # stats/print_results keep working under the kill switch
            self._hist = Histogram("dl4j_eager_op_seconds",
                                   label_names=("op",), _enabled=True)
        self.stats: Dict[str, OpStats] = _StatsView(self)

    @classmethod
    def get_instance(cls) -> "OpProfiler":
        if cls._instance is None:
            cls._instance = OpProfiler()
        return cls._instance

    getInstance = get_instance

    # ----------------------------------------------------------- lifecycle
    def set_config(self, config: ProfilerConfig):
        self.config = config
        config.apply()
        if (config.op_timing or config.check_for_nan or config.check_for_inf
                or config.verbose):
            self._install()
        else:
            self._uninstall()
        return self

    setConfig = set_config

    def _install(self):
        if self._installed:
            return
        self._orig_exec = _registry.exec_op
        profiler = self

        def profiled_exec(name, *args, **attrs):
            t0 = time.perf_counter() if profiler.config.op_timing else None
            out = profiler._orig_exec(name, *args, **attrs)
            if t0 is not None:
                # eager timing: block on the result like the reference's
                # per-op sync (inside jit this wrapper never runs)
                jax.block_until_ready(out)
                profiler.stats[name]._hist.observe(
                    time.perf_counter() - t0)
            if profiler.config.verbose:
                print(f"[op] {name}")
            if profiler.config.check_for_nan or profiler.config.check_for_inf:
                profiler._panic_check(name, out)
            return out

        _registry.exec_op = profiled_exec
        # layers.py did `from registry import exec_op` and holds its own
        # reference — patch that binding too (the only other consumer)
        import deeplearning4j_tpu.nn.conf.layers as layers_mod
        layers_mod.exec_op = profiled_exec
        self._installed = True

    def _uninstall(self):
        if not self._installed:
            return
        _registry.exec_op = self._orig_exec
        import deeplearning4j_tpu.nn.conf.layers as layers_mod
        layers_mod.exec_op = self._orig_exec
        self._installed = False

    def _panic_check(self, name, out):
        # only meaningful on concrete (eager) arrays; traced values skip
        leaves = out if isinstance(out, (tuple, list)) else [out]
        for leaf in leaves:
            if leaf is None or isinstance(leaf, jax.core.Tracer):
                continue
            arr = jnp.asarray(leaf)
            if not jnp.issubdtype(arr.dtype, jnp.floating):
                continue
            if self.config.check_for_nan and bool(jnp.any(jnp.isnan(arr))):
                raise FloatingPointError(
                    f"NAN_PANIC: op {name!r} produced NaN")
            if self.config.check_for_inf and bool(jnp.any(jnp.isinf(arr))):
                raise FloatingPointError(
                    f"INF_PANIC: op {name!r} produced Inf")

    # ------------------------------------------------------------- reports
    def reset(self):
        """Zero the report window (registry series stay cumulative)."""
        for st in self.stats.values():
            st._rebase()

    def print_results(self) -> str:
        lines = [f"{'op':<28}{'calls':>8}{'total ms':>12}{'avg ms':>10}"]
        for name, st in sorted(self.stats.items(),
                               key=lambda kv: -kv[1].total_seconds):
            if not st.invocations:
                continue
            lines.append(f"{name:<28}{st.invocations:>8}"
                         f"{st.total_seconds * 1e3:>12.2f}"
                         f"{st.average_ms:>10.3f}")
        out = "\n".join(lines)
        print(out)
        return out

    printResults = print_results


@on_registry_reset
def _rebind_profiler():
    if OpProfiler._instance is not None:
        OpProfiler._instance._bind()
