"""Op profiling + numerical-panic debugging (ref: SURVEY J12/5.1-5.2)."""
from deeplearning4j_tpu.profiler.op_profiler import (OpProfiler,
                                                     ProfilerConfig)
from deeplearning4j_tpu.profiler.performance import PerformanceTracker
from deeplearning4j_tpu.profiler.xprof import DeviceProfiler, profile_step

__all__ = ["OpProfiler", "ProfilerConfig", "PerformanceTracker",
           "DeviceProfiler", "profile_step"]
