"""Throughput/bandwidth tracking
(ref: org.nd4j.linalg.api.ops.performance.PerformanceTracker +
listeners.PerformanceListener internals, SURVEY J12).

Observability refactor: every recording is published into the process-wide
metrics registry (``dl4j_perf_*`` / ``dl4j_transfer_bytes_total`` series,
scrapeable at ``/metrics``). The legacy accessors remain INSTANCE-local
windows (two trackers don't alias each other's numbers, and an explicitly
constructed tracker keeps working under ``DL4J_TPU_METRICS=0`` — the kill
switch silences the export, not the tool)."""
from __future__ import annotations

import time
from typing import Optional

from deeplearning4j_tpu.observability import global_registry, on_registry_reset


class PerformanceTracker:
    """Examples/sec + host↔device byte accounting. The reference tracks
    memcpy bandwidth per device; here transfers are whatever crosses the
    PJRT boundary — callers report them via ``add_transfer_bytes``."""

    _instance: Optional["PerformanceTracker"] = None

    def __init__(self):
        self._bind()
        self.reset()

    def _bind(self):
        reg = global_registry()
        self._examples_c = reg.counter(
            "dl4j_perf_examples_total",
            "examples reported to PerformanceTracker")
        self._iterations_c = reg.counter(
            "dl4j_perf_iterations_total",
            "iterations reported to PerformanceTracker")
        tb = reg.counter("dl4j_transfer_bytes_total",
                         "host<->device transfer bytes",
                         label_names=("direction",))
        self._h2d_c = tb.labels(direction="h2d")
        self._d2h_c = tb.labels(direction="d2h")

    @classmethod
    def get_instance(cls) -> "PerformanceTracker":
        if cls._instance is None:
            cls._instance = PerformanceTracker()
        return cls._instance

    getInstance = get_instance

    def reset(self):
        self._start = time.time()
        self.examples = 0
        self.iterations = 0
        self.h2d_bytes = 0
        self.d2h_bytes = 0

    def record_iteration(self, batch_size: int):
        self.examples += batch_size
        self.iterations += 1
        self._examples_c.inc(batch_size)
        self._iterations_c.inc()

    def add_transfer_bytes(self, host_to_device: int = 0,
                           device_to_host: int = 0):
        self.h2d_bytes += host_to_device
        self.d2h_bytes += device_to_host
        if host_to_device:
            self._h2d_c.inc(host_to_device)
        if device_to_host:
            self._d2h_c.inc(device_to_host)

    addMemoryTransaction = add_transfer_bytes

    @property
    def elapsed(self) -> float:
        return max(time.time() - self._start, 1e-9)

    def examples_per_second(self) -> float:
        return self.examples / self.elapsed

    def iterations_per_second(self) -> float:
        return self.iterations / self.elapsed

    def bandwidth_mb_s(self) -> float:
        return (self.h2d_bytes + self.d2h_bytes) / self.elapsed / 1e6

    def summary(self) -> str:
        return (f"{self.examples} examples in {self.elapsed:.1f}s "
                f"({self.examples_per_second():.1f} ex/s, "
                f"{self.iterations_per_second():.2f} it/s, "
                f"{self.bandwidth_mb_s():.1f} MB/s transfers)")


@on_registry_reset
def _rebind_tracker():
    if PerformanceTracker._instance is not None:
        PerformanceTracker._instance._bind()
