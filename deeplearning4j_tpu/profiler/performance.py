"""Throughput/bandwidth tracking
(ref: org.nd4j.linalg.api.ops.performance.PerformanceTracker +
listeners.PerformanceListener internals, SURVEY J12)."""
from __future__ import annotations

import time
from typing import Optional


class PerformanceTracker:
    """Examples/sec + host↔device byte accounting. The reference tracks
    memcpy bandwidth per device; here transfers are whatever crosses the
    PJRT boundary — callers report them via ``add_transfer_bytes``."""

    _instance: Optional["PerformanceTracker"] = None

    def __init__(self):
        self.reset()

    @classmethod
    def get_instance(cls) -> "PerformanceTracker":
        if cls._instance is None:
            cls._instance = PerformanceTracker()
        return cls._instance

    getInstance = get_instance

    def reset(self):
        self._start = time.time()
        self.examples = 0
        self.iterations = 0
        self.h2d_bytes = 0
        self.d2h_bytes = 0

    def record_iteration(self, batch_size: int):
        self.examples += batch_size
        self.iterations += 1

    def add_transfer_bytes(self, host_to_device: int = 0,
                           device_to_host: int = 0):
        self.h2d_bytes += host_to_device
        self.d2h_bytes += device_to_host

    addMemoryTransaction = add_transfer_bytes

    @property
    def elapsed(self) -> float:
        return max(time.time() - self._start, 1e-9)

    def examples_per_second(self) -> float:
        return self.examples / self.elapsed

    def iterations_per_second(self) -> float:
        return self.iterations / self.elapsed

    def bandwidth_mb_s(self) -> float:
        return (self.h2d_bytes + self.d2h_bytes) / self.elapsed / 1e6

    def summary(self) -> str:
        return (f"{self.examples} examples in {self.elapsed:.1f}s "
                f"({self.examples_per_second():.1f} ex/s, "
                f"{self.iterations_per_second():.2f} it/s, "
                f"{self.bandwidth_mb_s():.1f} MB/s transfers)")
