"""In-graph numerics health: non-finite detection, gradient norms, and
update-to-weight ratios fused into the jitted train step.

A diverged run (NaN/Inf loss or gradients) burns accelerator-hours
producing garbage, and the usual detector — a host-side ``isnan`` on the
fetched loss — both misses non-finite *gradients* that haven't reached
the loss yet and adds a device round-trip per step. Here the health
terms are computed INSIDE the already-jitted train step (a handful of
``jnp.isfinite`` / norm reductions XLA fuses into the backward pass), so
they ride the deferred-score cadence of the async runtime (PR 2): the
fit loop accumulates the per-step device scalars and materializes them
only at the sync points where ``float(loss)`` already blocks — no extra
host sync, async-safe.

Published series (per model kind):

- ``dl4j_numerics_nonfinite_total{model,kind}`` — steps whose loss
  (``kind="loss"``) or gradients (``kind="grad"``) went non-finite
- ``dl4j_numerics_grad_norm`` / ``dl4j_numerics_update_ratio``
  histograms — global L2 gradient norm and update-norm / param-norm
  ratio (the classic divergence leading indicators: the ratio of a
  healthy net sits around 1e-3, explosion shows here first)
- ``dl4j_numerics_skipped_steps_total{model}`` — steps whose optimizer
  update was skipped by the policy below

Divergence feeds :class:`DivergenceRule` → ``/health`` flips failing
(and ``/alerts`` names the rule) while the event is recent on both the
step and wall clocks.

Skip policy (opt-in, ``DL4J_TPU_NUMERICS_SKIP=1``): on non-finite
gradients the step keeps its params/optimizer-state/running-stats
unchanged (an in-graph ``where`` select — the data batch is consumed,
the model survives). Skips are counted, recorded into the trace
(``numerics_skip`` span), and listener-visible via ``model.last_numerics``.

Kill switches: ``DL4J_TPU_NUMERICS=0`` (health terms never enter the
graph — the compiled step is byte-identical to pre-PR-4) under the
``DL4J_TPU_METRICS=0`` master. The flag is read at TRACE time: flipping
it affects newly-traced steps (fresh nets), not already-compiled ones.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.observability.registry import (global_registry,
                                                       metrics_enabled,
                                                       on_registry_reset)
from deeplearning4j_tpu.observability.slo import FAILING, OK, SLORule


def numerics_enabled() -> bool:
    """Kill switch — read at trace time (see module doc)."""
    return (metrics_enabled()
            and os.environ.get("DL4J_TPU_NUMERICS", "1") != "0")


def skip_on_nonfinite() -> bool:
    """Opt-in policy: skip the optimizer update on non-finite grads."""
    return os.environ.get("DL4J_TPU_NUMERICS_SKIP", "0") == "1"


def health_terms(loss, grads, params, updates) -> Dict[str, object]:
    """The in-graph health scalars (all jnp 0-d arrays; no host sync).

    Called from inside the jitted train step, AFTER the optimizer
    transform, so clipping/normalization is reflected in ``updates`` but
    the raw divergence signal (``grads``) is pre-clip.

    Gradient finiteness is derived from the L2 norm instead of a second
    elementwise ``isfinite`` pass: any NaN/Inf leaf propagates through
    the square-sum, so ``isfinite(grad_norm)`` covers the whole tree in
    the one reduction the norm already needs. (Caveat: finite gradients
    whose square-sum overflows f32 — leaves around 1e19 — also read
    non-finite; at that magnitude the run has diverged by any name.)
    """
    import jax
    import jax.numpy as jnp

    def _sq_sum(tree):
        leaves = [l for l in jax.tree.leaves(tree) if hasattr(l, "dtype")]
        if not leaves:
            return jnp.zeros((), jnp.float32)
        return sum(jnp.sum(jnp.square(l.astype(jnp.float32)))
                   for l in leaves)

    loss_finite = jnp.all(jnp.isfinite(loss))
    grad_norm = jnp.sqrt(_sq_sum(grads))
    grads_finite = jnp.isfinite(grad_norm)
    update_norm = jnp.sqrt(_sq_sum(updates))
    param_norm = jnp.sqrt(_sq_sum(params))
    return {
        "loss_finite": loss_finite,
        "grads_finite": grads_finite,
        "grad_norm": grad_norm,
        "update_ratio": update_norm / (param_norm + 1e-12),
        "skipped": jnp.zeros((), jnp.bool_),   # set by select() if policy on
    }


def select(ok, new_tree, old_tree):
    """In-graph skip: keep ``old_tree`` when ``ok`` is False. Donated
    input buffers are still readable inside the computation — only the
    Python-side references die with donation."""
    import jax
    import jax.numpy as jnp
    return jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                        new_tree, old_tree)


# --------------------------------------------------------- host-side state
class _DivergenceTracker:
    """Recent non-finite events on both clocks (step index + wall time),
    the state :class:`DivergenceRule` grades from."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: List[dict] = []    # {step, unix_ts, kind, model}
        self._last: Dict[str, dict] = {}  # model kind -> last published

    def record_nonfinite(self, model_kind: str, kind: str, step: int):
        with self._lock:
            self._events.append({"model": model_kind, "kind": kind,
                                 "step": step, "unix_ts": time.time()})
            del self._events[:-64]

    def note_publish(self, model_kind: str, values: dict):
        with self._lock:
            self._last[model_kind] = values

    def recent(self, window_steps: int, window_seconds: float,
               current_step: int) -> List[dict]:
        now = time.time()
        with self._lock:
            return [dict(e) for e in self._events
                    if current_step - e["step"] <= window_steps
                    and now - e["unix_ts"] <= window_seconds]

    def snapshot(self) -> dict:
        with self._lock:
            return {"nonfinite_events": [dict(e) for e in self._events],
                    "last_published": {k: dict(v)
                                       for k, v in self._last.items()}}

    def clear(self):
        with self._lock:
            self._events.clear()
            self._last.clear()


_tracker = _DivergenceTracker()


def tracker() -> _DivergenceTracker:
    return _tracker


def _current_step() -> int:
    """The shared fit-iteration clock (train_metrics.total_iterations) —
    the same clock the divergence window ages against."""
    from deeplearning4j_tpu.observability.train_metrics import (
        total_iterations)
    return total_iterations()


def stamp_step(health: Dict[str, object]) -> Dict[str, object]:
    """Stamp the CURRENT step index onto a just-produced health dict —
    called at the step, not at the (possibly ~64-steps-later) deferred
    publish, so divergence events carry the step they happened at."""
    health["step"] = _current_step()
    return health


def publish(model, pending: List[Dict[str, object]]) -> Optional[dict]:
    """Materialize and publish a batch of per-step health dicts (device
    scalars accumulated since the last sync point). Called where the fit
    loop already blocks — the arrays are computed, fetching them is a
    copy, not a pipeline stall. Returns the LAST step's values as floats
    (also stored on ``model.last_numerics`` for listener-level access).
    """
    if not pending:
        return None
    import jax

    model_kind = type(model).__name__
    host = jax.device_get(pending)
    reg = global_registry()
    nonfinite = reg.counter(
        "dl4j_numerics_nonfinite_total",
        "train steps with a non-finite loss or gradient, by kind",
        label_names=("model", "kind"))
    skipped_c = reg.counter(
        "dl4j_numerics_skipped_steps_total",
        "optimizer updates skipped by DL4J_TPU_NUMERICS_SKIP on "
        "non-finite gradients",
        label_names=("model",))
    grad_h = reg.histogram(
        "dl4j_numerics_grad_norm",
        "global L2 norm of the gradients, per train step",
        label_names=("model",),
        buckets=(1e-4, 1e-3, 1e-2, 0.1, 0.3, 1.0, 3.0, 10.0, 100.0, 1e4))
    ratio_h = reg.histogram(
        "dl4j_numerics_update_ratio",
        "update L2 norm / param L2 norm, per train step (healthy nets "
        "sit around 1e-3)",
        label_names=("model",),
        buckets=(1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0))
    fallback_step = _current_step()
    last = None
    for h in host:
        # the step index was stamped when the step ran (stamp_step) —
        # the publish may lag it by a whole deferred window
        step = int(h.pop("step", fallback_step))
        loss_ok = bool(h["loss_finite"])
        grads_ok = bool(h["grads_finite"])
        if not loss_ok:
            nonfinite.labels(model=model_kind, kind="loss").inc()
            _tracker.record_nonfinite(model_kind, "loss", step)
        if not grads_ok:
            nonfinite.labels(model=model_kind, kind="grad").inc()
            _tracker.record_nonfinite(model_kind, "grad", step)
        gn = float(h["grad_norm"])
        ur = float(h["update_ratio"])
        if gn == gn:                                   # NaN-safe observe
            grad_h.labels(model=model_kind).observe(gn)
        if ur == ur:
            ratio_h.labels(model=model_kind).observe(ur)
        skipped = bool(h.get("skipped", False))
        if skipped:
            skipped_c.labels(model=model_kind).inc()
            # traced: the skip is visible on the timeline next to its step
            from deeplearning4j_tpu.observability.tracing import (now_us,
                                                                  record_span)
            t = now_us()
            record_span("numerics_skip", t, t, model=model_kind,
                        loss_finite=loss_ok, grads_finite=grads_ok)
        last = {"loss_finite": loss_ok, "grads_finite": grads_ok,
                "grad_norm": gn, "update_ratio": ur, "skipped": skipped}
    if last is not None:
        _tracker.note_publish(model_kind, last)
        # listener-visible: the bus passes `model`, so a listener (or any
        # caller) reads the freshest health without touching the registry
        model.last_numerics = last
    return last


class DivergenceRule(SLORule):
    """Non-finite loss/gradients recently ⇒ ``failing`` — a diverged
    trainer must page immediately (every further step is wasted hours).
    Recovers once the event ages out of BOTH windows (or after a registry
    reset / fresh process)."""

    def __init__(self, name: str = "numerics_divergence",
                 window_steps: int = 200, window_seconds: float = 600.0,
                 description: str = ""):
        super().__init__(name, description or
                         "non-finite loss/gradients in the recent window")
        self.window_steps = window_steps
        self.window_seconds = window_seconds

    def _evaluate(self, registry) -> dict:
        recent = _tracker.recent(self.window_steps, self.window_seconds,
                                 _current_step())
        if not recent:
            return {"status": OK, "value": 0}
        worst = recent[-1]
        return {"status": FAILING, "value": len(recent),
                "detail": f"last: non-finite {worst['kind']} "
                          f"({worst['model']}) at step {worst['step']}"}


def snapshot() -> dict:
    """Bundle payload: recent non-finite events + last published health
    per model kind (the numerics half of a postmortem)."""
    return {"enabled": numerics_enabled(),
            "skip_on_nonfinite": skip_on_nonfinite(),
            **_tracker.snapshot()}


@on_registry_reset
def _clear_tracker():
    # a fresh registry restarts the step clock (test isolation)
    _tracker.clear()
