"""SLO engine: declarative health rules evaluated from the metrics registry.

``/health`` used to be a hardcoded ``"ok"`` — production health must be
*measured* (the serving-SLO posture of TF-Serving-style stacks, Abadi et
al. arXiv:1605.08695 §9). A rule reads live series from the registry and
grades them ``ok`` / ``degraded`` / ``failing``; the engine folds rule
grades into one process status, tracks transitions, and feeds:

- ``UIServer GET /health`` — JSON report, HTTP 503 when any rule fails
  (load balancers eject the replica), 200 with ``status: degraded``
  otherwise (alerting without traffic loss);
- ``UIServer GET /alerts`` — currently-violated rules with since-when
  timestamps plus the recent transition history.

Rules are deliberately few and structural (thresholds are constructor
params; ``None`` disables a grade):

- :class:`LatencyQuantileRule` — a histogram quantile (reservoir-exact)
  against degraded/failing bounds; skips until ``min_count`` samples so a
  near-empty histogram cannot grade a fresh process. Note the honest
  limit: a cold-compile outlier still dominates p99 until enough traffic
  dilutes the reservoir — ``min_count`` bounds how *early* that can
  happen (default 16), it does not exclude the outlier.
- :class:`ErrorRateRule`      — errors/requests counter ratio.
- :class:`GaugeThresholdRule` — gauge bound, ``mode="above"`` (queue
  depth) or ``"below"`` (prefetch overlap ratio), optionally gated on an
  activity counter so an idle pipeline reads healthy.

Evaluation never *creates* series (rules peek at live children only) and a
rule that raises grades ``degraded`` with the error in ``detail`` — a
typo'd rule must page, not crash the probe.
"""
from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence

from deeplearning4j_tpu.observability.registry import (Histogram,
                                                       MetricsRegistry,
                                                       global_registry,
                                                       on_registry_reset)

OK, DEGRADED, FAILING = "ok", "degraded", "failing"
_SEVERITY = {OK: 0, DEGRADED: 1, FAILING: 2}


def _children(inst):
    """Live (label_values, child) series WITHOUT creating any (the
    registry's public enumeration surface)."""
    return inst.series()


def _grade(value: float, degraded: Optional[float],
           failing: Optional[float], below: bool = False) -> str:
    if failing is not None and (value < failing if below
                                else value > failing):
        return FAILING
    if degraded is not None and (value < degraded if below
                                 else value > degraded):
        return DEGRADED
    return OK


class SLORule:
    """One named health check; subclasses implement :meth:`_evaluate`."""

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description

    def evaluate(self, registry: MetricsRegistry) -> dict:
        try:
            result = self._evaluate(registry)
        except Exception as e:
            result = {"status": DEGRADED, "detail": f"rule error: {e!r}"}
        result.setdefault("status", OK)
        result["rule"] = self.name
        if self.description:
            result.setdefault("description", self.description)
        return result

    def _evaluate(self, registry: MetricsRegistry) -> dict:
        raise NotImplementedError


class LatencyQuantileRule(SLORule):
    def __init__(self, name: str, metric: str, quantile: float = 0.99,
                 degraded: Optional[float] = 1.0,
                 failing: Optional[float] = 5.0,
                 min_count: int = 16, description: str = ""):
        super().__init__(name, description or
                         f"p{int(quantile * 100)} of {metric}")
        self.metric = metric
        self.quantile = quantile
        self.degraded = degraded
        self.failing = failing
        self.min_count = min_count

    def _evaluate(self, registry: MetricsRegistry) -> dict:
        inst = registry.get(self.metric)
        if not isinstance(inst, Histogram):
            return {"status": OK, "detail": "no data"}
        # worst child wins: a healthy INSTANT series must not mask a
        # drowning BATCHED one
        worst, worst_labels, total = None, (), 0
        for lvals, child in _children(inst):
            total += child.count
            if child.count < self.min_count:
                continue
            q = child.quantile(self.quantile)
            if q == q and (worst is None or q > worst):
                worst, worst_labels = q, lvals
        if worst is None:
            return {"status": OK, "samples": total,
                    "detail": f"<{self.min_count} samples"}
        return {"status": _grade(worst, self.degraded, self.failing),
                "value": worst, "quantile": self.quantile,
                "labels": list(worst_labels), "degraded_above": self.degraded,
                "failing_above": self.failing}


class ErrorRateRule(SLORule):
    def __init__(self, name: str, errors_metric: str, requests_metric: str,
                 degraded: Optional[float] = 0.01,
                 failing: Optional[float] = 0.05,
                 min_requests: int = 20, description: str = ""):
        super().__init__(name, description or
                         f"{errors_metric} / {requests_metric}")
        self.errors_metric = errors_metric
        self.requests_metric = requests_metric
        self.degraded = degraded
        self.failing = failing
        self.min_requests = min_requests

    @staticmethod
    def _total(registry, name) -> float:
        inst = registry.get(name)
        if inst is None:
            return 0.0
        return sum(child.value for _, child in _children(inst))

    def _evaluate(self, registry: MetricsRegistry) -> dict:
        requests = self._total(registry, self.requests_metric)
        if requests < self.min_requests:
            return {"status": OK, "requests": requests,
                    "detail": f"<{self.min_requests} requests"}
        rate = self._total(registry, self.errors_metric) / requests
        return {"status": _grade(rate, self.degraded, self.failing),
                "value": rate, "requests": requests,
                "degraded_above": self.degraded,
                "failing_above": self.failing}


class GaugeThresholdRule(SLORule):
    def __init__(self, name: str, metric: str,
                 degraded: Optional[float] = None,
                 failing: Optional[float] = None, mode: str = "above",
                 activity_metric: Optional[str] = None,
                 min_activity: float = 0, description: str = ""):
        if mode not in ("above", "below"):
            raise ValueError("mode must be 'above' or 'below'")
        super().__init__(name, description or
                         f"{metric} {mode} threshold")
        self.metric = metric
        self.degraded = degraded
        self.failing = failing
        self.mode = mode
        self.activity_metric = activity_metric
        self.min_activity = min_activity

    def _evaluate(self, registry: MetricsRegistry) -> dict:
        if self.activity_metric is not None:
            activity = ErrorRateRule._total(registry, self.activity_metric)
            if activity < self.min_activity:
                return {"status": OK,
                        "detail": f"<{self.min_activity} observations"}
        inst = registry.get(self.metric)
        if inst is None:
            return {"status": OK, "detail": "no data"}
        below = self.mode == "below"
        values = [child.value for _, child in _children(inst)]
        if not values:
            return {"status": OK, "detail": "no data"}
        worst = min(values) if below else max(values)
        key = "below" if below else "above"
        return {"status": _grade(worst, self.degraded, self.failing,
                                 below=below),
                "value": worst, f"degraded_{key}": self.degraded,
                f"failing_{key}": self.failing}


class PerfRegressionRule(SLORule):
    """Live MFU sustained below its own rolling baseline — the cost
    observatory's per-fn MFU (cost_model FLOPs / rolling-mean step time)
    is compared against the slow-EWMA reference the model keeps for each
    entry point. A sustained drop means the same program got slower:
    input starvation, a background process, a degraded interconnect, or
    a silently worse executable. Perf-only signal: degrades, never fails
    (slow is a page, not an ejection). Thin-data gated — a fn needs
    ``min_samples`` timed executions before it can grade."""

    def __init__(self, name: str = "perf_regression",
                 drop: Optional[float] = None,
                 min_samples: int = 24, description: str = ""):
        if drop is None:
            # ONE constant shared with the baseline's freeze margin
            # (cost_model) — a drop this rule flags can never erode its
            # own reference. A custom smaller drop loses that guarantee.
            from deeplearning4j_tpu.observability.cost_model import (
                PERF_REGRESSION_DROP)
            drop = PERF_REGRESSION_DROP
        super().__init__(name, description or
                         f"live MFU > {drop:.0%} below its rolling baseline")
        self.drop = drop
        self.min_samples = min_samples

    def _evaluate(self, registry) -> dict:
        # lazy: cost_model imports nothing from here, but keeping the
        # import out of module scope matches the other observatory rules
        from deeplearning4j_tpu.observability.cost_model import (
            global_cost_model)
        worst = None
        for fn, mfu, baseline, samples in global_cost_model(
                ).regression_view():
            if samples < self.min_samples or not baseline:
                continue
            ratio = mfu / baseline
            if worst is None or ratio < worst[1]:
                worst = (fn, ratio, mfu, baseline)
        if worst is None:
            return {"status": OK, "detail": f"<{self.min_samples} samples"}
        fn, ratio, mfu, baseline = worst
        status = DEGRADED if ratio < 1.0 - self.drop else OK
        return {"status": status, "value": ratio,
                "degraded_below": 1.0 - self.drop,
                "detail": f"{fn}: mfu {mfu:.4g} vs baseline "
                          f"{baseline:.4g}"}


def default_rules() -> List[SLORule]:
    """The serving/training SLOs every deployment cares about. Perf-only
    signals (prefetch overlap, retrace churn) cap short of ejection —
    slow is a page; divergence IS an ejection (every further step is
    wasted accelerator time)."""
    # lazy: compile_watch/numerics (and resilience.policy) import SLORule
    # from this module
    from deeplearning4j_tpu.observability.compile_watch import (
        RetraceStormRule)
    from deeplearning4j_tpu.observability.numerics import DivergenceRule
    from deeplearning4j_tpu.resilience.policy import CircuitOpenRule
    return [
        LatencyQuantileRule(
            "inference_p99_latency_seconds",
            "dl4j_inference_latency_seconds", quantile=0.99,
            degraded=1.0, failing=5.0, min_count=16,
            description="end-to-end ParallelInference p99 latency"),
        ErrorRateRule(
            "inference_error_rate",
            "dl4j_inference_errors_total", "dl4j_inference_requests_total",
            degraded=0.01, failing=0.05, min_requests=20,
            description="fraction of ParallelInference requests that raised"),
        GaugeThresholdRule(
            "inference_queue_depth",
            "dl4j_inference_queue_depth", degraded=48, failing=256,
            mode="above",
            description="requests waiting in the serving batch queue"),
        GaugeThresholdRule(
            "prefetch_overlap_ratio",
            "dl4j_async_overlap_ratio", degraded=0.2, failing=None,
            mode="below", activity_metric="dl4j_async_prefetch_total",
            min_activity=256,
            description="fraction of batches already on device when the "
                        "step asked (transfer/compute overlap health)"),
        RetraceStormRule(),
        DivergenceRule(),
        # the same program getting slower (MFU under its own rolling
        # baseline) pages; like retrace churn it never ejects the replica
        PerfRegressionRule(),
        # per-tenant SLO: the WORST tenant's p99 grades /health (the
        # worst-child-wins rule semantics — a drowning tenant must not
        # hide behind the healthy aggregate; labels are bounded by the
        # qos tenant_label top-N helper, so this scan stays small)
        LatencyQuantileRule(
            "tenant_p99_latency_seconds",
            "dl4j_tenant_latency_seconds", quantile=0.99,
            degraded=1.0, failing=5.0, min_count=16,
            description="per-tenant end-to-end p99 latency (worst "
                        "tenant wins; multi-tenant QoS)"),
        # an OPEN circuit means callers are being failed fast — eject the
        # replica; half-open (recovery probing) is a page, not an ejection
        CircuitOpenRule(),
    ]


#: every live engine, global or privately held (FleetHealth, rollout
#: gates) — a drill/test reset must clear ALL since/transition state,
#: not just the global engine's, or fleet alert timestamps survive
#: `reset_global_slo_engine()` and the next phase starts dirty
_ALL_ENGINES: "weakref.WeakSet[SLOEngine]" = weakref.WeakSet()


class SLOEngine:
    """Evaluates a rule set against a registry and tracks transitions."""

    _HISTORY_MAX = 64

    def __init__(self, rules: Optional[Sequence[SLORule]] = None,
                 registry=None):
        self.rules: List[SLORule] = list(rules if rules is not None
                                         else default_rules())
        self._registry = registry        # None = global (resolved per eval)
        self._lock = threading.Lock()
        self._since: Dict[str, tuple] = {}     # rule -> (status, since_ts)
        self._history: List[dict] = []         # recent transitions
        _ALL_ENGINES.add(self)

    def add_rule(self, rule: SLORule) -> "SLOEngine":
        self.rules.append(rule)
        return self

    def reset_state(self):
        with self._lock:
            self._since.clear()
            self._history.clear()

    def evaluate(self) -> dict:
        reg = self._registry or global_registry()
        results = [rule.evaluate(reg) for rule in self.rules]
        now = time.time()
        with self._lock:
            for res in results:
                prev = self._since.get(res["rule"])
                if prev is None or prev[0] != res["status"]:
                    self._since[res["rule"]] = (res["status"], now)
                    if prev is not None or res["status"] != OK:
                        self._history.append(
                            {"rule": res["rule"],
                             "from": prev[0] if prev else OK,
                             "to": res["status"], "at": now})
                        del self._history[:-self._HISTORY_MAX]
                res["since"] = self._since[res["rule"]][1]
        overall = max((r["status"] for r in results),
                      key=_SEVERITY.__getitem__, default=OK)
        return {
            "status": overall,
            "rules": results,
            "degraded_rules": [r["rule"] for r in results
                               if r["status"] == DEGRADED],
            "failing_rules": [r["rule"] for r in results
                              if r["status"] == FAILING],
        }

    def alerts(self) -> dict:
        """Active violations (with since-when) + recent transitions —
        re-evaluates so the answer is current, not last-scrape."""
        report = self.evaluate()
        active = [{"rule": r["rule"], "status": r["status"],
                   "since": r["since"],
                   "value": r.get("value"),
                   "detail": r.get("detail")}
                  for r in report["rules"] if r["status"] != OK]
        with self._lock:
            history = list(self._history)
        return {"status": report["status"], "active": active,
                "history": history}


_global_engine: Optional[SLOEngine] = None
_engine_lock = threading.Lock()


def global_slo_engine() -> SLOEngine:
    """THE process-wide engine ``/health`` and ``/alerts`` consult."""
    global _global_engine
    if _global_engine is None:
        with _engine_lock:
            if _global_engine is None:
                _global_engine = SLOEngine()
    return _global_engine


def _reset_all_engine_state():
    for eng in list(_ALL_ENGINES):
        eng.reset_state()


def reset_global_slo_engine(
        rules: Optional[Sequence[SLORule]] = None) -> SLOEngine:
    global _global_engine
    with _engine_lock:
        _global_engine = SLOEngine(rules)
    # every OTHER live engine too: alert since-timestamps must not
    # survive the reset through a privately-held engine (the fleet
    # health view, a rollout gate) — drills and tests start clean
    _reset_all_engine_state()
    return _global_engine


@on_registry_reset
def _clear_engine_state():
    # a fresh registry invalidates since/transition state (tests reset the
    # registry under a long-lived engine) — for every live engine, not
    # just the global one
    _reset_all_engine_state()
