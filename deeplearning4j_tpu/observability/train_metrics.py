"""Shared fit-loop instrumentation for both network runtimes.

One :class:`TrainingMetrics` instance per model kind (MultiLayerNetwork /
ComputationGraph) publishes the step-time decomposition into the global
registry. The decomposition follows the distributed-training
characterization playbook (Awan et al. arXiv:1810.11112): a step is

- ``data_wait``       — host time blocked on the input iterator
- ``device_compute``  — dispatch + XLA execution of the jitted train step,
  bounded by the blocking ``float(loss)`` device sync the fit loop already
  performs (no extra sync is added to measure)
- ``host_callback``   — listener bus dispatch (stats, checkpoints, UI)

plus a straggler check of the whole-step duration against the rolling
median. All instruments are cheap no-ops under ``DL4J_TPU_METRICS=0``.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from deeplearning4j_tpu.observability import device_memory
from deeplearning4j_tpu.observability.registry import (global_registry,
                                                       on_registry_reset)
from deeplearning4j_tpu.observability.straggler import StragglerDetector

_instances: Dict[str, "TrainingMetrics"] = {}
_lock = threading.Lock()


def total_iterations() -> int:
    """THE process-wide fit-iteration clock: completed iterations summed
    over model kinds. The observatory (compile_watch's retrace-storm
    window, numerics' divergence window) ages events against this one
    definition — do not reimplement it per consumer."""
    inst = global_registry().get("dl4j_training_iterations_total")
    if inst is None:
        return 0
    return int(sum(child.value for _, child in inst.series()))


class TrainingMetrics:
    """Label-bound handles for one model kind; get via :func:`for_model`."""

    def __init__(self, model_kind: str):
        reg = global_registry()
        self.model_kind = model_kind
        self.step_seconds = reg.histogram(
            "dl4j_training_step_seconds",
            "wall time of one fit iteration (all phases)",
            label_names=("model",)).labels(model=model_kind)
        phase_h = reg.histogram(
            "dl4j_training_phase_seconds",
            "fit iteration decomposed: data_wait | device_compute | "
            "host_callback",
            label_names=("model", "phase"))
        self.data_wait = phase_h.labels(model=model_kind, phase="data_wait")
        self.device_compute = phase_h.labels(model=model_kind,
                                             phase="device_compute")
        self.host_callback = phase_h.labels(model=model_kind,
                                            phase="host_callback")
        self.iterations = reg.counter(
            "dl4j_training_iterations_total",
            "completed fit iterations",
            label_names=("model",)).labels(model=model_kind)
        # incremented by the resilience layer (ResilientTrainer) when a
        # step raises past its in-place retries — the training analog of
        # dl4j_inference_errors_total
        self.step_failures = reg.counter(
            "dl4j_training_step_failures_total",
            "fit iterations that raised (after any in-place retries)",
            label_names=("model",)).labels(model=model_kind)
        self.examples = reg.counter(
            "dl4j_training_examples_total",
            "training examples consumed",
            label_names=("model",)).labels(model=model_kind)
        self.epochs = reg.counter(
            "dl4j_training_epochs_total",
            "completed training epochs",
            label_names=("model",)).labels(model=model_kind)
        self.score = reg.gauge(
            "dl4j_training_score",
            "last minibatch score (loss)",
            label_names=("model",)).labels(model=model_kind)
        self.straggler = StragglerDetector(phase=f"train_step:{model_kind}")

    def record_step(self, batch_size: int, score: float,
                    compute_seconds: float, callback_seconds: float,
                    data_wait_seconds: Optional[float] = None,
                    pipelined: bool = False):
        total = compute_seconds + callback_seconds
        if data_wait_seconds is not None:
            self.data_wait.observe(data_wait_seconds)
            total += data_wait_seconds
        self.device_compute.observe(compute_seconds)
        self.host_callback.observe(callback_seconds)
        self.step_seconds.observe(total)
        self.iterations.inc()
        if batch_size:
            self.examples.inc(batch_size)
        if score == score:                      # skip NaN
            self.score.set(score)
        if not pipelined:
            # under the async runtime's deferred loss fetch, per-call wall
            # time is dispatch-only for most steps and a whole window of
            # queued device work at sync points — every sync step would
            # read as a straggler against the dispatch-time median, so the
            # detector only sees honestly per-step-synchronous loops
            self.straggler.observe(total)
        # step boundary = the safe moment to read the PJRT allocator
        # (throttled internally; no-op latch on stat-less CPU backends)
        device_memory.sample()


def for_model(model) -> TrainingMetrics:
    """Per-model-kind singleton (instruments are label-bound, so two nets of
    the same kind share series — the process-wide registry contract)."""
    kind = type(model).__name__
    inst = _instances.get(kind)
    if inst is None:
        with _lock:
            inst = _instances.get(kind)
            if inst is None:
                inst = _instances[kind] = TrainingMetrics(kind)
    return inst


@on_registry_reset
def reset():
    """Forget cached handles (tests reset the global registry under us)."""
    with _lock:
        _instances.clear()
