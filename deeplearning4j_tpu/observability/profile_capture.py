"""On-demand device profiling: drive the ``profiler/xprof.py``
DeviceProfiler from an HTTP endpoint and serve a parsed top-K per-op
device-time table.

bench.py proved the XPlane protocol (device-measured picosecond durations
that transport timing cannot fake — ``benchmarks/device_timing.py``); this
module makes the same capture available to a RUNNING process without
restarting it under a profiler:

    GET /debug/profile?steps=N   — trace until N more work units (fit
                                   iterations + serving device batches)
                                   complete, bounded by ``timeout_s``
    GET /debug/profile           — the retained parsed captures

A capture is one ``jax.profiler`` trace written under the postmortem
directory (``profile-<pid>-<nonce>-<seq>``), parsed into:

- ``top_ops``  — per-op device time, aggregated and sorted (the "XLA Ops"
  line of the device planes; on stat-less CPU backends the per-op events
  live on host execution planes and the parser falls back to those)
- ``modules``  — per-XLA-module execution durations (the step-level view
  bench.py's device timing uses)

Retention is capped like postmortem bundles: trace directories beyond
``DL4J_TPU_POSTMORTEM_KEEP`` are evicted oldest-first (trace files are
multi-MB; the parsed tables are small and ride a bounded ring). One
capture runs at a time — the jax profiler is process-global.

Kill switch: ``DL4J_TPU_PROFILE=0`` refuses captures (HTTP 403).
"""
from __future__ import annotations

import glob
import os
import shutil
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from deeplearning4j_tpu.observability.flight_recorder import (_keep_bundles,
                                                              postmortem_dir)
from deeplearning4j_tpu.observability.registry import global_registry

#: retained parsed capture records (metadata + tables, small)
_RING_CAPACITY = 16

#: hard ceiling on one capture's wall time, whatever the caller asked for
_MAX_TIMEOUT_S = 60.0


class ProfileDisabled(RuntimeError):
    """DL4J_TPU_PROFILE=0 — captures are refused."""


class CaptureBusy(RuntimeError):
    """A capture is already running (the jax profiler is process-global)."""


def profile_enabled() -> bool:
    """Kill switch (read per call so tests can flip it)."""
    return os.environ.get("DL4J_TPU_PROFILE", "1") != "0"


def _work_units() -> int:
    """Completed work units the capture waits on: fit iterations + serving
    device batches — the same clocks the flight recorder's progress
    channels beat on."""
    from deeplearning4j_tpu.observability.train_metrics import (
        total_iterations)
    n = total_iterations()
    inst = global_registry().get("dl4j_inference_batches_total")
    if inst is not None:
        n += int(sum(child.value for _, child in inst.series()))
    return n


# ------------------------------------------------------------- xplane parse
def _load_xplanes(logdir: str):
    # deferred: the xplane proto ships inside tensorflow (tsl) and is heavy
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    spaces = []
    for f in glob.glob(os.path.join(logdir, "**", "*.xplane.pb"),
                       recursive=True):
        sp = xplane_pb2.XSpace()
        with open(f, "rb") as fh:
            sp.ParseFromString(fh.read())
        spaces.append(sp)
    return spaces


def parse_top_ops(logdir: str, top: int = 20) -> Dict[str, List[dict]]:
    """Aggregate per-op and per-module device time out of a trace dir.

    Device planes ("/device:TPU:*" — durations measured by the chip) are
    authoritative; when none carry events (CPU backend), per-op events on
    the host execution planes (every line except the python tracer) are
    the fallback, which is exactly what the CPU test mesh produces."""
    op_agg: Dict[str, List[float]] = {}
    mod_agg: Dict[str, List[float]] = {}
    host_agg: Dict[str, List[float]] = {}
    for space in _load_xplanes(logdir):
        for plane in space.planes:
            meta = plane.event_metadata
            device = plane.name.startswith("/device:") \
                and "CUSTOM" not in plane.name
            for line in plane.lines:
                if device and line.name == "XLA Ops":
                    for ev in line.events:
                        if ev.duration_ps <= 0:
                            continue
                        a = op_agg.setdefault(meta[ev.metadata_id].name,
                                              [0.0, 0])
                        a[0] += ev.duration_ps / 1e12
                        a[1] += 1
                elif device and "module" in line.name.lower():
                    for ev in line.events:
                        name = meta[ev.metadata_id].name.split("(")[0]
                        a = mod_agg.setdefault(name, [0.0, 0])
                        a[0] += ev.duration_ps / 1e12
                        a[1] += 1
                elif not device and line.name != "python":
                    for ev in line.events:
                        if ev.duration_ps <= 0:
                            continue
                        name = meta[ev.metadata_id].name
                        if ".py:" in name:     # python-tracer frames, not ops
                            continue
                        a = host_agg.setdefault(name, [0.0, 0])
                        a[0] += ev.duration_ps / 1e12
                        a[1] += 1
    src = op_agg or host_agg
    rows = sorted(((k, v[0], v[1]) for k, v in src.items()),
                  key=lambda r: -r[1])[:top]
    return {
        "top_ops": [{"op": k, "total_seconds": s, "count": c}
                    for k, s, c in rows],
        "modules": [{"module": k, "total_seconds": s, "count": c}
                    for k, s, c in sorted(
                        ((k, v[0], v[1]) for k, v in mod_agg.items()),
                        key=lambda r: -r[1])],
        "source": "device" if op_agg else "host",
    }


class ProfileCapture:
    """Bounded ring of parsed captures + the capture mutex. One
    process-wide instance via :func:`global_profile_capture`."""

    def __init__(self, out_dir: Optional[str] = None):
        self._out_dir = out_dir
        self._busy = threading.Lock()
        self._ring_lock = threading.Lock()
        self._ring: deque = deque(maxlen=_RING_CAPACITY)
        self._seq = 0
        self._instance = os.urandom(3).hex()

    def _base_dir(self) -> str:
        return self._out_dir or postmortem_dir()

    def capture(self, steps: int = 1, timeout_s: float = 5.0,
                top: int = 20) -> dict:
        """Profile until ``steps`` more work units complete (or
        ``timeout_s``), parse, retain, return the record."""
        if not profile_enabled():
            raise ProfileDisabled("device profiling disabled "
                                  "(DL4J_TPU_PROFILE=0)")
        if not self._busy.acquire(blocking=False):
            raise CaptureBusy("a profile capture is already running")
        try:
            from deeplearning4j_tpu.profiler.xprof import DeviceProfiler

            with self._ring_lock:
                self._seq += 1
                seq = self._seq
            trace_dir = os.path.join(
                self._base_dir(),
                f"profile-{os.getpid()}-{self._instance}-{seq:03d}")
            timeout_s = min(max(0.1, float(timeout_s)), _MAX_TIMEOUT_S)
            steps = max(1, int(steps))
            prof = DeviceProfiler(trace_dir)
            base = _work_units()
            t0 = time.monotonic()
            prof.start()
            try:
                while (time.monotonic() - t0 < timeout_s
                       and _work_units() - base < steps):
                    time.sleep(0.02)
            finally:
                prof.stop()
            record = {
                "id": f"{os.getpid()}-{self._instance}-{seq:03d}",
                "trace_dir": trace_dir,
                "unix_ts": time.time(),
                "duration_seconds": time.monotonic() - t0,
                "steps_requested": steps,
                "steps_seen": _work_units() - base,
            }
            try:
                record.update(parse_top_ops(trace_dir, top=top))
            except Exception as e:      # TF absent / proto drift: the trace
                record["parse_error"] = repr(e)   # dir still exists on disk
            self._prune()
            with self._ring_lock:
                self._ring.append(record)
            return record
        finally:
            self._busy.release()

    def _prune(self):
        """Evict trace dirs beyond the postmortem retention cap (the same
        knob bundles honor — trace files are multi-MB)."""
        keep = _keep_bundles()
        base = self._base_dir()
        try:
            entries = [os.path.join(base, e) for e in os.listdir(base)
                       if e.startswith("profile-")
                       and os.path.isdir(os.path.join(base, e))]
            entries.sort(key=lambda p: (os.path.getmtime(p), p))
            # the just-written trace dir is in the listing (newest) — the
            # same oldest-first eviction bundles use
            for old in entries[:-keep]:
                shutil.rmtree(old, ignore_errors=True)
        except OSError:
            pass

    def snapshot(self) -> dict:
        with self._ring_lock:
            captures = [dict(r) for r in self._ring]
        return {"enabled": profile_enabled(), "captures": captures}

    def clear(self):
        with self._ring_lock:
            self._ring.clear()


_global_capture: Optional[ProfileCapture] = None
_capture_lock = threading.Lock()


def global_profile_capture() -> ProfileCapture:
    """THE process-wide capture ring ``/debug/profile`` serves."""
    global _global_capture
    if _global_capture is None:
        with _capture_lock:
            if _global_capture is None:
                _global_capture = ProfileCapture()
    return _global_capture


def reset_global_profile_capture(**kw) -> ProfileCapture:
    global _global_capture
    with _capture_lock:
        _global_capture = ProfileCapture(**kw)
    return _global_capture
