"""Structured tracing: nested ``span()`` context managers, causal trace
context, and Chrome-trace export (tentpole of the observability PRs).

Spans record wall-clock duration with host thread + nesting depth, buffer
into a process-wide ring (bounded memory — a week-long trainer cannot OOM
the host by tracing), and export as Chrome trace-event JSON: complete
events (``ph: "X"`` with ``ts``/``dur`` in microseconds) plus flow events
(``ph: "s"/"f"``) that load directly in Perfetto / ``chrome://tracing``.
This is the portable twin of the device timeline ``profiler.xprof``
captures — host phases (data wait, dispatch, callbacks) live here, XLA
kernels live there.

Causal context (the production-tracing model of TF-Serving-style systems,
Abadi et al. arXiv:1605.08695 §9): every span carries
``trace_id``/``span_id``/``parent_id``. Within one thread, nesting on the
thread-local stack parents spans automatically. ACROSS threads and queues
the context is explicit: capture :func:`current_context` where a request
is enqueued, attach it to the queue item, and either open spans under
:func:`trace_context` on the consuming thread or stamp externally-timed
sections with :func:`record_span`. A request that crosses the
batcher→dispatcher→completer serving pipeline (or the device-prefetch
thread) then shares ONE trace_id, and the Chrome export emits flow events
so Perfetto draws the request arrows between threads.

Usage::

    from deeplearning4j_tpu.observability import span

    with span("fit.step", iteration=i):
        with span("data_wait"):
            batch = next(it)
        ...

    # cross-thread: producer side
    ctx = current_context()
    queue.put((work, ctx))
    # consumer side
    work, ctx = queue.get()
    with trace_context(ctx), span("consume"):
        ...

Kill switches: ``DL4J_TPU_METRICS=0`` (everything no-ops) and
``DL4J_TPU_TRACE=0`` (spans no-op, metrics stay live — isolates the
trace-propagation cost, see benchmarks/obs_overhead.py).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, NamedTuple, Optional

from deeplearning4j_tpu.observability.registry import (global_registry,
                                                       metrics_enabled,
                                                       on_registry_reset)
# cycle-safe: trace_store imports only registry, never tracing
from deeplearning4j_tpu.observability.trace_store import (store_span_close,
                                                          store_span_open)

#: default ring capacity — ~200k spans at <100 bytes each stays tens of MB
_DEFAULT_CAPACITY = 65536

# trace clock: perf_counter is monotonic; anchor it once so ts values are
# comparable across threads and roughly epoch-aligned
_EPOCH_ANCHOR = time.time() - time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() + _EPOCH_ANCHOR) * 1e6


#: public alias — callers timing cross-thread sections (queue waits) use
#: the same clock so their spans line up with ``with span(...)`` records
now_us = _now_us


def tracing_enabled() -> bool:
    """Spans record only when metrics are on AND ``DL4J_TPU_TRACE`` != 0
    (the latter keeps metrics live while isolating tracing's cost)."""
    return metrics_enabled() and os.environ.get("DL4J_TPU_TRACE", "1") != "0"


def _new_id() -> str:
    """16-hex-char random id (64 bits — the W3C trace-context span-id
    size; cheap enough for one or two per span on a hot fit loop)."""
    return os.urandom(8).hex()


class TraceContext(NamedTuple):
    """The portable half of a span: what a queue item must carry so work
    executed on another thread parents into the originating trace."""

    trace_id: str
    span_id: str


class SpanRecord:
    """One finished span (complete event)."""

    __slots__ = ("name", "ts_us", "dur_us", "tid", "depth", "attrs",
                 "trace_id", "span_id", "parent_id", "error", "error_type")

    def __init__(self, name: str, ts_us: float, dur_us: float, tid: int,
                 depth: int, attrs: Optional[Dict[str, Any]],
                 trace_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 parent_id: Optional[str] = None,
                 error: bool = False, error_type: Optional[str] = None):
        self.name = name
        self.ts_us = ts_us
        self.dur_us = dur_us
        self.tid = tid
        self.depth = depth
        self.attrs = attrs
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.error = error
        self.error_type = error_type

    def to_chrome_event(self) -> Dict[str, Any]:
        ev = {"name": self.name, "ph": "X", "ts": self.ts_us,
              "dur": self.dur_us, "pid": os.getpid(), "tid": self.tid,
              "cat": "host"}
        args: Dict[str, Any] = {}
        if self.attrs:
            args.update({k: (v if isinstance(v, (int, float, bool, str)
                                            ) or v is None else str(v))
                         for k, v in self.attrs.items()})
        if self.trace_id is not None:
            args["trace_id"] = self.trace_id
            args["span_id"] = self.span_id
            if self.parent_id is not None:
                args["parent_id"] = self.parent_id
        if self.error:
            args["error"] = True
            if self.error_type:
                args["error_type"] = self.error_type
        if args:
            ev["args"] = args
        return ev


# lazily-bound ring instruments (satellite: silent overflow made traces lie
# by omission — drops and occupancy are now scrapeable)
_ring_obs_cache: Optional[tuple] = None
_err_children: Dict[str, Any] = {}


def _ring_obs():
    global _ring_obs_cache
    if _ring_obs_cache is None:
        reg = global_registry()
        _ring_obs_cache = (
            reg.counter("dl4j_trace_spans_dropped_total",
                        "spans overwritten in the global trace ring before "
                        "export (raise TraceSink capacity if nonzero)"),
            reg.gauge("dl4j_trace_ring_fill_ratio",
                      "occupancy of the global trace ring (1.0 = full, "
                      "oldest spans are being dropped)"))
    return _ring_obs_cache


def _span_errors(name: str):
    child = _err_children.get(name)
    if child is None:
        child = _err_children[name] = global_registry().counter(
            "dl4j_span_errors_total",
            "spans that exited with an exception, by span name",
            label_names=("name",)).labels(name=name)
    return child


@on_registry_reset
def _drop_tracing_obs():
    global _ring_obs_cache
    _ring_obs_cache = None
    _err_children.clear()


class TraceSink:
    """Ring-buffered in-memory span store with Chrome-trace export."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._buf: List[Optional[SpanRecord]] = [None] * capacity
        self._head = 0          # next write slot
        self._total = 0         # spans ever recorded (drops = total - kept)
        self._drops_pending = 0  # overwrites not yet flushed to the counter
        self._lock = threading.Lock()

    def record(self, rec: SpanRecord):
        with self._lock:
            if self._buf[self._head] is not None:
                self._drops_pending += 1
            self._buf[self._head] = rec
            self._head = (self._head + 1) % self.capacity
            self._total += 1
            total = self._total
            publish = total % 64 == 0 or total == self.capacity
            flush, self._drops_pending = (
                (self._drops_pending, 0) if publish else (0,
                                                          self._drops_pending))
        if self is _global_sink and publish:
            # only THE process sink publishes ring health — per-test local
            # sinks would clobber each other's gauge. Both the fill gauge
            # and the drop counter flush every 64 records (once the ring
            # wraps, EVERY record overwrites — per-record instrument locks
            # on the span-exit hot path are exactly what this avoids; the
            # counter lags reality by <64 drops, scrape-time telemetry)
            dropped, fill_g = _ring_obs()
            if flush:
                dropped.inc(flush)
            fill_g.set(min(total, self.capacity) / self.capacity)

    def __len__(self) -> int:
        return min(self._total, self.capacity)

    @property
    def total_recorded(self) -> int:
        return self._total

    @property
    def dropped(self) -> int:
        return max(0, self._total - self.capacity)

    def spans(self) -> List[SpanRecord]:
        """Retained spans, oldest first."""
        with self._lock:
            if self._total <= self.capacity:
                out = self._buf[:self._head]
            else:
                out = self._buf[self._head:] + self._buf[:self._head]
            return [r for r in out if r is not None]

    def clear(self):
        with self._lock:
            self._buf = [None] * self.capacity
            self._head = 0
            self._total = 0
            flush, self._drops_pending = self._drops_pending, 0
        if self is _global_sink:
            # flush unreported drops and keep the occupancy gauge truthful
            # across a manual clear — a stale 1.0 would read as "currently
            # dropping spans"
            dropped, fill_g = _ring_obs()
            if flush:
                dropped.inc(flush)
            fill_g.set(0.0)

    # ------------------------------------------------------------- export
    def to_chrome_trace(self, flow_events: bool = True) -> List[Dict[str, Any]]:
        """The JSON-array flavor of the chrome trace format (what Perfetto
        and chrome://tracing load): complete events (``ph:"X"``) plus, for
        every parent→child edge that crosses threads, a flow-event pair
        (``ph:"s"`` on the parent's thread, ``ph:"f"`` on the child's) so
        the UI draws the request arrows across the pipeline."""
        spans = self.spans()
        events = [r.to_chrome_event() for r in spans]
        if not flow_events:
            return events
        by_id = {r.span_id: r for r in spans if r.span_id}
        pid = os.getpid()
        for r in spans:
            parent = by_id.get(r.parent_id) if r.parent_id else None
            if parent is None or parent.tid == r.tid:
                continue        # same-thread nesting needs no arrow
            # bind the arrow to the parent's slice start and the child's
            # slice start; Chrome requires s.ts <= f.ts
            s_ts = min(parent.ts_us, r.ts_us)
            events.append({"name": "handoff", "cat": "flow", "ph": "s",
                           "id": r.span_id, "ts": s_ts, "pid": pid,
                           "tid": parent.tid})
            events.append({"name": "handoff", "cat": "flow", "ph": "f",
                           "bp": "e", "id": r.span_id,
                           "ts": max(r.ts_us, s_ts), "pid": pid,
                           "tid": r.tid})
        return events

    def export_json(self, path: Optional[str] = None) -> str:
        payload = json.dumps(self.to_chrome_trace())
        if path is not None:
            with open(path, "w") as f:
                f.write(payload)
        return payload


_global_sink: Optional[TraceSink] = None
_sink_lock = threading.Lock()
_tls = threading.local()


def global_trace_sink() -> TraceSink:
    global _global_sink
    if _global_sink is None:
        with _sink_lock:
            if _global_sink is None:
                _global_sink = TraceSink()
    return _global_sink


def reset_global_trace_sink(capacity: int = _DEFAULT_CAPACITY) -> TraceSink:
    global _global_sink
    with _sink_lock:
        _global_sink = TraceSink(capacity)
    return _global_sink


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def current_context() -> Optional[TraceContext]:
    """The context new work on THIS thread would parent under: the
    innermost open span, else a context attached via :func:`trace_context`,
    else None. Capture it at an enqueue site and ship it with the item."""
    st = getattr(_tls, "stack", None)
    if st:
        top = st[-1]
        return TraceContext(top.trace_id, top.span_id)
    return getattr(_tls, "ctx", None)


class trace_context:
    """Attach a captured :class:`TraceContext` to the current thread for
    the duration of the block — spans opened inside parent under it, so a
    worker thread's sections join the enqueuing request's trace::

        with trace_context(ctx), span("prefetch_place"):
            ...

    ``None`` is accepted and leaves the thread context unchanged-in-effect
    (callers need no conditional around the handoff)."""

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx if self.ctx is not None else self._prev
        return self.ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


class Span:
    """Context manager measuring one named section; nests via a
    thread-local stack so ``depth`` reflects the live call structure, and
    carries trace context (see module doc) so cross-thread work links."""

    __slots__ = ("name", "attrs", "sink", "_ts", "depth",
                 "trace_id", "span_id", "parent_id")

    def __init__(self, name: str, sink: Optional[TraceSink] = None,
                 **attrs):
        self.name = name
        self.attrs = attrs or None
        self.sink = sink

    def set_attr(self, key: str, value):
        """Attach/overwrite an attribute while the span is open."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    def __enter__(self):
        st = _stack()
        self.depth = len(st)
        if st:                          # nested: parent is the open span
            parent = st[-1]
            self.trace_id, self.parent_id = parent.trace_id, parent.span_id
        else:
            ctx = getattr(_tls, "ctx", None)
            if ctx is not None:         # cross-thread attached context
                self.trace_id, self.parent_id = ctx.trace_id, ctx.span_id
            else:                       # root: new trace
                self.trace_id, self.parent_id = _new_id(), None
        self.span_id = _new_id()
        if self.sink is None:
            # global-sink spans also feed the completed-trace store: the
            # open/close balance tells it when a trace's last span closed
            store_span_open(self.trace_id)
        st.append(self)
        self._ts = _now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        # dur shares self._ts's clock read: a second perf_counter
        # capture at enter left a preemption window that could make a
        # child's end time exceed its parent's (ts + dur must nest)
        dur = _now_us() - self._ts
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        else:                       # tolerate out-of-order exits
            try:
                st.remove(self)
            except ValueError:
                pass
        # satellite fix: the exception triple is no longer ignored —
        # failing sections are visible in traces AND as a counter series
        error = exc_type is not None
        # explicit None check: an EMPTY TraceSink is falsy (__len__ == 0),
        # so `or` would silently reroute the first span to the global sink
        sink = self.sink if self.sink is not None else global_trace_sink()
        rec = SpanRecord(
            self.name, self._ts, dur, threading.get_ident(), self.depth,
            self.attrs, trace_id=self.trace_id, span_id=self.span_id,
            parent_id=self.parent_id, error=error,
            error_type=exc_type.__name__ if error else None)
        sink.record(rec)
        if self.sink is None:
            store_span_close(rec, True)
        if error:
            _span_errors(self.name).inc()
        return False


class _NoopSpan:
    __slots__ = ()

    def set_attr(self, key, value):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(name: str, sink: Optional[TraceSink] = None, **attrs):
    """``with span("name", **attrs):`` — the one tracing entry point."""
    if not tracing_enabled():
        return _NOOP
    return Span(name, sink, **attrs)


def record_span(name: str, start_us: float, end_us: Optional[float] = None,
                ctx: Optional[TraceContext] = None,
                sink: Optional[TraceSink] = None,
                **attrs) -> Optional[SpanRecord]:
    """Record an externally-timed span — a section whose start and end were
    observed on different sides of a queue (e.g. a request's queue_wait:
    enqueue stamped on the producer, dequeue observed by the batcher).

    ``ctx`` parents the record into the originating trace; timestamps use
    the :func:`now_us` clock. Returns the record (None when tracing is
    off)."""
    if not tracing_enabled():
        return None
    end = end_us if end_us is not None else _now_us()
    rec = SpanRecord(
        name, start_us, max(0.0, end - start_us), threading.get_ident(), 0,
        attrs or None,
        trace_id=ctx.trace_id if ctx is not None else _new_id(),
        span_id=_new_id(),
        parent_id=ctx.span_id if ctx is not None else None)
    if sink is not None:
        sink.record(rec)
    else:
        global_trace_sink().record(rec)
        # externally-timed spans never opened on a stack; they complete a
        # trace only when it has no still-open span() blocks
        store_span_close(rec, False)
    return rec


def current_span() -> Optional[Span]:
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None
