"""Structured tracing: nested ``span()`` context managers + Chrome-trace
export (tentpole of the observability PR).

Spans record wall-clock duration with host thread + nesting depth, buffer
into a process-wide ring (bounded memory — a week-long trainer cannot OOM
the host by tracing), and export as Chrome trace-event JSON: a list of
complete events (``ph: "X"`` with ``ts``/``dur`` in microseconds) that
loads directly in Perfetto / ``chrome://tracing``. This is the portable
twin of the device timeline ``profiler.xprof`` captures — host phases
(data wait, dispatch, callbacks) live here, XLA kernels live there.

Usage::

    from deeplearning4j_tpu.observability import span

    with span("fit.step", iteration=i):
        with span("data_wait"):
            batch = next(it)
        ...

Same kill switch as the metrics registry (``DL4J_TPU_METRICS=0``): spans
become no-op context managers.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.observability.registry import metrics_enabled

#: default ring capacity — ~200k spans at <100 bytes each stays tens of MB
_DEFAULT_CAPACITY = 65536

# trace clock: perf_counter is monotonic; anchor it once so ts values are
# comparable across threads and roughly epoch-aligned
_EPOCH_ANCHOR = time.time() - time.perf_counter()


def _now_us() -> float:
    return (time.perf_counter() + _EPOCH_ANCHOR) * 1e6


class SpanRecord:
    """One finished span (complete event)."""

    __slots__ = ("name", "ts_us", "dur_us", "tid", "depth", "attrs")

    def __init__(self, name: str, ts_us: float, dur_us: float, tid: int,
                 depth: int, attrs: Optional[Dict[str, Any]]):
        self.name = name
        self.ts_us = ts_us
        self.dur_us = dur_us
        self.tid = tid
        self.depth = depth
        self.attrs = attrs

    def to_chrome_event(self) -> Dict[str, Any]:
        ev = {"name": self.name, "ph": "X", "ts": self.ts_us,
              "dur": self.dur_us, "pid": os.getpid(), "tid": self.tid,
              "cat": "host"}
        if self.attrs:
            ev["args"] = {k: (v if isinstance(v, (int, float, bool, str)
                                             ) or v is None else str(v))
                          for k, v in self.attrs.items()}
        return ev


class TraceSink:
    """Ring-buffered in-memory span store with Chrome-trace export."""

    def __init__(self, capacity: int = _DEFAULT_CAPACITY):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._buf: List[Optional[SpanRecord]] = [None] * capacity
        self._head = 0          # next write slot
        self._total = 0         # spans ever recorded (drops = total - kept)
        self._lock = threading.Lock()

    def record(self, rec: SpanRecord):
        with self._lock:
            self._buf[self._head] = rec
            self._head = (self._head + 1) % self.capacity
            self._total += 1

    def __len__(self) -> int:
        return min(self._total, self.capacity)

    @property
    def total_recorded(self) -> int:
        return self._total

    @property
    def dropped(self) -> int:
        return max(0, self._total - self.capacity)

    def spans(self) -> List[SpanRecord]:
        """Retained spans, oldest first."""
        with self._lock:
            if self._total <= self.capacity:
                out = self._buf[:self._head]
            else:
                out = self._buf[self._head:] + self._buf[:self._head]
            return [r for r in out if r is not None]

    def clear(self):
        with self._lock:
            self._buf = [None] * self.capacity
            self._head = 0
            self._total = 0

    # ------------------------------------------------------------- export
    def to_chrome_trace(self) -> List[Dict[str, Any]]:
        """The JSON-array flavor of the chrome trace format (what Perfetto
        and chrome://tracing load): a list of ``ph``/``ts``/``dur`` events."""
        return [r.to_chrome_event() for r in self.spans()]

    def export_json(self, path: Optional[str] = None) -> str:
        payload = json.dumps(self.to_chrome_trace())
        if path is not None:
            with open(path, "w") as f:
                f.write(payload)
        return payload


_global_sink: Optional[TraceSink] = None
_sink_lock = threading.Lock()
_tls = threading.local()


def global_trace_sink() -> TraceSink:
    global _global_sink
    if _global_sink is None:
        with _sink_lock:
            if _global_sink is None:
                _global_sink = TraceSink()
    return _global_sink


def reset_global_trace_sink(capacity: int = _DEFAULT_CAPACITY) -> TraceSink:
    global _global_sink
    with _sink_lock:
        _global_sink = TraceSink(capacity)
    return _global_sink


def _stack() -> list:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class Span:
    """Context manager measuring one named section; nests via a
    thread-local stack so ``depth`` reflects the live call structure."""

    __slots__ = ("name", "attrs", "sink", "_t0", "_ts", "depth")

    def __init__(self, name: str, sink: Optional[TraceSink] = None,
                 **attrs):
        self.name = name
        self.attrs = attrs or None
        self.sink = sink

    def set_attr(self, key: str, value):
        """Attach/overwrite an attribute while the span is open."""
        if self.attrs is None:
            self.attrs = {}
        self.attrs[key] = value
        return self

    def __enter__(self):
        st = _stack()
        self.depth = len(st)
        st.append(self)
        self._ts = _now_us()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = (time.perf_counter() - self._t0) * 1e6
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        else:                       # tolerate out-of-order exits
            try:
                st.remove(self)
            except ValueError:
                pass
        # explicit None check: an EMPTY TraceSink is falsy (__len__ == 0),
        # so `or` would silently reroute the first span to the global sink
        sink = self.sink if self.sink is not None else global_trace_sink()
        sink.record(SpanRecord(
            self.name, self._ts, dur, threading.get_ident(), self.depth,
            self.attrs))
        return False


class _NoopSpan:
    __slots__ = ()

    def set_attr(self, key, value):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


def span(name: str, sink: Optional[TraceSink] = None, **attrs):
    """``with span("name", **attrs):`` — the one tracing entry point."""
    if not metrics_enabled():
        return _NOOP
    return Span(name, sink, **attrs)


def current_span() -> Optional[Span]:
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None
