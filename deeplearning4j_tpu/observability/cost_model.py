"""XLA cost-model accounting: per-entry-point FLOPs/bytes, live MFU,
roofline grading.

PRs 1–4 made the stack observable in *time* (spans, step histograms,
compile events) but not in *work*: nothing in-process knew how many FLOPs
or bytes a compiled program moves, so "is this step fast?" was only
answerable by hand-running bench.py. This module closes that loop — the
same per-program accounting a whole-program XLA lowering gets for free
(Fishman et al. arXiv:1810.09868) and that weight-update-sharding papers
reason with (Xu et al. arXiv:2004.13336):

- **Cost accounting**: at the probe points compile_watch already owns
  (MLN/CG ``_train_step``, the ShardedTrainer sharded step, every
  ParallelInference shape-bucket executable), the entry point is AOT
  re-``lower()``-ed right after a (re)compile and its
  ``Lowered.cost_analysis()`` FLOPs / bytes-accessed published as
  ``dl4j_cost_flops{fn}`` / ``dl4j_cost_bytes{fn}``. The lowering is a
  jaxpr-cache HIT on the signature the step just ran (no retrace, no
  compile) and happens only when compile_watch's per-fn trace count
  moved — steady-state cost is one dict lookup and an int compare.
- **Live MFU**: the fit loops and the serving completer feed the same
  step/batch wall durations they already measure into a rolling window;
  ``dl4j_mfu{fn}`` = FLOPs / (rolling-mean seconds × peak FLOP/s). The
  window (64 samples) spans at least two deferred-score sync periods, so
  the async runtime's dispatch-only step timings average out correctly.
- **Roofline verdict**: arithmetic intensity (FLOPs / bytes accessed)
  against the ridge point of a per-backend peak-FLOPs / HBM-bandwidth
  table — ``compute_bound`` when the program could saturate the MXU,
  ``memory_bound`` when HBM sets the ceiling. Overridable via
  ``DL4J_TPU_PEAK_FLOPS`` (FLOP/s) and ``DL4J_TPU_HBM_GBPS`` (GB/s) so
  CPU tests are deterministic and bench comparisons share one table.
- **Regression reference**: a slow EWMA of the live MFU is each fn's own
  rolling baseline; :class:`~.slo.PerfRegressionRule` grades sustained
  drops on ``/health`` + ``/alerts``. The baseline freezes while a
  violation is in progress so a real regression cannot normalize itself
  away.

Surfaces: ``GET /debug/perf`` (full per-fn cost/time/MFU/roofline JSON),
``perf.json`` in flight-recorder bundles.

Known approximations (documented, not bugs): ``cost_analysis()`` runs on
the unoptimized HLO (fusion changes real bytes moved); sharded entries
report GLOBAL program FLOPs, so their peak is scaled by the mesh size
(:meth:`CostModel.set_scale`); serving batch durations include pipeline
queueing under multi-in-flight dispatch, so serving MFU is a lower bound.

Kill switch: ``DL4J_TPU_COST_MODEL=0`` (accounting + MFU timing no-op)
under the ``DL4J_TPU_METRICS=0`` master.
"""
from __future__ import annotations

import os
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from deeplearning4j_tpu.observability import compile_watch as _cw
from deeplearning4j_tpu.observability.registry import (global_registry,
                                                       metrics_enabled,
                                                       on_registry_reset)

#: step-duration samples the live MFU averages over — MUST span at least
#: two deferred-score sync periods (DL4J_TPU_SCORE_EVERY, default 16):
#: under the async runtime most per-step timings are dispatch-only and
#: the sync step absorbs the whole window, so only a window-spanning mean
#: reads the true per-step time
_TIMES_MAX = 64

#: slow EWMA weight for the per-fn MFU baseline the regression rule
#: grades against (half-life ~70 samples — a sustained drop is caught
#: long before the reference erodes)
_BASELINE_ALPHA = 0.01

#: fractional MFU drop below its rolling baseline that counts as a
#: regression. ONE constant on purpose: slo.PerfRegressionRule derives
#: its default ``drop`` from it, and the baseline EWMA freezes at the
#: same margin — a drop the rule would flag can never erode its own
#: reference. A custom rule with a smaller drop loses that guarantee.
PERF_REGRESSION_DROP = 0.3

#: per-chip peak dense FLOP/s and HBM bandwidth (bytes/s) by platform.
#: The TPU row matches bench.py's V5E_PEAK_BF16 so live MFU and the
#: bench's device-trace MFU share a denominator. CPU numbers are
#: order-of-magnitude placeholders — tests pin the table via the env
#: knobs for determinism.
_PEAK_DEFAULTS = {
    "tpu": (197e12, 819e9),      # v5e bf16 (scaling-book table)
    "axon": (197e12, 819e9),     # the remote-TPU plugin platform name
    "gpu": (312e12, 2039e9),     # A100 bf16
    "cpu": (1e11, 5e10),
}


def cost_model_enabled() -> bool:
    """Kill switch (read per call so tests can flip it)."""
    return (metrics_enabled()
            and os.environ.get("DL4J_TPU_COST_MODEL", "1") != "0")


_platform_cache: Optional[str] = None


def _platform() -> str:
    global _platform_cache
    if _platform_cache is None:
        try:
            import jax
            _platform_cache = jax.devices()[0].platform
        except Exception:
            _platform_cache = "cpu"
    return _platform_cache


def peak_flops() -> float:
    """Per-chip peak FLOP/s: ``DL4J_TPU_PEAK_FLOPS`` else platform table."""
    env = os.environ.get("DL4J_TPU_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return _PEAK_DEFAULTS.get(_platform(), _PEAK_DEFAULTS["cpu"])[0]


def hbm_bytes_per_second() -> float:
    """Per-chip HBM bandwidth: ``DL4J_TPU_HBM_GBPS`` (GB/s) else table."""
    env = os.environ.get("DL4J_TPU_HBM_GBPS")
    if env:
        try:
            return float(env) * 1e9
        except ValueError:
            pass
    return _PEAK_DEFAULTS.get(_platform(), _PEAK_DEFAULTS["cpu"])[1]


def ridge_intensity() -> float:
    """FLOPs/byte at which the roofline's compute and memory ceilings
    meet — programs above it can saturate the MXU, below it HBM rules."""
    return peak_flops() / max(hbm_bytes_per_second(), 1.0)


def parse_cost_analysis(costs) -> Tuple[float, float]:
    """Normalize ``Lowered/Compiled.cost_analysis()`` output across jax
    versions (some return a per-device list) → (flops, bytes_accessed).
    The ONE place that parsing lives — bench.py's cross-check uses it
    too, so a jax upgrade can't fix one consumer and strand the other."""
    if isinstance(costs, (list, tuple)):
        costs = costs[0] if costs else {}
    return (float(costs.get("flops", 0.0) or 0.0),
            float(costs.get("bytes accessed", 0.0) or 0.0))


def _publish_cost(fn: str, flops: float, byts: float):
    """The ONE registration site for the per-fn cost gauges (account and
    record_cost must agree on name + help text)."""
    reg = global_registry()
    reg.gauge("dl4j_cost_flops",
              "XLA cost-model FLOPs per execution of the jitted "
              "entry point (unoptimized-HLO cost analysis)",
              label_names=("fn",)).labels(fn=fn).set(float(flops))
    reg.gauge("dl4j_cost_bytes",
              "XLA cost-model bytes accessed per execution of the "
              "jitted entry point",
              label_names=("fn",)).labels(fn=fn).set(float(byts))


class _Entry:
    """Per-fn accounting state (no lock of its own — CostModel's lock)."""

    __slots__ = ("flops", "bytes", "signature", "source", "error",
                 "analyzed_count", "analyze_calls", "times", "count",
                 "mfu", "bw_util", "baseline_mfu", "g_mfu")

    def __init__(self):
        self.flops = 0.0
        self.bytes = 0.0
        self.signature = None
        self.source = None            # "cost_analysis" once accounted
        self.error = None
        self.analyzed_count = -1      # compile-watch count last analyzed at
        self.analyze_calls = 0        # how often cost analysis actually ran
        self.times = deque(maxlen=_TIMES_MAX)
        self.count = 0                # lifetime duration samples
        self.mfu = None               # rolling-window MFU
        self.bw_util = None           # rolling-window HBM-bandwidth util
        self.baseline_mfu = None      # slow EWMA (regression reference)
        self.g_mfu = None             # cached gauge child


class CostModel:
    """Per-fn cost/time/MFU store. One process-wide instance via
    :func:`global_cost_model`; tests may construct their own."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, _Entry] = {}
        self._collectives: Dict[str, Dict[str, float]] = {}
        self._compression: Dict[str, Dict[str, object]] = {}
        self._scales: Dict[str, int] = {}     # fn -> devices executing it

    # -------------------------------------------------------- accounting
    def has_entry(self, fn: str) -> bool:
        with self._lock:
            return fn in self._entries

    def needs_account(self, fn: str, probe_fn: Optional[str] = None) -> bool:
        """True when ``fn`` has never been analyzed, or compile_watch has
        counted a new (re)trace of ``probe_fn`` since the last analysis —
        the 'fires exactly once per compile' contract."""
        count = _cw.global_compile_watch().count_for(probe_fn or fn)
        with self._lock:
            e = self._entries.get(fn)
            return e is None or e.analyzed_count != count

    def account(self, fn: str, lower_thunk: Callable[[], object],
                probe_fn: Optional[str] = None) -> Optional[dict]:
        """Run ``lower_thunk()`` (an AOT ``jit(...).lower`` call at the
        signature that just executed — a jaxpr-cache hit, no compile) and
        record its ``cost_analysis()``. Analysis failures are recorded on
        the entry, never raised into the fit loop."""
        count = _cw.global_compile_watch().count_for(probe_fn or fn)
        with self._lock:
            e = self._entries.setdefault(fn, _Entry())
            e.analyzed_count = count
            e.analyze_calls += 1
        try:
            with _cw.suppress_probes():
                lowered = lower_thunk()
                costs = lowered.cost_analysis()
            flops, byts = parse_cost_analysis(costs)
            sig = None
            try:
                sig = _cw._signature([lowered.in_avals]) \
                    if hasattr(lowered, "in_avals") else None
            except Exception:
                sig = None
            with self._lock:
                # re-fetch: a concurrent clear()/invalidate() may have
                # dropped the entry between the two locked sections
                e = self._entries.setdefault(fn, _Entry())
                e.flops, e.bytes = flops, byts
                e.signature = sig
                e.source = "cost_analysis"
                e.error = None
            _publish_cost(fn, flops, byts)
            return {"flops": flops, "bytes": byts}
        except Exception as err:          # analysis is best-effort telemetry
            with self._lock:
                e = self._entries.get(fn)
                if e is not None:     # don't resurrect a concurrent clear()
                    e.error = repr(err)
            return None

    def record_cost(self, fn: str, flops: float, bytes_accessed: float = 0.0,
                    signature: Optional[str] = None):
        """Record externally computed costs (bench.py feeds the flagship
        transformer step it lowered itself)."""
        if not cost_model_enabled():      # same contract as every hook:
            return                        # the kill switch keeps it empty
        with self._lock:
            e = self._entries.setdefault(fn, _Entry())
            e.flops = float(flops)
            e.bytes = float(bytes_accessed or 0.0)
            e.signature = signature
            e.source = "external"
            e.analyze_calls += 1
        _publish_cost(fn, flops, bytes_accessed or 0.0)

    def invalidate(self, fn: str):
        """Drop one entry so the next step re-accounts it (ShardedTrainer
        re-placement recompiles WITHOUT a retrace — the probe count can't
        signal it)."""
        with self._lock:
            self._entries.pop(fn, None)

    def note_collectives(self, fn: str, bytes_by_op: Dict[str, float]):
        """Attach the analytic per-step collective traffic expectation to
        an entry (ShardedTrainer's allreduce / reduce-scatter+all-gather
        payload) — served next to the measured cost on /debug/perf."""
        with self._lock:
            self._collectives[fn] = {k: float(v)
                                     for k, v in bytes_by_op.items()}

    def note_compression(self, fn: str, info: Dict[str, object]):
        """Attach (merge) gradient-compression facts to an entry — the
        ThresholdAlgorithm in force, the analytic wire payload vs dense
        bytes, and the last synced encoded fraction — served as
        ``grad_compression`` next to the collective bytes on /debug/perf
        and in perf.json bundles."""
        with self._lock:
            self._compression.setdefault(fn, {}).update(info)

    def set_scale(self, fn: str, devices: int):
        """Sharded entries report GLOBAL program FLOPs — their roofline
        peak is ``devices`` chips, not one."""
        with self._lock:
            self._scales[fn] = max(1, int(devices))

    # ------------------------------------------------------------ timing
    def observe_time(self, fn: str, seconds: float):
        """Feed one measured execution duration; recomputes the rolling
        MFU/BW utilization and updates the regression baseline."""
        if seconds <= 0:
            return
        peak = peak_flops()
        hbm = hbm_bytes_per_second()
        with self._lock:
            e = self._entries.setdefault(fn, _Entry())
            e.times.append(float(seconds))
            e.count += 1
            if not e.flops:
                return
            scale = self._scales.get(fn, 1)
            mean_s = sum(e.times) / len(e.times)
            e.mfu = e.flops / (mean_s * peak * scale)
            e.bw_util = e.bytes / (mean_s * hbm * scale) if e.bytes else None
            # regression reference: slow EWMA, FROZEN at the SAME margin
            # PerfRegressionRule grades at — a drop the rule would flag
            # must not drag its own baseline down and self-heal the alert
            if e.baseline_mfu is None:
                e.baseline_mfu = e.mfu
            elif e.mfu >= e.baseline_mfu * (1.0 - PERF_REGRESSION_DROP):
                e.baseline_mfu += _BASELINE_ALPHA * (e.mfu - e.baseline_mfu)
            mfu, gauge = e.mfu, e.g_mfu
        if gauge is None:
            gauge = global_registry().gauge(
                "dl4j_mfu",
                "live model-FLOPs utilisation of the jitted entry point: "
                "cost-model FLOPs / (rolling-mean step seconds x peak "
                "FLOP/s from the DL4J_TPU_PEAK_FLOPS-overridable table)",
                label_names=("fn",)).labels(fn=fn)
            with self._lock:
                ent = self._entries.get(fn)   # clear() may have raced us
                if ent is not None:
                    ent.g_mfu = gauge
        gauge.set(mfu)

    def flops_for(self, fn: str) -> float:
        """Accounted FLOPs of one entry (0.0 when never analyzed) — the
        cheap read the per-tenant cost attribution uses per batch/step
        (one lock + dict lookup, no snapshot)."""
        with self._lock:
            e = self._entries.get(fn)
            return e.flops if e is not None else 0.0

    # ----------------------------------------------------------- queries
    def regression_view(self) -> List[Tuple[str, float, float, int]]:
        """(fn, rolling_mfu, baseline_mfu, samples) for every entry with
        both — the PerfRegressionRule's read surface."""
        with self._lock:
            return [(fn, e.mfu, e.baseline_mfu, e.count)
                    for fn, e in self._entries.items()
                    if e.mfu is not None and e.baseline_mfu]

    def entry(self, fn: str) -> Optional[dict]:
        snap = self.snapshot()
        return snap["fns"].get(fn)

    def snapshot(self) -> dict:
        """The /debug/perf + perf.json payload."""
        peak = peak_flops()
        hbm = hbm_bytes_per_second()
        ridge = peak / max(hbm, 1.0)
        fns = {}
        with self._lock:
            # times MUST be copied under the lock: observe_time appends
            # concurrently and list() over a mutating deque raises
            items = [(fn, e, list(e.times))
                     for fn, e in self._entries.items()]
            collectives = {k: dict(v) for k, v in self._collectives.items()}
            compression = {k: dict(v) for k, v in self._compression.items()}
            scales = dict(self._scales)
        for fn, e, times in items:
            mean_s = (sum(times) / len(times)) if times else None
            intensity = (e.flops / e.bytes) if e.bytes else None
            rec = {
                "flops": e.flops or None,
                "bytes_accessed": e.bytes or None,
                "arithmetic_intensity": intensity,
                "signature": e.signature,
                "source": e.source,
                "analyze_calls": e.analyze_calls,
                "error": e.error,
                "samples": e.count,
                "recent_seconds_mean": mean_s,
                "mfu": e.mfu,
                "bw_utilization": e.bw_util,
                "baseline_mfu": e.baseline_mfu,
                "mfu_vs_baseline": (e.mfu / e.baseline_mfu
                                    if e.mfu is not None and e.baseline_mfu
                                    else None),
                "roofline_verdict": (
                    None if intensity is None
                    else "compute_bound" if intensity >= ridge
                    else "memory_bound"),
                "devices": scales.get(fn, 1),
            }
            if fn in collectives:
                rec["collective_bytes_per_step"] = collectives[fn]
            if fn in compression:
                rec["grad_compression"] = compression[fn]
            fns[fn] = rec
        return {
            "enabled": cost_model_enabled(),
            "platform": _platform(),
            "peak_flops": peak,
            "hbm_bytes_per_second": hbm,
            "ridge_intensity": ridge,
            "fns": fns,
        }

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._collectives.clear()
            self._compression.clear()
            self._scales.clear()


# --------------------------------------------------------- process wiring
_global_model: Optional[CostModel] = None
_model_lock = threading.Lock()


def global_cost_model() -> CostModel:
    """THE process-wide cost model every built-in hook records into."""
    global _global_model
    if _global_model is None:
        with _model_lock:
            if _global_model is None:
                _global_model = CostModel()
    return _global_model


def reset_global_cost_model() -> CostModel:
    global _global_model
    with _model_lock:
        _global_model = CostModel()
    return _global_model


# ------------------------------------------------------------ hook helpers
def on_step(probe_fn: str, fn: str, seconds: float,
            lower_thunk: Callable[[], object]):
    """The one-line fit-loop hook: observe the step duration and (only
    when compile_watch counted a fresh trace of ``probe_fn``) re-account
    the entry point's cost. ``fn`` may differ from ``probe_fn`` when a
    wrapper renames the entry (ShardedTrainer.step)."""
    if not cost_model_enabled():
        return
    cm = global_cost_model()
    if cm.needs_account(fn, probe_fn):
        cm.account(fn, lower_thunk, probe_fn=probe_fn)
    cm.observe_time(fn, seconds)


def bucket_fn(model, target: int) -> str:
    """Per-serving-bucket entry name, e.g.
    ``MultiLayerNetwork._output_jit[b8]`` — bounded cardinality (the
    bucket set is log2(batch_limit)+1 per model kind)."""
    return f"{type(model).__name__}._output_jit[b{int(target)}]"


def maybe_account_bucket(model, target: int, x):
    """Account one serving shape-bucket executable (called AFTER the real
    dispatch compiled it, so the AOT lowering is a cache hit and the
    bucket-miss cause attribution is untouched). Keyed to the model's
    ``_output_jit`` compile count: a bucket retraced at a new dtype — or
    a different same-class model compiling its first bucket — refreshes
    every bucket's FLOPs on next use, one cache-hit lowering each. Two
    same-class models serving the SAME bucket shape still share one
    entry (the last to account wins); keeping the label cardinality
    bounded per model KIND is the documented tradeoff."""
    if not cost_model_enabled():
        return
    fn = bucket_fn(model, target)
    probe = f"{type(model).__name__}._output_jit"
    cm = global_cost_model()
    if not cm.needs_account(fn, probe_fn=probe):
        return
    lower = getattr(model, "_lower_output", None)
    if lower is None:
        return
    cm.account(fn, lambda: lower(x), probe_fn=probe)


def observe_bucket_time(model, target: int, seconds: float):
    """Feed one device-batch dispatch→complete duration into the bucket's
    MFU (under multi-in-flight dispatch this includes queueing, so
    serving MFU is a lower bound — see module doc)."""
    if not cost_model_enabled():
        return
    global_cost_model().observe_time(bucket_fn(model, target), seconds)


@on_registry_reset
def _clear_model():
    # gauge handles and compile-count anchors die with the registry
    if _global_model is not None:
        _global_model.clear()
