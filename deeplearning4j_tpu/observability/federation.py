"""Fleet observability plane: cross-process trace propagation, metrics
federation, fleet health rollup, and coordinated incident capture.

PRs 1/3/4/6 built a deep *per-process* observatory; the PR-11/15 fleet
(proxy + N workers + leader + shared store) was observable only one
process at a time.  This module is the fleet-level half (the cross-host
posture of Abadi et al. arXiv:1605.08695 §9 — aggregated metrics and
request-scoped tracing are what make a multi-process system debuggable):

- **Trace propagation**: the ``X-Dl4j-Trace-Id`` / ``X-Dl4j-Parent-Id``
  request headers carry the caller's :class:`TraceContext` across
  process hops.  :func:`inbound_context` joins an HTTP handler to it
  (or pre-allocates a fresh root id so EVERY response path can carry
  the header); :func:`inject_trace_headers` rewrites a buffered raw
  request so the proxy's upstream hop forwards its own context — one
  request is ONE trace id across proxy span, worker span ring, response
  header and SSE stream, including across an idempotent-replay
  failover.
- **Metrics federation**: :func:`render_fleet` scrapes every live
  worker's ``/metrics`` (worker set from the SharedStore registry),
  merges the Prometheus text streams with a ``worker`` label injected
  per series (cardinality bounded by a ``tenant_label``-style fold to
  ``__other__`` beyond ``DL4J_TPU_FLEET_WORKER_TOP_N``), and folds in
  the local process's own series.  A dead worker yields a partial
  result plus ``dl4j_fleet_scrape_errors_total{worker}`` — never a 500
  because one worker died.
- **Fleet health**: :class:`FleetHealth` grades the federated view
  through the existing :class:`SLOEngine` rule machinery — worst-worker
  latency quantile, fleet error rate, workers-alive vs registered,
  leader-term staleness — with per-worker attribution; the leader
  publishes the rollup into the shared store (:func:`publish_rollup`)
  so ``/debug/fleet`` shows one consistent verdict.
- **Incident capture**: a tripped flight recorder posts an incident
  record into the store (:func:`post_incident`, wired by
  :func:`install_incident_publisher`); every worker's
  :func:`incident_beat` sees the leader's fan-out and dumps its own
  bundle stamped with the SAME incident id (``reason="incident:<id>"``
  writes ``incident.json`` into the bundle), so one incident yields a
  fleet-wide set of bundles under the existing
  ``DL4J_TPU_POSTMORTEM_KEEP`` retention.

Kill switch: ``DL4J_TPU_FLEET_OBS=0`` (read live) restores the
pre-fleet-observability behavior byte-identically — inbound trace
headers are ignored, the fleet endpoints 404, the proxy opens no spans
and injects nothing, and the incident protocol is inert.
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from deeplearning4j_tpu.observability.registry import (_fmt_labels,
                                                       _fmt_value,
                                                       global_registry,
                                                       on_registry_reset)
from deeplearning4j_tpu.observability.slo import (FAILING, OK, SLOEngine,
                                                  SLORule, _grade,
                                                  global_slo_engine)
from deeplearning4j_tpu.observability.timeseries import (
    timeseries_payload, watchtower_enabled)
from deeplearning4j_tpu.observability.trace_store import (
    global_trace_store, trace_store_enabled)
from deeplearning4j_tpu.observability.tracing import (TraceContext,
                                                      current_context,
                                                      global_trace_sink)
from deeplearning4j_tpu.observability.watchtower import (
    PAGE, WARN, BurnRateDetector, ChangePointDetector, ThresholdDetector,
    Watchtower, global_watchtower, incident_cooldown_s)

__all__ = [
    "TRACE_HEADER", "PARENT_HEADER", "fleet_obs_enabled", "worker_top_n",
    "scrape_timeout_s", "health_interval_s", "parse_trace_id",
    "inbound_context", "trace_context_from_bytes", "inject_trace_headers",
    "parse_prometheus", "merge_prometheus", "fold_workers",
    "scrape_workers", "render_fleet", "FleetHealth", "publish_rollup",
    "post_incident", "incident_beat", "install_incident_publisher",
    "FleetAdminServer",
    "scrape_worker_traces", "fleet_recent_traces", "assemble_trace",
    "assembled_chrome_trace", "handle_trace_route", "PHASES",
    "FleetWatch", "fleet_default_detectors", "publish_alerts",
    "handle_alerts_route",
]

#: the cross-process trace headers (the front door already EMITTED the
#: first one; the fleet plane makes both flow inbound and proxy→worker)
TRACE_HEADER = "X-Dl4j-Trace-Id"
PARENT_HEADER = "X-Dl4j-Parent-Id"

#: worker heartbeat freshness window — ONE constant with
#: ``serving.shared_state.WORKER_TTL_S`` (spelled locally so this module
#: never imports the serving tree at import time: frontdoor imports us)
_WORKER_TTL_S = 3.0

#: shared-store incident list cap (newest kept) and the window inside
#: which a fanned-out incident still triggers peer captures — an
#: ancient record must not dump-storm a freshly joined worker
_INCIDENT_CAP = 16
_INCIDENT_FRESH_S = 600.0


def fleet_obs_enabled() -> bool:
    """``DL4J_TPU_FLEET_OBS`` kill switch, resolved LIVE per call —
    flipping it off restores pre-PR behavior without a restart."""
    return os.environ.get("DL4J_TPU_FLEET_OBS", "1") != "0"


def worker_top_n() -> int:
    """Workers beyond the first N (sorted ids) fold their ``worker``
    label to ``__other__`` — the qos ``tenant_label`` cardinality
    posture applied to the fleet dimension."""
    try:
        return max(1, int(os.environ.get("DL4J_TPU_FLEET_WORKER_TOP_N",
                                         16)))
    except (TypeError, ValueError):
        return 16


def scrape_timeout_s() -> float:
    """Per-worker ``/metrics`` scrape timeout: one wedged worker must
    cost one bounded wait, not the whole federation response."""
    try:
        return max(0.05, float(os.environ.get(
            "DL4J_TPU_FLEET_SCRAPE_TIMEOUT_S", 2.0)))
    except (TypeError, ValueError):
        return 2.0


def health_interval_s() -> float:
    """How often the LEADER re-evaluates and publishes the fleet health
    rollup into the shared store."""
    try:
        return max(0.05, float(os.environ.get(
            "DL4J_TPU_FLEET_HEALTH_INTERVAL_S", 5.0)))
    except (TypeError, ValueError):
        return 5.0


# ------------------------------------------------------ trace propagation

_ID_RE = re.compile(r"^[0-9a-f]{8,32}$")


def parse_trace_id(value) -> Optional[str]:
    """A caller-supplied trace/span id, validated: 8–32 lowercase hex
    chars (the W3C trace-context id alphabet).  Anything else — absent,
    empty, injection-shaped — is None: ids land in response headers and
    log lines, so they must never round-trip arbitrary bytes."""
    if not value:
        return None
    v = str(value).strip().lower()
    return v if _ID_RE.match(v) else None


def _fresh_id() -> str:
    return os.urandom(8).hex()


def inbound_context(headers) -> TraceContext:
    """The request's trace context from its inbound headers (any mapping
    with ``.get``, e.g. ``http.server``'s message object).  A valid
    caller id joins the caller's trace (parent optional); otherwise a
    fresh root id is pre-allocated so EVERY response path — including
    the pre-span early exits — can carry ``X-Dl4j-Trace-Id``."""
    tid = parse_trace_id(headers.get(TRACE_HEADER))
    if tid is None:
        return TraceContext(_fresh_id(), None)
    return TraceContext(tid, parse_trace_id(headers.get(PARENT_HEADER)))


def trace_context_from_bytes(hmap: Dict[bytes, bytes]) -> TraceContext:
    """Same as :func:`inbound_context` for the proxy's buffered request
    (lowercased ``bytes`` header map from ``_read_request``)."""
    def get(name: str):
        v = hmap.get(name.lower().encode("ascii"))
        return v.decode("ascii", "replace") if v is not None else None
    tid = parse_trace_id(get(TRACE_HEADER))
    if tid is None:
        return TraceContext(_fresh_id(), None)
    return TraceContext(tid, parse_trace_id(get(PARENT_HEADER)))


def inject_trace_headers(raw: bytes, trace_id: Optional[str],
                         parent_id: Optional[str]) -> bytes:
    """Rewrite a buffered raw HTTP request so the upstream hop carries
    OUR trace context: any existing trace/parent header lines are
    stripped (a client must not spoof past the proxy's span) and the
    proxy's are inserted.  The body is untouched; a head the splitter
    can't find (non-CRLF framing) passes through unmodified."""
    if trace_id is None:
        return raw
    head, sep, body = raw.partition(b"\r\n\r\n")
    if not sep:
        return raw
    drop = (TRACE_HEADER.lower().encode() + b":",
            PARENT_HEADER.lower().encode() + b":")
    lines = [ln for i, ln in enumerate(head.split(b"\r\n"))
             if i == 0 or not ln.lower().startswith(drop)]
    lines.append(TRACE_HEADER.encode() + b": " + trace_id.encode())
    if parent_id is not None:
        lines.append(PARENT_HEADER.encode() + b": " + parent_id.encode())
    return b"\r\n".join(lines) + sep + body


# ------------------------------------------------------ prometheus merge

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (v.replace(r"\n", "\n").replace(r"\"", '"')
            .replace("\\\\", "\\"))


def parse_prometheus(text: str) -> Dict[str, List[Tuple[Dict[str, str],
                                                        float]]]:
    """Minimal 0.0.4 text parse: ``{sample_name: [(labels, value)]}``.
    Comment/blank lines are skipped; unparseable sample lines are
    dropped (a half-written scrape must not fail the federation)."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        try:
            value = float(m.group(3))
        except ValueError:
            continue
        labels = {k: _unescape(v)
                  for k, v in _LABEL_RE.findall(m.group(2) or "")}
        out.setdefault(m.group(1), []).append((labels, value))
    return out


def fold_workers(worker_ids) -> Dict[str, str]:
    """Worker-label fold (the ``tenant_label`` posture): the first
    top-N sorted ids keep their own label, the rest share
    ``__other__`` — a 500-worker fleet cannot explode the label space
    of every federated series."""
    ids = sorted(worker_ids)
    keep = set(ids[:worker_top_n()])
    return {w: (w if w in keep else "__other__") for w in ids}


def merge_prometheus(parts) -> str:
    """Merge Prometheus text streams into one exposition.  ``parts`` is
    an iterable of ``(worker_label, text)`` — a ``worker`` label is
    injected into every sample that doesn't already carry one (a
    worker's own ``worker``-labeled series, e.g. the scrape-error
    counter, keeps its attribution), HELP/TYPE are first-wins per
    family, and samples that collide after the label fold sum."""
    fams: Dict[str, dict] = {}

    def fam_entry(name: str) -> dict:
        return fams.setdefault(name, {"help": None, "type": None,
                                      "samples": {}})

    for label, text in parts:
        fam = None
        for line in text.splitlines():
            if line.startswith("# HELP "):
                name, _, help_text = line[len("# HELP "):].partition(" ")
                ent = fam_entry(name)
                if ent["help"] is None:
                    ent["help"] = help_text
                fam = name
            elif line.startswith("# TYPE "):
                name, _, kind = line[len("# TYPE "):].partition(" ")
                ent = fam_entry(name)
                if ent["type"] is None:
                    ent["type"] = kind.strip()
                fam = name
            elif not line or line.startswith("#"):
                continue
            else:
                m = _SAMPLE_RE.match(line)
                if m is None:
                    continue
                try:
                    value = float(m.group(3))
                except ValueError:
                    continue
                sname = m.group(1)
                labels = {k: _unescape(v) for k, v
                          in _LABEL_RE.findall(m.group(2) or "")}
                if label is not None and "worker" not in labels:
                    labels["worker"] = str(label)
                # histogram/summary child samples (name_bucket/_sum/
                # _count) group under the family the TYPE line named
                fam_name = (fam if fam and sname.startswith(fam)
                            else sname)
                ent = fam_entry(fam_name)
                key = (sname, tuple(sorted(labels.items())))
                ent["samples"][key] = ent["samples"].get(key, 0.0) + value
    out: List[str] = []
    for fam_name in sorted(fams):
        ent = fams[fam_name]
        if not ent["samples"]:
            continue
        out.append(f"# HELP {fam_name} {ent['help'] or fam_name}")
        out.append(f"# TYPE {fam_name} {ent['type'] or 'untyped'}")
        for sname, litems in sorted(ent["samples"]):
            out.append(sname + _fmt_labels((), (), litems) + " "
                       + _fmt_value(ent["samples"][(sname, litems)]))
    return "\n".join(out) + "\n"


# -------------------------------------------------------------- scraping

_scrape_obs_cache: Optional[tuple] = None
_scrape_err_children: Dict[str, object] = {}


def _scrape_obs():
    global _scrape_obs_cache
    if _scrape_obs_cache is None:
        reg = global_registry()
        _scrape_obs_cache = (
            reg.counter("dl4j_fleet_scrape_errors_total",
                        "federation scrapes of a live-registered worker "
                        "that failed (dead/wedged worker — the merged "
                        "output is partial, never a 500)",
                        label_names=("worker",)),
            reg.histogram("dl4j_fleet_scrape_seconds",
                          "wall time of one worker /metrics scrape "
                          "during federation"))
    return _scrape_obs_cache


def _scrape_error(worker: str):
    child = _scrape_err_children.get(worker)
    if child is None:
        child = _scrape_err_children[worker] = _scrape_obs()[0].labels(
            worker=worker)
    return child


@on_registry_reset
def _drop_scrape_obs():
    global _scrape_obs_cache
    _scrape_obs_cache = None
    _scrape_err_children.clear()


def scrape_workers(store) -> Tuple[dict, Dict[str, str], Dict[str, str]]:
    """Scrape every live-registered worker's ``/metrics``: returns
    ``(store_doc, {worker: text}, {worker: error})``.  Liveness is the
    store heartbeat (the proxy's own freshness rule); an unreachable
    live worker lands in ``errors`` and bumps
    ``dl4j_fleet_scrape_errors_total{worker}`` — partial data is an
    answer, a dead worker is not an exception."""
    try:
        doc = store.read()
    except Exception as e:
        return {"error": repr(e)}, {}, {"__store__": repr(e)}
    now = time.time()
    timeout = scrape_timeout_s()
    texts: Dict[str, str] = {}
    errors: Dict[str, str] = {}
    for wid, rec in sorted((doc.get("workers") or {}).items()):
        if not isinstance(rec, dict) or not rec.get("port"):
            continue
        if now - float(rec.get("heartbeat", 0) or 0) > _WORKER_TTL_S:
            continue                       # expired: not live, not an error
        t0 = time.perf_counter()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{int(rec['port'])}/metrics",
                    timeout=timeout) as r:
                texts[wid] = r.read().decode("utf-8", "replace")
            _scrape_obs()[1].observe(time.perf_counter() - t0)
        except Exception as e:
            errors[wid] = repr(e)
            _scrape_error(wid).inc()
    return doc, texts, errors


def render_fleet(store, local_worker: str = "proxy",
                 registry=None) -> str:
    """The ``/metrics/fleet`` payload: every live worker's series with a
    ``worker`` label (fold-bounded cardinality), plus the LOCAL
    process's own series (the proxy's failover/circuit/queue counters,
    and the scrape-error counter naming any unreachable worker) under
    ``worker="<local_worker>"``."""
    _doc, texts, _errors = scrape_workers(store)
    fold = fold_workers(texts)
    parts = [(fold[w], texts[w]) for w in sorted(texts)]
    reg = registry if registry is not None else global_registry()
    parts.append((local_worker, reg.render_prometheus()))
    return merge_prometheus(parts)


# -------------------------------------------------------- trace assembly

#: waterfall phase decomposition: assembled span names → the request
#: phase they account to (the serving pipeline's queue→prefill→decode→
#: dispatch shape; names are lint-bounded by the span-names checker)
PHASES = {
    "queue_wait": ("queue_wait", "slot_wait"),
    "prefill": ("prefill",),
    "decode": ("decode_step",),
    "dispatch": ("inference_dispatch",),
}


def _fetch_worker_json(port: int, path: str,
                       timeout: float) -> Optional[dict]:
    """One worker debug-endpoint fetch; an HTTP 404 is a clean miss
    (the worker simply doesn't hold that trace) and returns None, any
    other failure raises for the caller's errors map."""
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{int(port)}{path}", timeout=timeout) as r:
            doc = json.loads(r.read().decode("utf-8", "replace"))
            return doc if isinstance(doc, dict) else None
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return None
        raise


def _live_worker_ports(doc) -> List[Tuple[str, int]]:
    now = time.time()
    out: List[Tuple[str, int]] = []
    for wid, rec in sorted((doc.get("workers") or {}).items()):
        if not isinstance(rec, dict) or not rec.get("port"):
            continue
        if now - float(rec.get("heartbeat", 0) or 0) > _WORKER_TTL_S:
            continue
        out.append((wid, int(rec["port"])))
    return out


def scrape_worker_traces(store, trace_id: str
                         ) -> Tuple[dict, Dict[str, dict],
                                    Dict[str, str]]:
    """Every live worker's LOCAL retained payload for ``trace_id`` (the
    ``?local=1`` form — fan-out must never recurse into another
    fan-out): ``(store_doc, {worker: payload}, {worker: error})``.
    Workers that don't hold the id are absent, not errors; a dead
    worker lands in ``errors`` exactly like a ``/metrics`` federation
    scrape — partial assembly is an answer."""
    try:
        doc = store.read()
    except Exception as e:
        return {"error": repr(e)}, {}, {"__store__": repr(e)}
    timeout = scrape_timeout_s()
    payloads: Dict[str, dict] = {}
    errors: Dict[str, str] = {}
    for wid, port in _live_worker_ports(doc):
        try:
            got = _fetch_worker_json(
                port, f"/debug/trace/{trace_id}?local=1", timeout)
            if got is not None:
                payloads[wid] = got
        except Exception as e:
            errors[wid] = repr(e)
            _scrape_error(wid).inc()
    return doc, payloads, errors


def fleet_recent_traces(store, local_worker: str = "proxy",
                        limit: int = 64) -> dict:
    """The fleet ``/debug/trace/recent`` payload: every live worker's
    retained-trace summaries (scraped ``?local=1``) merged with the
    local store's, each stamped with its holding worker, newest
    first."""
    try:
        doc = store.read()
    except Exception as e:
        doc, errors = {"error": repr(e)}, {"__store__": repr(e)}
        live = []
    else:
        errors = {}
        live = _live_worker_ports(doc)
    timeout = scrape_timeout_s()
    entries: List[dict] = []
    for wid, port in live:
        try:
            got = _fetch_worker_json(
                port, f"/debug/trace/recent?local=1&limit={int(limit)}",
                timeout)
        except Exception as e:
            errors[wid] = repr(e)
            _scrape_error(wid).inc()
            continue
        for t in ((got or {}).get("traces") or []):
            if isinstance(t, dict):
                entries.append({**t, "worker": wid})
    for t in global_trace_store().recent(limit=limit):
        entries.append({**t, "worker": local_worker})
    entries.sort(key=lambda t: -float(t.get("at", 0) or 0))
    return {"traces": entries[:max(1, int(limit))],
            "partial": bool(errors), "scrape_errors": errors}


def _assembled_depths(spans: List[dict]) -> Dict[str, int]:
    """Parent-chain depth across the ASSEMBLED span set (a worker span
    whose parent lives in the proxy nests under it; each record's local
    ``depth`` only knows its own process)."""
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    depths: Dict[str, int] = {}

    def depth_of(sid: str, hops: int = 0) -> int:
        if sid in depths:
            return depths[sid]
        if hops > 64:                      # cycle guard on hostile ids
            return 0
        s = by_id.get(sid)
        parent = s.get("parent_id") if s else None
        d = (depth_of(parent, hops + 1) + 1
             if parent and parent in by_id else 0)
        depths[sid] = d
        return d

    for s in spans:
        if s.get("span_id"):
            depth_of(s["span_id"])
    return depths


def assemble_trace(store, trace_id: str,
                   local_payload: Optional[dict] = None,
                   local_worker: str = "proxy") -> Optional[dict]:
    """Stitch one trace id's spans from every live worker (plus the
    local store's copy) into a single cross-worker waterfall: spans
    tagged with their holding worker, phase decomposition per
    :data:`PHASES`, per-span tenant attribution, and honest
    ``partial``/``scrape_errors`` when a worker couldn't answer.
    Returns None when NO process holds the id (the 404 case)."""
    _doc, payloads, errors = scrape_worker_traces(store, trace_id)
    if local_payload is not None:
        payloads = {**payloads, local_worker: local_payload}
    return _doc_from_payloads(trace_id, payloads, errors)


def _doc_from_payloads(trace_id: str, payloads: Dict[str, dict],
                       errors: Dict[str, str]) -> Optional[dict]:
    if not payloads:
        return None
    spans: List[dict] = []
    reasons: Dict[str, str] = {}
    for wid in sorted(payloads):
        p = payloads[wid]
        if p.get("reason"):
            reasons[wid] = p["reason"]
        for s in (p.get("spans") or []):
            if isinstance(s, dict):
                spans.append({**s, "worker": wid})
    if not spans:
        return None
    spans.sort(key=lambda s: float(s.get("ts_us", 0) or 0))
    depths = _assembled_depths(spans)
    ids = {s["span_id"] for s in spans if s.get("span_id")}
    roots = [s for s in spans
             if not s.get("parent_id") or s["parent_id"] not in ids]
    root = max(roots or spans,
               key=lambda s: float(s.get("dur_us", 0) or 0))
    t0 = float(spans[0].get("ts_us", 0) or 0)
    end = max(float(s.get("ts_us", 0) or 0)
              + float(s.get("dur_us", 0) or 0) for s in spans)
    phases = {
        phase: round(sum(float(s.get("dur_us", 0) or 0) for s in spans
                         if s.get("name") in names), 1)
        for phase, names in PHASES.items()}
    waterfall = [
        {"name": s.get("name"), "worker": s["worker"],
         "tenant": (s.get("attrs") or {}).get("tenant"),
         "offset_us": round(float(s.get("ts_us", 0) or 0) - t0, 1),
         "dur_us": round(float(s.get("dur_us", 0) or 0), 1),
         "depth": depths.get(s.get("span_id"), 0),
         "error": bool(s.get("error")
                       or (s.get("attrs") or {}).get("error_type"))}
        for s in spans]
    return {
        "trace_id": trace_id,
        "workers": sorted(payloads),
        "reasons": reasons,
        "partial": bool(errors),
        "scrape_errors": errors,
        "root": {"name": root.get("name"), "worker": root["worker"],
                 "error": bool(root.get("error")),
                 "error_type": (root.get("error_type")
                                or (root.get("attrs") or {})
                                .get("error_type")),
                 "attrs": root.get("attrs") or {}},
        "duration_us": round(end - t0, 1),
        "phases": phases,
        "n_spans": len(spans),
        "waterfall": waterfall,
        "spans": spans,
    }


def assembled_chrome_trace(doc: dict) -> List[dict]:
    """An assembled trace as Chrome trace events with per-worker
    namespacing (satellite fix): each worker gets its own integer
    ``pid`` (named via process_name metadata) so two workers' thread
    ids can't collide on one track, and flow-event ids are namespaced
    ``"<worker>:<span_id>"`` strings so concatenated exports from N
    processes can't alias each other's arrows.  Flow pairs are emitted
    for every parent→child edge that crosses a (worker, thread)
    boundary — including the proxy→worker hop one process's export
    could never draw."""
    spans = doc.get("spans") or []
    pid_of = {w: i + 1 for i, w in enumerate(sorted(doc.get("workers")
                                                    or []))}
    events: List[dict] = []
    for wid, pid in sorted(pid_of.items(), key=lambda kv: kv[1]):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": wid}})
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    for s in spans:
        pid = pid_of.get(s.get("worker"), 0)
        ev = {"name": s.get("name"), "ph": "X",
              "ts": s.get("ts_us"), "dur": s.get("dur_us"),
              "pid": pid, "tid": s.get("tid"), "cat": "host",
              "args": {**(s.get("attrs") or {}),
                       "trace_id": s.get("trace_id"),
                       "span_id": s.get("span_id"),
                       "parent_id": s.get("parent_id"),
                       "worker": s.get("worker")}}
        if s.get("error"):
            ev["args"]["error"] = True
            if s.get("error_type"):
                ev["args"]["error_type"] = s["error_type"]
        events.append(ev)
        parent = by_id.get(s.get("parent_id")) if s.get("parent_id") \
            else None
        if parent is None:
            continue
        if (parent.get("worker"), parent.get("tid")) == (s.get("worker"),
                                                         s.get("tid")):
            continue            # same-track nesting needs no arrow
        s_ts = min(float(parent.get("ts_us", 0) or 0),
                   float(s.get("ts_us", 0) or 0))
        flow_id = f"{s.get('worker')}:{s.get('span_id')}"
        events.append({"name": "handoff", "cat": "flow", "ph": "s",
                       "id": flow_id, "ts": s_ts,
                       "pid": pid_of.get(parent.get("worker"), 0),
                       "tid": parent.get("tid")})
        events.append({"name": "handoff", "cat": "flow", "ph": "f",
                       "bp": "e", "id": flow_id,
                       "ts": max(float(s.get("ts_us", 0) or 0), s_ts),
                       "pid": pid, "tid": s.get("tid")})
    return events


def handle_trace_route(path: str, query: Dict[str, list],
                       store=None, local_worker: str = "local",
                       fleet: bool = False) -> Tuple[int, object]:
    """Shared ``/debug/trace*`` routing for all three HTTP surfaces
    (front door, UIServer, proxy admin): ``(status, json_payload)``.

    - ``/debug/trace/recent`` — retained summaries with why-kept
      reasons; fleet surfaces fan out (``?local=1`` pins it local — the
      form fan-out itself requests, so scrapes can't recurse).
    - ``/debug/trace/<id>`` — the assembled cross-worker waterfall on
      fleet surfaces, the raw local payload with ``?local=1`` or on a
      plain worker; ``?format=chrome`` exports Perfetto-loadable
      events.  Unknown/invalid ids are a 404, never a 500.
    """
    q = query or {}
    local_only = (q.get("local", ["0"]) or ["0"])[0] == "1"
    as_fleet = (fleet and store is not None and not local_only
                and fleet_obs_enabled())
    chrome = (q.get("format", [""]) or [""])[0] == "chrome"
    st = global_trace_store()
    p = path.rstrip("/")
    if p in ("/debug/trace", "/debug/trace/recent"):
        try:
            limit = max(1, int((q.get("limit", ["64"]) or ["64"])[0]))
        except (TypeError, ValueError):
            limit = 64
        if as_fleet:
            return 200, fleet_recent_traces(store, local_worker, limit)
        return 200, {"worker": local_worker,
                     "traces": st.recent(limit=limit)}
    tid = (parse_trace_id(p[len("/debug/trace/"):])
           if p.startswith("/debug/trace/") else None)
    if tid is None:
        return 404, {"error": "NotFound", "path": path}
    local = st.get(tid)
    if local_only and not chrome:
        # the fan-out wire format: the RAW store payload (reason +
        # spans), exactly what scrape_worker_traces re-stitches
        if local is None:
            return 404, {"error": "NotFound", "trace_id": tid}
        return 200, {**local, "worker": local_worker}
    if as_fleet:
        doc = assemble_trace(store, tid, local_payload=local,
                             local_worker=local_worker)
    else:
        doc = _doc_from_payloads(tid, {local_worker: local} if local
                                 else {}, {})
    if doc is None:
        return 404, {"error": "NotFound", "trace_id": tid}
    if chrome:
        return 200, assembled_chrome_trace(doc)
    return 200, doc


# ---------------------------------------------------------- fleet health

class _FleetRule(SLORule):
    """Base for fleet rules: graded from the :class:`FleetHealth`
    snapshot (the federated scrape + store doc), not the local registry
    the engine passes — the whole point is the OTHER processes."""

    def __init__(self, name: str, description: str, fleet: "FleetHealth"):
        super().__init__(name, description)
        self._fleet = fleet


def _bucket_quantile(le_cum: Dict[float, float], q: float) -> float:
    """Prometheus-style histogram quantile over summed cumulative
    bucket counts: linear interpolation within the winning bucket; a
    quantile landing in the +Inf bucket answers the highest finite
    bound (the honest 'at least this much')."""
    bounds = sorted(le_cum)
    total = le_cum.get(float("inf"), 0.0)
    if total <= 0:
        return float("nan")
    target = q * total
    prev_bound, prev_cum = 0.0, 0.0
    for bound in bounds:
        cum = le_cum[bound]
        if cum >= target:
            if bound == float("inf"):
                finite = [b for b in bounds if b != float("inf")]
                return finite[-1] if finite else float("nan")
            if cum <= prev_cum:
                return bound
            frac = (target - prev_cum) / (cum - prev_cum)
            return prev_bound + frac * (bound - prev_bound)
        prev_bound, prev_cum = bound, cum
    return float("nan")


class _WorstWorkerLatencyRule(_FleetRule):
    """Worst worker wins (the LatencyQuantileRule posture lifted to the
    fleet): a drowning worker must not hide behind healthy peers."""

    def __init__(self, fleet, metric: str = "dl4j_http_latency_seconds",
                 quantile: float = 0.99, degraded: float = 1.0,
                 failing: float = 5.0, min_count: int = 16):
        super().__init__("fleet_worst_worker_p99",
                         f"worst worker p{int(quantile * 100)} of "
                         f"{metric} across the fleet", fleet)
        self.metric = metric
        self.quantile = quantile
        self.degraded = degraded
        self.failing = failing
        self.min_count = min_count

    def _evaluate(self, registry) -> dict:
        worst, worst_wid = None, None
        for wid, parsed in sorted(self._fleet.snap["workers"].items()):
            le_cum: Dict[float, float] = {}
            for labels, value in parsed.get(self.metric + "_bucket", ()):
                le = labels.get("le")
                if le is None:
                    continue
                try:
                    bound = float(le)
                except ValueError:
                    continue
                le_cum[bound] = le_cum.get(bound, 0.0) + value
            if le_cum.get(float("inf"), 0.0) < self.min_count:
                continue
            q = _bucket_quantile(le_cum, self.quantile)
            if q == q and (worst is None or q > worst):
                worst, worst_wid = q, wid
        if worst is None:
            return {"status": OK,
                    "detail": f"<{self.min_count} samples on every "
                              f"worker"}
        return {"status": _grade(worst, self.degraded, self.failing),
                "value": worst, "quantile": self.quantile,
                "worker": worst_wid,
                "detail": f"worker {worst_wid}: "
                          f"p{int(self.quantile * 100)}={worst:.4g}s",
                "degraded_above": self.degraded,
                "failing_above": self.failing}


class _FleetErrorRateRule(_FleetRule):
    """Fleet-wide 5xx fraction of ``dl4j_http_requests_total``, with
    the worst single worker named for attribution."""

    def __init__(self, fleet, metric: str = "dl4j_http_requests_total",
                 degraded: float = 0.02, failing: float = 0.10,
                 min_requests: int = 20):
        super().__init__("fleet_error_rate",
                         f"fleet-wide 5xx fraction of {metric}", fleet)
        self.metric = metric
        self.degraded = degraded
        self.failing = failing
        self.min_requests = min_requests

    def _evaluate(self, registry) -> dict:
        total = errors = 0.0
        per: Dict[str, float] = {}
        for wid, parsed in sorted(self._fleet.snap["workers"].items()):
            wt = we = 0.0
            for labels, value in parsed.get(self.metric, ()):
                wt += value
                if str(labels.get("code", "")).startswith("5"):
                    we += value
            total += wt
            errors += we
            if wt > 0:
                per[wid] = we / wt
        if total < self.min_requests:
            return {"status": OK, "requests": total,
                    "detail": f"<{self.min_requests} requests"}
        rate = errors / total
        worst = max(per, key=per.get) if per else None
        return {"status": _grade(rate, self.degraded, self.failing),
                "value": rate, "requests": total, "worker": worst,
                "detail": (f"worst worker {worst}: "
                           f"{per.get(worst, 0.0):.2%}" if worst
                           else "no per-worker data"),
                "degraded_above": self.degraded,
                "failing_above": self.failing}


class _WorkersAliveRule(_FleetRule):
    """Registered vs alive (store heartbeats) plus scrape reachability:
    zero alive with registrations is failing; any missing/unreachable
    worker is a page naming exactly who is gone."""

    def __init__(self, fleet):
        super().__init__("fleet_workers_alive",
                         "store-registered workers with fresh "
                         "heartbeats, all reachable for scrape", fleet)

    def _evaluate(self, registry) -> dict:
        doc = self._fleet.snap["doc"]
        workers = {w: r for w, r in (doc.get("workers") or {}).items()
                   if isinstance(r, dict)}
        if not workers:
            return {"status": OK, "detail": "no workers registered"}
        now = time.time()
        alive = sorted(
            w for w, r in workers.items()
            if now - float(r.get("heartbeat", 0) or 0) <= _WORKER_TTL_S)
        stale = sorted(set(workers) - set(alive))
        unreachable = sorted(set(self._fleet.snap["errors"]) - {"__store__"})
        missing = sorted(set(stale) | set(unreachable))
        if not alive:
            status = FAILING
        elif missing:
            status = "degraded"
        else:
            status = OK
        return {"status": status, "value": float(len(alive)),
                "registered": len(workers), "missing": missing,
                "detail": (f"missing workers: {', '.join(missing)}"
                           if missing
                           else f"{len(alive)}/{len(workers)} alive")}


class _LeaderStalenessRule(_FleetRule):
    """The leader record's holder must itself be alive: a stale leader
    heartbeat means stage transitions and rollups have no author."""

    def __init__(self, fleet):
        super().__init__("fleet_leader_staleness",
                         "the recorded leader's heartbeat freshness "
                         "(a fleet without a live leader cannot "
                         "advance rollouts or publish rollups)", fleet)

    def _evaluate(self, registry) -> dict:
        doc = self._fleet.snap["doc"]
        workers = doc.get("workers") or {}
        leader = doc.get("leader") or {}
        holder = leader.get("worker")
        if holder is None:
            if workers:
                return {"status": "degraded",
                        "detail": "workers registered but no leader "
                                  "recorded"}
            return {"status": OK, "detail": "no fleet"}
        rec = workers.get(holder) or {}
        age = time.time() - float(rec.get("heartbeat", 0) or 0)
        return {"status": _grade(age, _WORKER_TTL_S, 3 * _WORKER_TTL_S),
                "value": age, "worker": holder,
                "term": leader.get("term"),
                "detail": f"leader {holder} (term {leader.get('term')}) "
                          f"heartbeat {age:.1f}s old",
                "degraded_above": _WORKER_TTL_S,
                "failing_above": 3 * _WORKER_TTL_S}


class FleetHealth:
    """``/health/fleet`` — the whole fleet graded through the existing
    :class:`SLOEngine` machinery over the federated scrape.  Each
    ``evaluate()``/``alerts()`` re-scrapes (the answer is current, not
    last-beat), and every non-ok rule result names the worst worker."""

    def __init__(self, store, worker_id: str = "proxy"):
        self._store = store
        self.worker_id = worker_id
        self.snap: dict = {"workers": {}, "errors": {}, "doc": {},
                           "at": 0.0}
        self._engine = SLOEngine(rules=[
            _WorstWorkerLatencyRule(self),
            _FleetErrorRateRule(self),
            _WorkersAliveRule(self),
            _LeaderStalenessRule(self),
        ])

    def refresh(self) -> dict:
        doc, texts, errors = scrape_workers(self._store)
        self.snap = {
            "workers": {w: parse_prometheus(t) for w, t in texts.items()},
            "errors": errors, "doc": doc, "at": time.time()}
        return self.snap

    def evaluate(self) -> dict:
        self.refresh()
        report = self._engine.evaluate()
        report["by"] = self.worker_id
        report["workers_scraped"] = sorted(self.snap["workers"])
        report["scrape_errors"] = dict(self.snap["errors"])
        return report

    def alerts(self) -> dict:
        self.refresh()
        return self._engine.alerts()


def publish_rollup(store, worker_id: str, term, report: dict) -> None:
    """The LEADER's fleet-health verdict into the shared store — one
    consistent answer every worker's ``/debug/fleet`` shows, instead of
    N processes each grading a different scrape instant."""
    stamp = {
        "status": report.get("status"),
        "failing_rules": report.get("failing_rules", []),
        "degraded_rules": report.get("degraded_rules", []),
        "workers_scraped": report.get("workers_scraped", []),
        "scrape_errors": report.get("scrape_errors", {}),
        "by": worker_id,
        "term": term,
        "at": time.time(),
    }

    def mutate(doc):
        doc["fleet_health"] = stamp
    store.update(mutate)


# ----------------------------------------------------- fleet watchtower

def fleet_default_detectors(fleet: "FleetWatch"):
    """The LEADER's fleet-level watch rules, graded from the federated
    scrape (the :class:`_FleetRule` posture lifted to the watchtower):
    fleet-wide 5xx burn, worst-worker p99 step change, and a plain
    bound on missing workers."""
    return [
        BurnRateDetector(
            "fleet_error_burn", totals_fn=fleet.http_totals,
            description="fleet-wide 5xx error-budget burn over the "
                        "federated scrape (fast+slow window pair)",
            severity=PAGE),
        ChangePointDetector(
            "fleet_p99_shift", fleet.worst_p99, direction="up",
            description="worst-worker front-door p99 step change across "
                        "the fleet",
            severity=WARN),
        ThresholdDetector(
            "fleet_workers_missing", fleet.missing_workers,
            firing_above=0.5,
            description="registered workers heartbeat-stale or "
                        "unreachable for scrape",
            severity=WARN),
    ]


class FleetWatch:
    """Leader-side fleet watchtower: a second :class:`Watchtower` whose
    detectors read the :class:`FleetHealth` federated snapshot instead
    of the local registry.  ``beat()`` rides the leader's alert-publish
    cadence; a firing fleet page closes the detect→capture loop exactly
    like a local one (the leader's bundle dump posts the incident the
    fan-out protocol spreads)."""

    def __init__(self, health: FleetHealth):
        self.health = health
        self.tower = Watchtower(detectors=fleet_default_detectors(self),
                                scrape=False)

    # ------------------------------------------------- detector inputs
    def http_totals(self):
        """Fleet-cumulative ``(5xx, total)`` of the front-door request
        counter summed over every scraped worker."""
        errors = total = 0.0
        for _wid, parsed in sorted(
                (self.health.snap.get("workers") or {}).items()):
            for labels, value in parsed.get("dl4j_http_requests_total",
                                            ()):
                total += value
                if str(labels.get("code", "")).startswith("5"):
                    errors += value
        return errors, total

    def worst_p99(self, now) -> Optional[float]:
        worst = None
        for _wid, parsed in sorted(
                (self.health.snap.get("workers") or {}).items()):
            le_cum: Dict[float, float] = {}
            for labels, value in parsed.get(
                    "dl4j_http_latency_seconds_bucket", ()):
                le = labels.get("le")
                if le is None:
                    continue
                try:
                    bound = float(le)
                except ValueError:
                    continue
                le_cum[bound] = le_cum.get(bound, 0.0) + value
            if le_cum.get(float("inf"), 0.0) < 8:
                continue
            q = _bucket_quantile(le_cum, 0.99)
            if q == q and (worst is None or q > worst):
                worst = q
        return worst

    def missing_workers(self, now) -> float:
        doc = self.health.snap.get("doc") or {}
        workers = {w: r for w, r in (doc.get("workers") or {}).items()
                   if isinstance(r, dict)}
        stale = {w for w, r in workers.items()
                 if now - float(r.get("heartbeat", 0) or 0)
                 > _WORKER_TTL_S}
        unreachable = (set(self.health.snap.get("errors") or ())
                       - {"__store__"})
        return float(len(stale | (unreachable & set(workers))))

    # ------------------------------------------------------------ beat
    def beat(self, now: Optional[float] = None):
        """Refresh the federated scrape and run one forced evaluation
        (the caller owns the cadence); returns the transitions."""
        self.health.refresh()
        return self.tower.beat(now, force=True)

    def snapshot(self) -> dict:
        return self.tower.snapshot()


#: published per-worker alert records older than this are pruned from
#: the store doc — a long-dead worker must not haunt /debug/alerts
_ALERTS_STALE_S = 600.0


def publish_alerts(store, worker_id: str, term, local: dict,
                   fleet: Optional[dict] = None,
                   is_leader: bool = False) -> None:
    """This worker's alert snapshot — and, on the LEADER, the
    fleet-level snapshot — into the shared store's ``alerts`` doc, the
    rollup every surface's ``/debug/alerts`` shows."""
    at = time.time()
    mine = {"at": at, "state": "ok" if not local.get("firing")
            else "firing",
            "firing": local.get("firing") or [],
            "pending": local.get("pending") or [],
            "resolved": local.get("resolved") or []}

    def mutate(doc):
        alerts = doc.get("alerts")
        if not isinstance(alerts, dict):
            alerts = {}
        workers = alerts.get("workers")
        if not isinstance(workers, dict):
            workers = {}
        workers[worker_id] = mine
        alerts["workers"] = {
            w: r for w, r in workers.items()
            if isinstance(r, dict)
            and at - float(r.get("at", 0) or 0) <= _ALERTS_STALE_S}
        if is_leader and fleet is not None:
            alerts["fleet"] = {"at": at, "by": worker_id, "term": term,
                               "firing": fleet.get("firing") or [],
                               "pending": fleet.get("pending") or [],
                               "resolved": fleet.get("resolved") or []}
        doc["alerts"] = alerts
    store.update(mutate)


def handle_alerts_route(path: str, query: Dict[str, list],
                        store=None, local_worker: str = "local",
                        fleet: bool = False) -> Tuple[int, object]:
    """Shared ``/debug/alerts`` (and legacy ``/alerts``) routing for all
    three HTTP surfaces: ``(status, json_payload)``.

    The payload keeps the legacy SLO-engine keys (``status`` /
    ``active`` / ``history`` — old consumers of ``GET /alerts`` still
    parse) and adds the watchtower's lifecycle view; fleet surfaces add
    the store rollup (leader's fleet alerts + per-worker snapshots),
    the incident ledger, and an honest ``partial`` list naming live-
    registered workers whose alerts are unknown — never a 500 because
    a worker died.  With ``DL4J_TPU_WATCHTOWER=0`` the legacy path
    answers the pre-watchtower payload byte-identically and the new
    path 404s."""
    p = path.rstrip("/")
    if not watchtower_enabled():
        if p == "/alerts":
            return 200, global_slo_engine().alerts()
        return 404, {"error": "NotFound", "path": path}
    wt = global_watchtower()
    wt.beat()           # throttled internally — the answer is current
    payload = global_slo_engine().alerts()
    payload["worker"] = local_worker
    payload["watchtower"] = wt.snapshot()
    if not (fleet and store is not None and fleet_obs_enabled()):
        return 200, payload
    try:
        doc = store.read()
    # graftlint: disable=typed-errors — a torn store read degrades to
    # the local view; the alerts surface never 500s
    except Exception as e:
        payload["store_error"] = repr(e)
        doc = {}
    fleet_alerts = doc.get("alerts")
    if not isinstance(fleet_alerts, dict):
        fleet_alerts = {}
    workers = fleet_alerts.get("workers")
    payload["workers"] = workers if isinstance(workers, dict) else {}
    payload["fleet"] = fleet_alerts.get("fleet")
    now = time.time()
    partial = []
    for wid, rec in sorted((doc.get("workers") or {}).items()):
        if not isinstance(rec, dict):
            continue
        if now - float(rec.get("heartbeat", 0) or 0) > _WORKER_TTL_S:
            partial.append(wid)          # dead: its alerts are unknown
        elif wid not in payload["workers"]:
            partial.append(wid)          # live but not yet published
    payload["partial"] = partial
    payload["incidents"] = [i for i in (doc.get("incidents") or [])
                            if isinstance(i, dict)]
    return 200, payload


# ------------------------------------------------------ incident capture

def post_incident(store, worker_id: str, reason: str,
                  bundle: Optional[str],
                  trace_id: Optional[str] = None,
                  trace_ids: Optional[List[str]] = None) -> str:
    """Record a tripped flight recorder in the shared store: the record
    carries the trace id of the request that was live when it tripped
    (plus any watchtower-pinned evidence ids), the originating worker's
    bundle name, and a fresh incident id the leader will fan out so
    every peer captures under the SAME id.

    Watchtower dedup: two ``alert:<rule>`` incidents posted inside the
    alert cooldown window coalesce onto ONE incident id — two detectors
    paging on the same outage must yield one fleet-wide capture, not
    two dump storms."""
    inc_id = os.urandom(6).hex()
    name = os.path.basename(bundle) if bundle else None
    evidence = [t for t in (trace_ids or ()) if t]
    rec = {"id": inc_id, "worker": worker_id, "reason": str(reason),
           "bundle": name, "trace_id": trace_id,
           "trace_ids": evidence, "at": time.time(),
           "fanned_out": False,
           "captured": ({worker_id: name} if name else {})}
    coalesce = str(reason).startswith("alert:")
    out = {"id": inc_id}

    def mutate(doc):
        incidents = [i for i in (doc.get("incidents") or [])
                     if isinstance(i, dict)]
        if coalesce:
            window = incident_cooldown_s()
            now = time.time()
            for i in reversed(incidents):
                if (str(i.get("reason", "")).startswith("alert:")
                        and now - float(i.get("at", 0) or 0) <= window):
                    # same outage: fold this page onto the open incident
                    if name:
                        captured = dict(i.get("captured") or {})
                        captured.setdefault(worker_id, name)
                        i["captured"] = captured
                    merged = list(i.get("trace_ids") or [])
                    merged.extend(t for t in evidence
                                  if t not in merged)
                    i["trace_ids"] = merged[:32]
                    also = list(i.get("coalesced") or [])
                    if str(reason) != i.get("reason") \
                            and str(reason) not in also:
                        also.append(str(reason))
                        i["coalesced"] = also
                    out["id"] = i["id"]
                    doc["incidents"] = incidents[-_INCIDENT_CAP:]
                    return
        incidents.append(rec)
        out["id"] = inc_id
        doc["incidents"] = incidents[-_INCIDENT_CAP:]
    store.update(mutate)
    return out["id"]


def incident_beat(store, worker_id: str, is_leader: bool,
                  recorder=None) -> List[str]:
    """One beat of the coordinated-capture protocol (called from every
    worker's sync loop): the leader marks fresh incidents fanned-out;
    every worker that sees a fanned incident it hasn't captured dumps
    its OWN bundle with ``reason="incident:<id>"`` (stamping
    ``incident.json``) and records the bundle name in the incident's
    ``captured`` map.  Returns the bundle paths dumped this beat."""
    if not fleet_obs_enabled():
        return []
    doc = store.read()
    incidents = [i for i in (doc.get("incidents") or [])
                 if isinstance(i, dict)]
    if not incidents:
        return []
    if is_leader and any(not i.get("fanned_out") for i in incidents):
        def fan(d):
            for i in (d.get("incidents") or []):
                if isinstance(i, dict) and not i.get("fanned_out"):
                    i["fanned_out"] = True
        doc = store.update(fan)
        incidents = [i for i in (doc.get("incidents") or [])
                     if isinstance(i, dict)]
    now = time.time()
    todo = [i for i in incidents
            if i.get("fanned_out") and i.get("id")
            and worker_id not in (i.get("captured") or {})
            and now - float(i.get("at", 0) or 0) <= _INCIDENT_FRESH_S]
    if not todo:
        return []
    if recorder is None:
        from deeplearning4j_tpu.observability.flight_recorder import (
            global_flight_recorder)
        recorder = global_flight_recorder()
    dumped: List[str] = []
    for inc in todo:
        if trace_store_enabled():
            # the originating request's trace + everything completing
            # around the incident are evidence on THIS worker too
            st = global_trace_store()
            st.pin(parse_trace_id(inc.get("trace_id")))
            st.open_incident_window()
        # dump OUTSIDE any store transaction (bundles take real time);
        # the publisher hook skips incident-reason dumps, so the peer
        # capture can never re-post and ping-pong the fleet
        bundle = recorder.dump(f"incident:{inc['id']}")
        dumped.append(bundle)
        name = os.path.basename(bundle)

        def mark(d, _id=inc["id"], _name=name):
            for i in (d.get("incidents") or []):
                if isinstance(i, dict) and i.get("id") == _id:
                    captured = dict(i.get("captured") or {})
                    captured[worker_id] = _name
                    i["captured"] = captured
        store.update(mark)
    return dumped


def install_incident_publisher(store, worker_id: str) -> None:
    """Wire the flight recorder's dump hook to :func:`post_incident`:
    any non-incident-reason bundle on this worker becomes a shared
    incident record the leader fans out.  Live kill switch: with
    ``DL4J_TPU_FLEET_OBS=0`` the hook is inert."""
    from deeplearning4j_tpu.observability import flight_recorder as _fr

    def _publish(reason: str, bundle: str) -> None:
        if not fleet_obs_enabled():
            return
        if str(reason).startswith("incident"):
            return                       # peer capture: never re-post
        ctx = current_context()
        trace_ids = None
        if ctx is not None and trace_store_enabled():
            # the live request's trace is evidence: eviction-exempt,
            # and everything completing around the trip is kept too
            st = global_trace_store()
            st.pin(parse_trace_id(ctx.trace_id))
            st.open_incident_window()
        if trace_store_enabled() and str(reason).startswith("alert:"):
            # a watchtower page has no live request context — its
            # evidence is the offending traces it pinned before dumping
            trace_ids = global_trace_store().pinned_ids()[-8:]
        try:
            post_incident(store, worker_id, reason, bundle,
                          trace_id=ctx.trace_id if ctx else None,
                          trace_ids=trace_ids)
        except Exception:
            pass        # the store being down must never mask the dump
    _fr.set_incident_publisher(_publish)

    def _assemble(tid: str) -> Optional[dict]:
        # fleet-wide assembly for the bundle's traces.json: with the
        # fleet plane off (or a single process) the recorder falls back
        # to the local store's payload
        if not (fleet_obs_enabled() and trace_store_enabled()):
            return None
        local = global_trace_store().get(tid)
        return assemble_trace(store, tid, local_payload=local,
                              local_worker=worker_id)
    _fr.set_trace_assembler(_assemble)


# ------------------------------------------------------ proxy admin port

class FleetAdminServer:
    """The proxy's observability surface (satellite: the proxy exposed
    no metrics at all): plain ``/metrics`` for its own registry,
    ``/metrics/fleet`` / ``/health/fleet`` / ``/alerts/fleet`` for the
    federated view, and ``/debug/proxy`` (failover/breaker snapshot +
    recent ``proxy_request`` spans).  Same dependency-free
    ``ThreadingHTTPServer`` pattern as the front door."""

    def __init__(self, store, host: Optional[str] = None, port: int = 0,
                 local_worker: str = "proxy",
                 debug_extra: Optional[Callable[[], dict]] = None):
        self.store = store
        self.local_worker = local_worker
        self._extra = debug_extra
        self.health = FleetHealth(store, worker_id=local_worker)
        srv = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, payload: dict):
                self._send(code,
                           json.dumps(payload, default=str).encode(),
                           "application/json")

            def do_GET(self):
                path = urlparse(self.path).path
                try:
                    if path == "/metrics":
                        self._send(
                            200,
                            global_registry().render_prometheus().encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/metrics/fleet":
                        self._send(
                            200,
                            render_fleet(srv.store,
                                         srv.local_worker).encode(),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/health/fleet":
                        report = srv.health.evaluate()
                        self._json(
                            503 if report["status"] == FAILING else 200,
                            report)
                    elif path == "/alerts/fleet":
                        self._json(200, srv.health.alerts())
                    elif (path == "/debug/alerts"
                            and watchtower_enabled()):
                        q = parse_qs(urlparse(self.path).query)
                        code, payload = handle_alerts_route(
                            path, q, srv.store, srv.local_worker,
                            fleet=True)
                        self._json(code, payload)
                    elif (path == "/debug/timeseries"
                            and watchtower_enabled()):
                        q = parse_qs(urlparse(self.path).query)
                        self._json(200, timeseries_payload(
                            q, local_worker=srv.local_worker))
                    elif path == "/debug/proxy":
                        self._json(200, srv.debug_snapshot())
                    elif (path.startswith("/debug/trace")
                            and trace_store_enabled()):
                        q = parse_qs(urlparse(self.path).query)
                        code, payload = handle_trace_route(
                            path, q, srv.store, srv.local_worker,
                            fleet=True)
                        self._json(code, payload)
                    else:
                        self._json(404, {"error": "NotFound",
                                         "path": path})
                except (BrokenPipeError, ConnectionResetError):
                    pass
                except Exception as e:
                    # never a 500-with-traceback page: the admin port is
                    # scraped by machines
                    try:
                        self._json(500, {"error": repr(e)})
                    except OSError:
                        pass

        if host is None:
            from deeplearning4j_tpu.ui.server import default_bind_host
            host = default_bind_host()
        self._httpd = ThreadingHTTPServer((host, int(port)), Handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FleetAdminServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="dl4j-fleet-admin")
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()

    def get_address(self) -> str:
        host = self.host or "127.0.0.1"
        if host == "0.0.0.0":
            host = "127.0.0.1"
        return f"http://{host}:{self.port}"

    def debug_snapshot(self) -> dict:
        extra: dict = {}
        if self._extra is not None:
            try:
                extra = dict(self._extra() or {})
            except Exception as e:
                extra = {"error": repr(e)}
        spans = [
            {"trace_id": r.trace_id, "span_id": r.span_id,
             "dur_us": r.dur_us, "error": r.error,
             "attrs": dict(r.attrs or {})}
            for r in global_trace_sink().spans()
            if r.name == "proxy_request"][-32:]
        return {"worker": self.local_worker, "proxy": extra,
                "recent_proxy_spans": spans}
