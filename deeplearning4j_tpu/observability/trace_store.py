"""Trace intelligence: a bounded-bytes per-process store of *completed*
traces with head sampling plus tail-based retention.

PR 16 carried one trace id across the whole fleet, but the spans behind
that id still died in per-process :class:`TraceSink` ring buffers — by
the time a p99 or failed request was noticed, its trace had usually been
overwritten.  This store sits BEHIND the span ring (the ring stays the
raw recent-everything view): every span recorded into the *global* sink
is also fed here, grouped by ``trace_id``, and when a trace completes
(its last open span closes) a keep/discard decision runs:

1. **error** — the trace's root span ended in an exception, a typed shed
   / deadline outcome, or an HTTP error status (the front door stamps
   ``error_type``/``status`` attrs on its root span).
2. **latency_tail** — the root's duration exceeds a rolling per-endpoint
   quantile threshold (``DL4J_TPU_TRACE_TAIL_Q``, default p95 over the
   endpoint's recent window) — tail-based sampling: the traces worth
   keeping are exactly the ones the head sampler would have missed.
3. **incident** — the trace id was pinned (flight-recorder incident
   protocol) or the trace completed inside an active incident window.
4. **head_sample** — a uniform coin at ``DL4J_TPU_TRACE_SAMPLE`` keeps a
   bounded baseline of boring traces for comparison.

Retained traces are indexed by id with their why-kept reason
(``dl4j_trace_retained_total{reason}`` / ``dl4j_trace_discarded_total``)
inside a bytes budget (``DL4J_TPU_TRACE_STORE_BYTES``): oldest
unpinned traces evict first, and the store-bytes gauges make the budget
scrapeable.  ``federation.py`` assembles any retained id fleet-wide
(``GET /debug/trace/<id>``) into one cross-worker waterfall.

Kill switch: ``DL4J_TPU_TRACE_STORE=0`` (read live per call) restores
byte-identical pre-store behavior — no feeds, no instruments, no debug
endpoints.  The store only sees spans at all when tracing is on
(``DL4J_TPU_METRICS`` / ``DL4J_TPU_TRACE``).
"""
from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.observability.registry import (global_registry,
                                                       on_registry_reset)

#: default bytes budget for retained traces (~8 MiB ≈ thousands of
#: request-sized traces; a week-long server cannot OOM the host keeping
#: its own postmortems)
DEFAULT_BUDGET_BYTES = 8 << 20

#: default head-sampling probability for traces no tail rule kept
DEFAULT_SAMPLE = 0.01

#: default rolling-quantile threshold for the latency-tail rule
DEFAULT_TAIL_QUANTILE = 0.95

#: per-endpoint rolling window length and the minimum samples before the
#: tail rule activates (an empty window has no p95 to exceed)
_TAIL_WINDOW = 128
_TAIL_MIN_SAMPLES = 16

#: bounded in-progress state: open traces beyond this evict oldest-first
#: (a leaked trace context must not grow the pending map forever), and a
#: single trace buffers at most this many spans (a fit loop's thousands
#: of nested spans truncate, keeping the root + earliest structure)
_MAX_PENDING = 512
_MAX_SPANS_PER_TRACE = 256

#: incident pins are a tiny set — one per coordinated capture, not one
#: per request
_MAX_PINS = 32

#: bounded hook queue: span hooks append here (one lock-free deque
#: append on the hot path) and a daemon drainer runs the retention
#: machinery off the request's critical path; overflow drops oldest
_QUEUE_MAX = 8192

#: drainer poll interval — also the worst-case retention-decision lag
#: (queries drain synchronously, so reads never see it)
_DRAIN_INTERVAL_S = 0.05

#: default incident window: traces completing this long after an
#: incident trips are kept (the requests AROUND a death explain it)
INCIDENT_WINDOW_S = 30.0

#: root-span names whose error/latency decide retention for serving
#: traffic; attrs stamped by the front door / proxy ride on these
_TYPED_ERROR_OUTCOMES = ("reset", "no_backend")


# The hooks run on EVERY span open/close, and os.environ's Mapping +
# key-encode machinery is a measured ~2.5us per read — a third of the
# whole hook budget.  os.environ._data is the live dict the Mapping
# mutates (setenv/monkeypatch write through to it), so reading it with a
# precomputed byte key is exactly as live at plain-dict speed.  Parses
# are cached keyed on the RAW value, so flipping a knob mid-process
# still takes effect on the very next span.
try:
    _ENV_DATA = os.environ._data          # CPython; keys are fsencoded
    _K_STORE = os.fsencode("DL4J_TPU_TRACE_STORE")
    _K_SAMPLE = os.fsencode("DL4J_TPU_TRACE_SAMPLE")
    _K_TAIL_Q = os.fsencode("DL4J_TPU_TRACE_TAIL_Q")
    _K_BYTES = os.fsencode("DL4J_TPU_TRACE_STORE_BYTES")
except AttributeError:                    # non-CPython fallback
    _ENV_DATA = None


def _raw_knob(key_bytes, name: str):
    if _ENV_DATA is not None:
        v = _ENV_DATA.get(key_bytes)
        return None if v is None else os.fsdecode(v)
    return os.environ.get(name)


def trace_store_enabled() -> bool:
    """``DL4J_TPU_TRACE_STORE`` kill switch, resolved LIVE per call —
    with it off the span-close hook is inert and behavior is
    byte-identical to the pre-store code."""
    if _ENV_DATA is not None:
        return _ENV_DATA.get(_K_STORE, b"1") != b"0"
    return os.environ.get("DL4J_TPU_TRACE_STORE", "1") != "0"


_sample_cache = (None, DEFAULT_SAMPLE)
_tail_q_cache = (None, DEFAULT_TAIL_QUANTILE)
_budget_cache = (None, DEFAULT_BUDGET_BYTES)


def sample_rate() -> float:
    """``DL4J_TPU_TRACE_SAMPLE`` — head-sampling probability in [0, 1]
    for traces no tail rule retained."""
    global _sample_cache
    raw = _raw_knob(_K_SAMPLE, "DL4J_TPU_TRACE_SAMPLE")
    if raw == _sample_cache[0]:
        return _sample_cache[1]
    try:
        v = min(1.0, max(0.0, float(raw)))
    except (TypeError, ValueError):
        v = DEFAULT_SAMPLE
    _sample_cache = (raw, v)
    return v


def tail_quantile() -> float:
    """``DL4J_TPU_TRACE_TAIL_Q`` — the rolling per-endpoint latency
    quantile a root must exceed to be tail-retained."""
    global _tail_q_cache
    raw = _raw_knob(_K_TAIL_Q, "DL4J_TPU_TRACE_TAIL_Q")
    if raw == _tail_q_cache[0]:
        return _tail_q_cache[1]
    try:
        v = min(0.999, max(0.5, float(raw)))
    except (TypeError, ValueError):
        v = DEFAULT_TAIL_QUANTILE
    _tail_q_cache = (raw, v)
    return v


def budget_bytes() -> int:
    """``DL4J_TPU_TRACE_STORE_BYTES`` — the retained-trace bytes budget
    (estimated span bytes; oldest unpinned traces evict past it)."""
    global _budget_cache
    raw = _raw_knob(_K_BYTES, "DL4J_TPU_TRACE_STORE_BYTES")
    if raw == _budget_cache[0]:
        return _budget_cache[1]
    try:
        v = max(64 << 10, int(raw))
    except (TypeError, ValueError):
        v = DEFAULT_BUDGET_BYTES
    _budget_cache = (raw, v)
    return v


# lazily-bound instruments (the tracing.py `_ring_obs` posture: no
# registry work on import, registry-reset safe)
_obs_cache: Optional[tuple] = None
_retained_children: Dict[str, Any] = {}


def _obs():
    global _obs_cache
    if _obs_cache is None:
        reg = global_registry()
        _obs_cache = (
            reg.counter("dl4j_trace_retained_total",
                        "completed traces kept by the trace store, by "
                        "why-kept reason (error / latency_tail / "
                        "incident / head_sample)",
                        label_names=("reason",)),
            reg.counter("dl4j_trace_discarded_total",
                        "completed traces the retention rules dropped "
                        "(boring and head-unsampled)"),
            reg.counter("dl4j_trace_store_evicted_total",
                        "retained traces evicted oldest-first to stay "
                        "inside the bytes budget"),
            reg.gauge("dl4j_trace_store_bytes",
                      "estimated bytes of retained trace spans "
                      "currently held by the trace store"),
            reg.gauge("dl4j_trace_store_budget_bytes",
                      "the trace store's bytes budget "
                      "(DL4J_TPU_TRACE_STORE_BYTES)"),
            reg.gauge("dl4j_trace_store_traces",
                      "retained traces currently held by the trace "
                      "store"))
    return _obs_cache


def _retained_counter(reason: str):
    child = _retained_children.get(reason)
    if child is None:
        child = _retained_children[reason] = _obs()[0].labels(reason=reason)
    return child


@on_registry_reset
def _drop_store_obs():
    global _obs_cache
    _obs_cache = None
    _retained_children.clear()


def _span_dict(rec) -> Dict[str, Any]:
    """A SpanRecord as the JSON shape the debug endpoints and fleet
    assembly ship (attrs coerced to scalars the same way the Chrome
    export does)."""
    attrs = {}
    if rec.attrs:
        attrs = {k: (v if isinstance(v, (int, float, bool, str))
                     or v is None else str(v))
                 for k, v in rec.attrs.items()}
    return {"name": rec.name, "ts_us": rec.ts_us, "dur_us": rec.dur_us,
            "tid": rec.tid, "depth": rec.depth, "attrs": attrs,
            "trace_id": rec.trace_id, "span_id": rec.span_id,
            "parent_id": rec.parent_id, "error": bool(rec.error),
            "error_type": rec.error_type}


def _est_bytes(span: Dict[str, Any]) -> int:
    """Cheap per-span byte estimate for the budget — close enough to
    the JSON size without serializing on the span-close hot path."""
    n = 120 + len(span["name"] or "")
    for k, v in (span["attrs"] or {}).items():
        n += len(str(k)) + len(str(v)) + 8
    return n


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("inf")
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


class _Pending:
    """One in-flight trace: spans fed so far + the count of still-open
    ``span()`` blocks (the close of the last one completes the trace)."""

    __slots__ = ("spans", "open_count", "started", "truncated")

    def __init__(self):
        self.spans: List[Any] = []      # raw SpanRecords until decision
        self.open_count = 0
        self.started = time.monotonic()
        self.truncated = False


class TraceStore:
    """See module doc.  One process-wide instance via
    :func:`global_trace_store`; tests construct their own."""

    def __init__(self, budget: Optional[int] = None):
        self._budget_override = budget
        self._lock = threading.Lock()
        self._pending: "OrderedDict[str, _Pending]" = OrderedDict()
        self._retained: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._bytes = 0
        self._tail: Dict[str, deque] = {}
        # per-endpoint cached tail threshold: (threshold, appends since
        # recompute) — re-sorting the 128-sample window on EVERY span
        # close is the measured hot spot; a threshold ≤8 samples stale
        # is the same tail, 1/8th the sorts
        self._tail_thresh: Dict[str, list] = {}
        self._pins: "OrderedDict[str, bool]" = OrderedDict()
        self._incident_until = 0.0
        self._rng = random.Random()
        # async hook queue (GIL economics: a span close on the batcher
        # thread sits on every batched request's handoff path, and ANY
        # locked Python work there was measured at ~17us wall under
        # contention — a deque append is the whole hot-path cost)
        self._queue: deque = deque(maxlen=_QUEUE_MAX)
        self._drain_lock = threading.Lock()
        self._drainer: Optional[threading.Thread] = None
        # decision counters mirrored locally so snapshot()/tests don't
        # need a registry scrape
        self.retained_count = 0
        self.discarded_count = 0
        self.evicted_count = 0

    # ----------------------------------------------------- async hook path
    def enqueue_open(self, trace_id: Optional[str]):
        """Hot-path half of :meth:`note_open`: one deque append; the
        drainer (or the next query) does the locked work."""
        if trace_id:
            self._queue.append((None, trace_id))
            if self._drainer is None:
                self._start_drainer()

    def enqueue_close(self, rec, span_close: bool = True):
        """Hot-path half of :meth:`feed`."""
        if rec.trace_id:
            self._queue.append((rec, span_close))
            if self._drainer is None:
                self._start_drainer()

    def _start_drainer(self):
        with self._drain_lock:
            if self._drainer is not None:
                return
            t = threading.Thread(target=self._drain_loop,
                                 name="dl4j-trace-store-drain", daemon=True)
            self._drainer = t
            t.start()

    def _drain_loop(self):
        while True:
            time.sleep(_DRAIN_INTERVAL_S)
            try:
                self.drain()
            except Exception:
                pass            # the store must never kill its drainer

    def drain(self):
        """Apply every queued hook event now (queries call this, so a
        read is always coherent with the spans closed before it).
        Serialized (two concurrent drainers would interleave pops and
        apply a close before its own open) and batched: one store-lock
        acquisition per pass, not per event — on a GIL-bound box the
        store's total bytecode IS its overhead, so per-event locking
        was the next-biggest line item after the hooks themselves."""
        q = self._queue
        with self._drain_lock:
            batch = []
            while q:
                try:
                    batch.append(q.popleft())
                except IndexError:
                    break
            if not batch:
                return
            publishes = []
            with self._lock:
                for rec, arg in batch:
                    if rec is None:
                        self._note_open_locked(arg)
                    else:
                        pub = self._feed_locked(rec, arg)
                        if pub:
                            publishes.append(pub)
        for pub in publishes:
            self._flush(pub)

    # ------------------------------------------------------------- feeding
    def note_open(self, trace_id: Optional[str]):
        """A ``span()`` block opened under ``trace_id`` (global sink):
        the trace cannot complete until this block's close is fed."""
        if not trace_id:
            return
        with self._lock:
            self._note_open_locked(trace_id)

    def _note_open_locked(self, trace_id: str):
        if trace_id in self._retained:
            return
        p = self._pending.get(trace_id)
        if p is None:
            p = self._ensure_pending_locked(trace_id)
        p.open_count += 1

    def _ensure_pending_locked(self, trace_id: str) -> _Pending:
        p = self._pending[trace_id] = _Pending()
        self._pending.move_to_end(trace_id)
        # bounded in-progress state: a leaked context (thread died with
        # the span open) is discarded oldest-first, never accumulated
        while len(self._pending) > _MAX_PENDING:
            self._pending.popitem(last=False)
            # counted locally only — the registry counter flushes on the
            # next completed-trace decision (no instrument work under a
            # hook that runs on every span open)
            self.discarded_count += 1
        return p

    def feed(self, rec, span_close: bool = True):
        """One completed span record (from the global sink).
        ``span_close`` is True for ``Span.__exit__`` records (they
        balance a :meth:`note_open`), False for externally-timed
        :func:`record_span` records."""
        tid = rec.trace_id
        if not tid:
            return
        with self._lock:
            publish = self._feed_locked(rec, span_close)
        if publish:
            self._flush(publish)

    def _feed_locked(self, rec, span_close: bool) -> Optional[dict]:
        tid = rec.trace_id
        entry = self._retained.get(tid)
        if entry is not None:
            # late span for an already-retained trace (a queue
            # consumer finishing after the root closed): append it
            if len(entry["spans"]) < _MAX_SPANS_PER_TRACE:
                span = _span_dict(rec)
                entry["spans"].append(span)
                entry["spans"].sort(key=lambda s: s["ts_us"])
                grew = _est_bytes(span)
                entry["bytes"] += grew
                self._bytes += grew
                return self._evict_locked()
            entry["truncated"] = True
            return None
        p = self._pending.get(tid)
        if p is None:
            if not span_close:
                # orphan externally-timed record (a phase marker under
                # a fresh id, no span() block to join): a one-span
                # "trace" is never an assemblable waterfall, and
                # finalizing one per batch on the batcher thread sat on
                # every request's handoff critical path — drop it
                return None
            p = self._ensure_pending_locked(tid)
        # raw SpanRecords until the keep/discard decision — the
        # common discard path never pays per-span dict building
        if len(p.spans) < _MAX_SPANS_PER_TRACE:
            p.spans.append(rec)
        else:
            p.truncated = True
        if span_close and p.open_count > 0:
            p.open_count -= 1
        if p.open_count <= 0:
            del self._pending[tid]
            return self._finalize_locked(tid, p)
        return None

    # ----------------------------------------------------------- retention
    def _root_of(self, recs) -> Any:
        """The trace's root SpanRecord: no parent, or a parent that is
        not a local span (a joined fleet trace's proxy parent)."""
        ids = {r.span_id for r in recs if r.span_id}
        roots = [r for r in recs
                 if not r.parent_id or r.parent_id not in ids]
        pool = roots or recs
        return max(pool, key=lambda r: (r.dur_us, -r.ts_us))

    @staticmethod
    def _root_errored(root: Dict[str, Any]) -> bool:
        if root["error"] or root["error_type"]:
            return True
        attrs = root["attrs"] or {}
        if attrs.get("error_type"):
            return True             # front door: typed shed/deadline/4xx
        try:
            if int(attrs.get("status", 200)) >= 400:
                return True
        except (TypeError, ValueError):
            pass
        return attrs.get("outcome") in _TYPED_ERROR_OUTCOMES  # proxy span

    def _endpoint_key(self, root: Dict[str, Any]) -> str:
        route = (root["attrs"] or {}).get("route")
        return f"{root['name']}:{route}" if route else root["name"]

    def _finalize_locked(self, tid: str, p: _Pending) -> Optional[dict]:
        """The keep/discard decision for one completed trace; returns
        the instrument updates to flush OUTSIDE the lock."""
        recs = p.spans
        if not recs:
            return None
        root = _span_dict(self._root_of(recs))
        endpoint = self._endpoint_key(root)
        window = self._tail.get(endpoint)
        if window is None:
            if len(self._tail) < 64:        # bounded endpoint keys (the
                window = self._tail[endpoint] = deque(maxlen=_TAIL_WINDOW)
            # span-names lint keeps names literal, but a rogue caller
            # must not explode this dict either

        reason = None
        if tid in self._pins:
            reason = "incident"
        elif self._root_errored(root):
            reason = "error"
        elif (window is not None and len(window) >= _TAIL_MIN_SAMPLES
                and root["dur_us"] > self._tail_threshold_locked(endpoint,
                                                                 window)):
            reason = "latency_tail"
        elif time.time() < self._incident_until:
            reason = "incident"
        elif self._rng.random() < sample_rate():
            reason = "head_sample"
        if window is not None:
            window.append(float(root["dur_us"]))
        if reason is None:
            self.discarded_count += 1
            return {"discarded": 1}
        spans = sorted((_span_dict(r) for r in recs),
                       key=lambda s: s["ts_us"])
        entry = {
            "trace_id": tid, "reason": reason, "root": root["name"],
            "route": (root["attrs"] or {}).get("route"),
            "tenant": (root["attrs"] or {}).get("tenant"),
            "ts_us": root["ts_us"], "dur_us": root["dur_us"],
            "error": self._root_errored(root),
            "error_type": (root["error_type"]
                           or (root["attrs"] or {}).get("error_type")),
            "at": time.time(), "pinned": tid in self._pins,
            "truncated": p.truncated,
            "bytes": sum(_est_bytes(s) for s in spans),
            "spans": spans,
        }
        self._retained[tid] = entry
        self._bytes += entry["bytes"]
        self.retained_count += 1
        out = self._evict_locked() or {}
        out["retained"] = reason
        return out

    def _tail_threshold_locked(self, endpoint: str, window: deque) -> float:
        """The rolling quantile over ``window``, recomputed at most
        every 8 appends (sorting 128 floats per span close was the
        measured hot spot; a few-sample-stale threshold keeps the same
        tail)."""
        cached = self._tail_thresh.get(endpoint)
        if cached is not None and cached[1] < 8:
            cached[1] += 1
            return cached[0]
        thresh = _quantile(sorted(window), tail_quantile())
        self._tail_thresh[endpoint] = [thresh, 0]
        return thresh

    def _evict_locked(self) -> Optional[dict]:
        """FIFO eviction past the bytes budget, skipping pinned traces
        (an incident's evidence outlives the budget until unpinned)."""
        budget = (self._budget_override if self._budget_override is not None
                  else budget_bytes())
        evicted = 0
        if self._bytes > budget:
            # graftlint: disable=lock-discipline — _locked suffix: every
            # caller already holds self._lock (checker can't cross calls)
            for tid in list(self._retained):
                if self._bytes <= budget:
                    break
                if self._retained[tid].get("pinned"):
                    continue
                self._bytes -= self._retained[tid]["bytes"]
                del self._retained[tid]
                evicted += 1
        if evicted:
            self.evicted_count += evicted
            return {"evicted": evicted}
        return None

    def _flush(self, updates: dict):
        """Publish instrument updates outside the store lock (the
        TraceSink discipline: no metric locks under the span path's
        lock)."""
        try:
            (retained_c, discarded_c, evicted_c, bytes_g, budget_g,
             traces_g) = _obs()
            reason = updates.get("retained")
            if reason:
                _retained_counter(reason).inc()
            if updates.get("discarded"):
                discarded_c.inc(updates["discarded"])
            if updates.get("evicted"):
                evicted_c.inc(updates["evicted"])
            if reason or updates.get("evicted"):
                # the gauges only move when the retained set does — the
                # common discard path (99% of traffic at the default
                # head rate) skips three gauge writes per request
                bytes_g.set(float(self._bytes))
                budget_g.set(float(self._budget_override
                                   if self._budget_override is not None
                                   else budget_bytes()))
                traces_g.set(float(len(self._retained)))
        except Exception:
            pass        # metrics off / mid-reset must never break a span

    # ----------------------------------------------------------- incidents
    def pin(self, trace_id: Optional[str]):
        """Always-retain ``trace_id``: if already retained it becomes
        eviction-exempt; if still pending/future it will be kept with
        reason ``incident`` when it completes."""
        if not trace_id:
            return
        with self._lock:
            self._pins[trace_id] = True
            while len(self._pins) > _MAX_PINS:
                old, _ = self._pins.popitem(last=False)
                ent = self._retained.get(old)
                if ent is not None:
                    ent["pinned"] = False
            ent = self._retained.get(trace_id)
            if ent is not None:
                ent["pinned"] = True

    def pinned_ids(self) -> List[str]:
        with self._lock:
            return list(self._pins)

    def open_incident_window(self, seconds: float = INCIDENT_WINDOW_S):
        """Keep every trace completing in the next ``seconds`` — the
        requests around an incident explain it."""
        with self._lock:
            self._incident_until = max(self._incident_until,
                                       time.time() + max(0.0, seconds))

    def incident_active(self) -> bool:
        return time.time() < self._incident_until

    # ------------------------------------------------------------- queries
    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """The retained trace payload (spans included), or None."""
        self.drain()
        with self._lock:
            entry = self._retained.get(trace_id)
            if entry is None:
                return None
            out = dict(entry)
            out["spans"] = list(entry["spans"])
            return out

    def recent(self, limit: int = 64) -> List[Dict[str, Any]]:
        """Newest-first retained-trace summaries (no span bodies)."""
        self.drain()
        with self._lock:
            entries = list(self._retained.values())[-max(1, int(limit)):]
        return [{k: e[k] for k in
                 ("trace_id", "reason", "root", "route", "tenant",
                  "ts_us", "dur_us", "error", "error_type", "at",
                  "pinned", "truncated", "bytes")}
                | {"n_spans": len(e["spans"])}
                for e in reversed(entries)]

    def snapshot(self) -> Dict[str, Any]:
        self.drain()
        with self._lock:
            return {
                "enabled": trace_store_enabled(),
                "traces": len(self._retained),
                "pending": len(self._pending),
                "bytes": self._bytes,
                "budget_bytes": (self._budget_override
                                 if self._budget_override is not None
                                 else budget_bytes()),
                "retained": self.retained_count,
                "discarded": self.discarded_count,
                "evicted": self.evicted_count,
                "pinned": list(self._pins),
                "incident_window_open": time.time() < self._incident_until,
                "sample_rate": sample_rate(),
                "tail_quantile": tail_quantile(),
            }

    def clear(self):
        self._queue.clear()
        with self._lock:
            self._pending.clear()
            self._retained.clear()
            self._tail.clear()
            self._tail_thresh.clear()
            self._pins.clear()
            self._bytes = 0
            self._incident_until = 0.0


_global_store: Optional[TraceStore] = None
_store_lock = threading.Lock()


def global_trace_store() -> TraceStore:
    global _global_store
    if _global_store is None:
        with _store_lock:
            if _global_store is None:
                _global_store = TraceStore()
    return _global_store


def reset_global_trace_store(**kw) -> TraceStore:
    global _global_store
    with _store_lock:
        _global_store = TraceStore(**kw)
    return _global_store


# ------------------------------------------------- tracing-side hooks
# (called by tracing.py for every global-sink span; both resolve the
# kill switch LIVE so DL4J_TPU_TRACE_STORE=0 is a pure no-op)

def store_span_open(trace_id: Optional[str]) -> None:
    if not trace_store_enabled():
        return
    global_trace_store().enqueue_open(trace_id)


def store_span_close(rec, span_close: bool = True) -> None:
    if not trace_store_enabled():
        return
    global_trace_store().enqueue_close(rec, span_close=span_close)
