"""Bounded in-process timeseries rings fed by a periodic registry scrape.

The registry answers "what is the value NOW"; a postmortem needs "what
were the minutes BEFORE the trip".  This module keeps a small ring of
``(ts, value)`` samples per metric name — counters and gauges as their
summed-across-children value, histograms as cumulative ``:count`` /
``:sum`` plus a point-in-time ``:p99`` — fed by a cheap throttled scrape
that rides the front door's sync beat (never the request hot path).

Consumers:

- ``GET /debug/timeseries`` on the UI server, front door, and proxy
  admin port (:func:`timeseries_payload`);
- ``timeseries.json`` in flight-recorder bundles (the minutes before
  the trip, plus the watchtower's alert state at the moment of death);
- the watchtower's change-point detectors, which read windowed rates
  and latest values instead of re-deriving them per detector.

Counters are delta-aware: :meth:`TimeseriesStore.rate` sums only
*positive* deltas between consecutive samples, so a registry reset (the
cumulative total dropping) reads as a gap, never a negative rate.

Kill switch: ``DL4J_TPU_WATCHTOWER=0`` (read live, byte-key fast path —
the trace-store idiom) makes the scrape a no-op and the HTTP surfaces
404; nothing is ringed, no ``dl4j_timeseries_*`` series are created.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from deeplearning4j_tpu.observability.registry import (Counter, Gauge,
                                                       Histogram,
                                                       global_registry,
                                                       on_registry_reset)

__all__ = [
    "watchtower_enabled", "timeseries_len", "timeseries_interval_s",
    "TimeseriesStore", "global_timeseries", "reset_global_timeseries",
    "timeseries_payload",
]

# the live-env fast path (trace_store idiom): CPython's environ._data is
# the underlying dict of bytes, so the per-call check costs one dict get
try:
    _ENV_DATA = os.environ._data          # type: ignore[attr-defined]
    _K_WATCH = os.fsencode("DL4J_TPU_WATCHTOWER")
except AttributeError:                     # non-CPython: plain getenv
    _ENV_DATA = None


def watchtower_enabled() -> bool:
    """``DL4J_TPU_WATCHTOWER`` kill switch, resolved LIVE per call —
    flipping it off restores pre-watchtower behavior (no scrape, no
    detectors, no alert routes) without a restart."""
    if _ENV_DATA is not None:
        return _ENV_DATA.get(_K_WATCH, b"1") != b"0"
    return os.environ.get("DL4J_TPU_WATCHTOWER", "1") != "0"


def timeseries_len() -> int:
    """Samples kept per series (``DL4J_TPU_TIMESERIES_LEN``, default
    240 — 20 minutes at the default 5 s scrape interval)."""
    try:
        return max(8, int(os.environ.get("DL4J_TPU_TIMESERIES_LEN", 240)))
    except (TypeError, ValueError):
        return 240


def timeseries_interval_s() -> float:
    """Minimum seconds between scrapes (``DL4J_TPU_TIMESERIES_INTERVAL_S``,
    default 5.0; drills shrink it so tests run in seconds)."""
    try:
        return max(0.05, float(os.environ.get(
            "DL4J_TPU_TIMESERIES_INTERVAL_S", 5.0)))
    except (TypeError, ValueError):
        return 5.0


#: ring-name cap — the registry is bounded by convention, but a runaway
#: metric factory must not turn the postmortem ring into the leak
_MAX_SERIES = 512

#: the point-in-time histogram quantile sampled per scrape
_HIST_QUANTILE = 0.99

# lazily-bound self-instruments, dropped on registry reset so a fresh
# registry re-binds (and so NOTHING is created while the switch is off)
_ts_obs_cache = None
_ts_obs_lock = threading.Lock()


def _ts_obs():
    global _ts_obs_cache
    obs = _ts_obs_cache
    if obs is None:
        with _ts_obs_lock:
            obs = _ts_obs_cache
            if obs is None:
                reg = global_registry()
                obs = (
                    reg.counter("dl4j_timeseries_scrapes_total",
                                "registry scrapes into the timeseries "
                                "rings"),
                    reg.gauge("dl4j_timeseries_series",
                              "live timeseries ring count"),
                )
                _ts_obs_cache = obs
    return obs


@on_registry_reset
def _drop_ts_obs():
    global _ts_obs_cache
    _ts_obs_cache = None


class TimeseriesStore:
    """Bounded per-metric rings of ``(ts, value)`` samples."""

    def __init__(self, maxlen: Optional[int] = None):
        self._maxlen_override = maxlen
        self._rings: Dict[str, deque] = {}
        self._lock = threading.Lock()
        self._last_scrape = 0.0
        self.scrapes = 0

    # ------------------------------------------------------------ scraping
    def _ring(self, name: str) -> Optional[deque]:
        ring = self._rings.get(name)
        if ring is None:
            if len(self._rings) >= _MAX_SERIES:
                return None              # bounded: first-come keeps its ring
            ring = deque(maxlen=(self._maxlen_override
                                 if self._maxlen_override is not None
                                 else timeseries_len()))
            self._rings[name] = ring
        return ring

    def _append(self, name: str, now: float, value: float):
        ring = self._ring(name)
        if ring is not None:
            ring.append((now, float(value)))

    def scrape(self, registry=None, now: Optional[float] = None) -> int:
        """One pass over every registry instrument; returns the number
        of series sampled.  No-op (0) with the watchtower off."""
        if not watchtower_enabled():
            return 0
        reg = registry if registry is not None else global_registry()
        if now is None:
            now = time.time()
        sampled = 0
        with self._lock:
            for name in reg.names():
                inst = reg.get(name)
                if inst is None:
                    continue
                try:
                    if isinstance(inst, Histogram):
                        count = total = 0.0
                        worst_q = None
                        for _lvals, child in inst.series():
                            count += child.count
                            total += child.sum
                            q = child.quantile(_HIST_QUANTILE)
                            if q == q and (worst_q is None or q > worst_q):
                                worst_q = q
                        self._append(name + ":count", now, count)
                        self._append(name + ":sum", now, total)
                        if worst_q is not None:
                            self._append(name + ":p99", now, worst_q)
                        sampled += 3
                    elif isinstance(inst, (Counter, Gauge)):
                        self._append(name, now, sum(
                            child.value for _l, child in inst.series()))
                        sampled += 1
                # graftlint: disable=typed-errors — one torn instrument
                # must not veto the rest of the scrape
                except Exception:
                    continue
            self._last_scrape = now
            self.scrapes += 1
        obs = _ts_obs()
        obs[0].inc()
        obs[1].set(len(self._rings))
        return sampled

    def maybe_scrape(self, now: Optional[float] = None) -> bool:
        """Throttled :meth:`scrape` — at most one per
        ``DL4J_TPU_TIMESERIES_INTERVAL_S``."""
        if not watchtower_enabled():
            return False
        if now is None:
            now = time.time()
        with self._lock:
            if now - self._last_scrape < timeseries_interval_s():
                return False
        self.scrape(now=now)
        return True

    # ------------------------------------------------------------- queries
    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._rings)

    def window(self, name: str, seconds: float,
               now: Optional[float] = None) -> List[Tuple[float, float]]:
        """Samples of ``name`` from the last ``seconds``, oldest first."""
        if now is None:
            now = time.time()
        cutoff = now - max(0.0, seconds)
        with self._lock:
            ring = self._rings.get(name)
            if ring is None:
                return []
            return [(ts, v) for ts, v in ring if ts >= cutoff]

    def latest(self, name: str) -> Optional[float]:
        with self._lock:
            ring = self._rings.get(name)
            if not ring:
                return None
            return ring[-1][1]

    def delta(self, name: str, seconds: float,
              now: Optional[float] = None) -> Optional[float]:
        """Reset-aware cumulative increase of ``name`` over the window:
        the sum of POSITIVE deltas between consecutive samples (a
        registry reset reads as a gap, never a negative delta).  None
        with fewer than two samples in the window."""
        samples = self.window(name, seconds, now)
        if len(samples) < 2:
            return None
        total = 0.0
        prev = samples[0][1]
        for _ts, v in samples[1:]:
            if v > prev:
                total += v - prev
            prev = v
        return total

    def rate(self, name: str, seconds: float,
             now: Optional[float] = None) -> Optional[float]:
        """Per-second reset-aware rate of a cumulative series over the
        window (None with <2 samples or a zero-length span)."""
        samples = self.window(name, seconds, now)
        if len(samples) < 2:
            return None
        span = samples[-1][0] - samples[0][0]
        if span <= 0:
            return None
        inc = self.delta(name, seconds, now)
        return None if inc is None else inc / span

    def snapshot(self, names: Optional[List[str]] = None,
                 last: Optional[int] = None) -> dict:
        """The ``/debug/timeseries`` / bundle payload: every ring (or
        the requested names), newest ``last`` samples each."""
        with self._lock:
            keys = sorted(self._rings)
        if names:
            wanted = set(names)
            keys = [k for k in keys
                    if k in wanted or any(k.startswith(n) for n in wanted)]
        out: Dict[str, list] = {}
        with self._lock:
            for k in keys:
                ring = self._rings.get(k)
                if ring is None:
                    continue
                samples = list(ring)
                if last is not None:
                    samples = samples[-max(1, int(last)):]
                out[k] = [[round(ts, 3), v] for ts, v in samples]
        return {"enabled": watchtower_enabled(),
                "interval_s": timeseries_interval_s(),
                "maxlen": (self._maxlen_override
                           if self._maxlen_override is not None
                           else timeseries_len()),
                "scrapes": self.scrapes,
                "series": out}

    def clear(self):
        with self._lock:
            self._rings.clear()
            self._last_scrape = 0.0


_global_store: Optional[TimeseriesStore] = None
_store_lock = threading.Lock()


def global_timeseries() -> TimeseriesStore:
    """THE process-wide ring store the scrape beat and detectors use."""
    global _global_store
    if _global_store is None:
        with _store_lock:
            if _global_store is None:
                _global_store = TimeseriesStore()
    return _global_store


def reset_global_timeseries(**kw) -> TimeseriesStore:
    global _global_store
    with _store_lock:
        _global_store = TimeseriesStore(**kw)
    return _global_store


@on_registry_reset
def _clear_rings():
    # a fresh registry restarts every cumulative total; stale rings
    # would make windowed deltas span two registry lifetimes
    if _global_store is not None:
        _global_store.clear()


def timeseries_payload(query: Optional[Dict[str, list]] = None,
                       local_worker: str = "local") -> dict:
    """Shared ``GET /debug/timeseries`` payload for all three HTTP
    surfaces: ``?name=<prefix>`` filters series, ``?last=N`` bounds
    samples per series.  Callers gate on :func:`watchtower_enabled`."""
    q = query or {}
    names = [n for n in q.get("name", []) if n] or None
    last = None
    try:
        raw = (q.get("last", []) or [None])[0]
        if raw is not None:
            last = max(1, int(raw))
    except (TypeError, ValueError):
        last = None
    payload = global_timeseries().snapshot(names=names, last=last)
    payload["worker"] = local_worker
    return payload
