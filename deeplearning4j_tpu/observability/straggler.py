"""Slow-step (straggler) detection over a rolling median.

Distributed-training throughput dies on per-step tail latency (Awan et al.
arXiv:1810.11112 characterize exactly this step-time-vs-communication
decomposition): one slow host/input shard stalls every synchronous
allreduce. The detector keeps a rolling window of recent step durations and
counts steps exceeding ``k × rolling-median`` into the registry, labeled by
phase, so a scrape shows *that* and *where* stalls happen without a trace.
"""
from __future__ import annotations

import threading
from typing import Optional

from deeplearning4j_tpu.observability.registry import (MetricsRegistry,
                                                       global_registry)


class StragglerDetector:
    """Counts observations exceeding ``threshold ×`` the rolling median.

    The first ``warmup`` observations only seed the window — compile /
    cache-cold steps would otherwise poison the median and flag every
    subsequent healthy step as "fast" relative to a bogus baseline.
    """

    def __init__(self, phase: str = "train_step", threshold: float = 3.0,
                 window: int = 64, warmup: int = 3,
                 registry: Optional[MetricsRegistry] = None):
        if threshold <= 1.0:
            raise ValueError("threshold must exceed 1.0")
        self.phase = phase
        self.threshold = threshold
        self.window = max(8, window)
        self.warmup = warmup
        self._samples: list = []
        self._pos = 0
        self._seen = 0
        self._lock = threading.Lock()
        reg = registry or global_registry()
        self._slow = reg.counter(
            "dl4j_slow_steps_total",
            "steps slower than k x rolling-median step time",
            label_names=("phase",)).labels(phase=phase)
        self._total = reg.counter(
            "dl4j_straggler_checked_steps_total",
            "steps checked by the straggler detector",
            label_names=("phase",)).labels(phase=phase)

    def _median(self) -> float:
        data = sorted(self._samples)
        n = len(data)
        mid = n // 2
        return data[mid] if n % 2 else (data[mid - 1] + data[mid]) / 2.0

    def observe(self, seconds: float) -> bool:
        """Record one step duration; returns True when flagged slow."""
        slow = False
        with self._lock:
            self._seen += 1
            warm = self._seen > self.warmup and self._samples
            if warm:
                median = self._median()
                slow = median > 0 and seconds > self.threshold * median
            if self._seen > self.warmup:
                self._total.inc()
            if len(self._samples) < self.window:
                self._samples.append(seconds)
            else:
                self._samples[self._pos] = seconds
                self._pos = (self._pos + 1) % self.window
        if slow:
            self._slow.inc()
        return slow

    @property
    def slow_count(self) -> int:
        return int(self._slow.value)
