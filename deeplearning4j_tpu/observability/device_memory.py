"""Device (HBM) memory telemetry sampled at step/batch boundaries.

An OOM on an accelerator is the other silent killer next to retrace
storms and divergence: fragmentation and leak curves are invisible until
the allocator throws. ``jax.Device.memory_stats()`` exposes the PJRT
allocator's live view (``bytes_in_use`` / ``peak_bytes_in_use`` /
``bytes_limit`` on TPU/GPU backends); this module turns it into gauges

    ``dl4j_device_memory_bytes{device,kind}``   kind ∈ in_use|peak|limit

scraped at ``/metrics`` and snapshotted into flight-recorder bundles.
Sampling happens at the boundaries the fit loops and the serving
completer already cross (``train_metrics.record_step``, the
``ParallelInference`` completer) — never inside the jitted step — and is
throttled to at most one sweep per ``_MIN_INTERVAL_S`` so a fast step
loop pays one cached-time comparison, not eight PJRT calls.

Graceful no-op everywhere stats are unavailable: the CPU backend returns
``None`` from ``memory_stats()`` — the sampler remembers that and stops
asking (per process), so the CPU test mesh costs nothing.

Rides the master kill switch ``DL4J_TPU_METRICS=0``.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from deeplearning4j_tpu.observability.registry import (global_registry,
                                                       metrics_enabled)

_MIN_INTERVAL_S = 1.0

#: stat-dict keys → gauge ``kind`` label (PJRT's naming, stable across
#: TPU and GPU plugins)
_KINDS = (("bytes_in_use", "in_use"),
          ("peak_bytes_in_use", "peak"),
          ("bytes_limit", "limit"))

_lock = threading.Lock()
_last_sample_mono = 0.0
_unsupported = False


def _stats_per_device() -> List[tuple]:
    """[(device, stats-dict)] for devices that report stats."""
    import jax

    out = []
    for d in jax.devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            out.append((d, stats))
    return out


def sample(min_interval_s: Optional[float] = None) -> bool:
    """Sweep every device's memory stats into the gauges (throttled).
    Returns True when a sweep actually published."""
    global _last_sample_mono, _unsupported
    if not metrics_enabled() or _unsupported:
        return False
    interval = _MIN_INTERVAL_S if min_interval_s is None else min_interval_s
    now = time.monotonic()
    with _lock:
        if now - _last_sample_mono < interval:
            return False
        _last_sample_mono = now
    per_dev = _stats_per_device()
    if not per_dev:
        # nothing on this backend reports (CPU test mesh) — stop asking
        _unsupported = True
        return False
    gauge = global_registry().gauge(
        "dl4j_device_memory_bytes",
        "PJRT allocator memory per device (sampled at step/batch "
        "boundaries): kind=in_use|peak|limit",
        label_names=("device", "kind"))
    for d, stats in per_dev:
        dev_id = str(getattr(d, "id", d))
        for stat_key, kind in _KINDS:
            v = stats.get(stat_key)
            if v is not None:
                gauge.labels(device=dev_id, kind=kind).set(float(v))
    return True


def snapshot() -> dict:
    """Unthrottled point-in-time view for postmortem bundles."""
    import jax

    devices = []
    try:
        devs = jax.devices()
    except Exception as e:
        return {"error": repr(e)}
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        devices.append({
            "id": getattr(d, "id", None),
            "platform": getattr(d, "platform", None),
            "kind": getattr(d, "device_kind", None),
            "memory_stats": ({k: stats[k] for k in sorted(stats)}
                             if stats else None),
        })
    return {"devices": devices}


def reset_for_tests() -> None:
    """Forget the throttle and the unsupported latch (test isolation)."""
    global _last_sample_mono, _unsupported
    with _lock:
        _last_sample_mono = 0.0
        _unsupported = False
