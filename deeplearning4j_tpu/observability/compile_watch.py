"""Compile watch: XLA trace/retrace accounting for the jitted entry points.

Whole-program jit is this stack's performance model — and its silent
failure mode. A signature change (new shape, new dtype, weak-typed leaf,
new sharding) retraces and recompiles the ENTIRE train/output program,
and nothing in a step-time histogram says *why* a step took 40× median:
recompilation storms are the dominant hidden cost when whole programs
compile per shape (Fishman et al. arXiv:1810.09868 make the same
argument for whole-program emission; the PR 1–3 decomposition stops at
time, this module extends it to compile events).

Mechanism — two independent sources, correlated best-effort:

- **Trace probes**: the jitted bodies (``MultiLayerNetwork._train_step``
  / ``_output_jit``, the ``ComputationGraph`` twins — and through them
  the ``ShardedTrainer`` step and every ``ParallelInference`` bucket
  executable) call :func:`note_trace` as their first statement. The body
  only executes while jax TRACES it, so each call is exactly one
  (re)trace of that entry point, and the abstract args carry the
  shape/dtype signature that triggered it. Steady-state cost is zero:
  a cached executable never re-enters the Python body.
- **Compile timing**: a process-wide ``jax.monitoring`` listener
  observes ``backend_compile_duration`` events into
  ``dl4j_compile_seconds`` and attributes each duration to the most
  recent probe (bounded staleness window) — trace counts are exact,
  compile seconds are best-effort global. A compile with NO fresh trace
  (jax recompiles for sharding/layout-only changes without re-entering
  the Python body — the ``ShardedTrainer`` placement path) still lands
  in the ring as an ``(untraced)`` event when a declared cause is
  pending, so mesh re-homing stays visible.

Each event lands in a bounded ring (``compiles.json`` in postmortem
bundles, ``GET /debug/compiles`` live) stamped with the training
iteration count at trace time, which is what makes
:class:`RetraceStormRule` possible: *recompiles* (per-fn events beyond
the fn's first compile) inside the last ``window_steps`` training steps
AND ``window_seconds`` grade degraded/failing on ``/health`` +
``/alerts``. Serving correlates causes: a shape-bucket miss registers a
pending cause via :func:`note_cause`, and the compile it provokes
carries ``cause="bucket_miss"``.

Metrics: ``dl4j_compile_total{fn}``, ``dl4j_compile_seconds``.
Kill switches: ``DL4J_TPU_COMPILE_WATCH=0`` (probes and listener no-op)
under the ``DL4J_TPU_METRICS=0`` master.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.observability.registry import (global_registry,
                                                       metrics_enabled,
                                                       on_registry_reset)
from deeplearning4j_tpu.observability.slo import (DEGRADED, FAILING, OK,
                                                  SLORule)

#: retained compile events (a storm of thousands keeps only the tail —
#: the counts survive in dl4j_compile_total either way)
_RING_CAPACITY = 256

#: how long a noted cause (bucket miss, sharded placement) stays eligible
#: to be claimed by the next trace — compiles follow their cause within
#: the same dispatch, so seconds suffice
_CAUSE_TTL_S = 5.0

#: a backend_compile_duration is attributed to the latest probe only if
#: the probe is fresher than this (tracing immediately precedes compile)
_ATTRIBUTION_TTL_S = 120.0


def compile_watch_enabled() -> bool:
    """Kill switch (read per call so tests can flip it; probes only fire
    at trace time, so the per-step cost of the check is zero)."""
    return (metrics_enabled()
            and os.environ.get("DL4J_TPU_COMPILE_WATCH", "1") != "0")


def _signature(trees) -> str:
    """shape/dtype signature of the abstract args that triggered a trace,
    e.g. ``f32[32,784], f32[32,10], None``. Works on tracers (shape and
    dtype are aval attributes) and on concrete arrays alike."""
    import jax

    parts: List[str] = []
    for tree in trees:
        leaves = jax.tree.leaves(tree)
        if not leaves:
            parts.append("None" if tree is None else "{}")
            continue
        for leaf in leaves:
            dt = getattr(leaf, "dtype", None)
            shape = getattr(leaf, "shape", None)
            if dt is None or shape is None:
                parts.append(type(leaf).__name__)
            else:
                name = getattr(dt, "name", str(dt))
                short = (name.replace("float", "f").replace("uint", "u")
                         .replace("int", "i").replace("complex", "c")
                         .replace("bool", "pred"))
                parts.append(f"{short}[{','.join(str(d) for d in shape)}]")
    return ", ".join(parts)


def _current_training_step() -> int:
    """The shared fit-iteration clock the retrace-storm window counts
    against (see train_metrics.total_iterations)."""
    from deeplearning4j_tpu.observability.train_metrics import (
        total_iterations)
    return total_iterations()


def _compile_counter(fn: str):
    """The one registration site for the per-fn compile counter (traced
    and untraced events must land in the SAME series)."""
    return global_registry().counter(
        "dl4j_compile_total",
        "XLA traces (each one compiles a fresh executable) of the "
        "jitted entry points, by function",
        label_names=("fn",)).labels(fn=fn)


class CompileWatch:
    """Bounded ring of trace/compile events + the correlation state.

    One process-wide instance via :func:`global_compile_watch`; tests
    construct their own and pass it to probes explicitly if needed.
    """

    def __init__(self, capacity: int = _RING_CAPACITY):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=capacity)
        self._seen_fns: set = set()      # fns that have compiled ≥once
        self._counts: Dict[str, int] = {}
        self._seq = 0
        self._pending_cause: Optional[Dict[str, Any]] = None
        self._last_trace_mono = 0.0

    # ------------------------------------------------------------ probes
    def note_trace(self, fn: str, *arg_trees, **attrs) -> None:
        """Record one (re)trace of ``fn``. Call from INSIDE the jitted
        body — it executes once per trace, never per cached step."""
        if not compile_watch_enabled():
            return
        sig = _signature(arg_trees)
        now = time.time()
        mono = time.monotonic()
        with self._lock:
            self._seq += 1
            cause = None
            pc = self._pending_cause
            if pc is not None and mono - pc["noted_mono"] <= _CAUSE_TTL_S:
                cause = {k: v for k, v in pc.items() if k != "noted_mono"}
                self._pending_cause = None
            first = fn not in self._seen_fns
            self._seen_fns.add(fn)
            self._counts[fn] = self._counts.get(fn, 0) + 1
            event = {
                "seq": self._seq,
                "fn": fn,
                "signature": sig,
                "unix_ts": now,
                "step": _current_training_step(),
                "first_compile_of_fn": first,
                "compile_seconds": None,   # filled by the duration listener
                "cause": cause,
            }
            if attrs:
                event["attrs"] = {k: (v if isinstance(
                    v, (int, float, bool, str)) or v is None else str(v))
                    for k, v in attrs.items()}
            self._ring.append(event)
            self._last_trace_mono = mono
        _compile_counter(fn).inc()

    def note_cause(self, cause: str, **attrs) -> None:
        """Declare WHY the next trace (within a few seconds) will happen —
        e.g. the serving batcher's shape-bucket miss, or a ShardedTrainer
        re-homing params onto a mesh. Best-effort: claimed by the next
        :meth:`note_trace`, expires unclaimed."""
        if not compile_watch_enabled():
            return
        with self._lock:
            self._pending_cause = {"cause": cause,
                                   "noted_mono": time.monotonic(), **attrs}

    def attribute_duration(self, seconds: float) -> bool:
        """Fold one ``backend_compile_duration`` into the freshest
        unattributed event (tracing immediately precedes its compile).
        Returns False when no recent trace is waiting for a duration."""
        with self._lock:
            if (time.monotonic() - self._last_trace_mono
                    > _ATTRIBUTION_TTL_S):
                return False
            for event in reversed(self._ring):
                if event["compile_seconds"] is None:
                    event["compile_seconds"] = seconds
                    return True
        return False

    def note_untraced_compile(self, seconds: float) -> None:
        """A backend compile fired with NO fresh trace to claim it — on
        this jax a sharding/layout-only change (e.g. ``ShardedTrainer``
        re-homing params onto a mesh) hits the jaxpr cache and recompiles
        the executable WITHOUT re-entering the Python body, so the probes
        stay silent. Recorded into the ring ONLY when a declared cause is
        pending (placement, bucket miss): unscoped process-wide compiles
        (eager ops, other libraries) would otherwise flood the ring and
        poison the storm rule."""
        now = time.time()
        mono = time.monotonic()
        with self._lock:
            pc = self._pending_cause
            if pc is None or mono - pc["noted_mono"] > _CAUSE_TTL_S:
                return
            cause = {k: v for k, v in pc.items() if k != "noted_mono"}
            self._pending_cause = None
            self._seq += 1
            fn = "(untraced)"
            first = fn not in self._seen_fns
            self._seen_fns.add(fn)
            self._counts[fn] = self._counts.get(fn, 0) + 1
            self._ring.append({
                "seq": self._seq,
                "fn": fn,
                "signature": "sharding/layout change (no retrace)",
                "unix_ts": now,
                "step": _current_training_step(),
                "first_compile_of_fn": first,
                "compile_seconds": seconds,
                "cause": cause,
            })
        _compile_counter(fn).inc()

    # ---------------------------------------------------------- queries
    def events(self, limit: Optional[int] = None) -> List[dict]:
        """Retained events, oldest first (``compiles.json`` payload)."""
        with self._lock:
            out = [dict(e) for e in self._ring]
        return out[-limit:] if limit else out

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)

    @property
    def total(self) -> int:
        with self._lock:
            return self._seq

    def count_for(self, fn: str) -> int:
        with self._lock:
            return self._counts.get(fn, 0)

    def recompiles_in_window(self, window_steps: int,
                             window_seconds: float) -> List[dict]:
        """RE-compiles (events past each fn's first-ever compile) recent
        on BOTH clocks: within ``window_steps`` of the current training
        iteration count AND ``window_seconds`` of now. A serving-only
        process never advances the step clock (diff 0), so the time
        window alone decays its storms; a training process ages events
        out by steps long before wall time."""
        cur = _current_training_step()
        now = time.time()
        with self._lock:
            return [dict(e) for e in self._ring
                    if not e["first_compile_of_fn"]
                    and cur - e["step"] <= window_steps
                    and now - e["unix_ts"] <= window_seconds]

    def snapshot(self) -> dict:
        """The bundle/endpoint payload."""
        return {
            "enabled": compile_watch_enabled(),
            "total_traces": self.total,
            "by_fn": self.counts(),
            "events": self.events(),
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seen_fns.clear()
            self._counts.clear()
            self._seq = 0
            self._pending_cause = None


class RetraceStormRule(SLORule):
    """Retrace storm: recompiles of already-compiled entry points keep
    landing inside the recent step window — shape/signature churn is
    burning accelerator time on the compiler instead of the model.
    First-ever compiles per fn are free (cold start is not a storm)."""

    def __init__(self, name: str = "retrace_storm",
                 window_steps: int = 50, window_seconds: float = 600.0,
                 degraded: Optional[int] = 3, failing: Optional[int] = 8,
                 description: str = ""):
        super().__init__(name, description or
                         f"recompiles in the last {window_steps} steps / "
                         f"{window_seconds:.0f}s")
        self.window_steps = window_steps
        self.window_seconds = window_seconds
        self.degraded = degraded
        self.failing = failing

    def _evaluate(self, registry) -> dict:
        watch = global_compile_watch()
        recent = watch.recompiles_in_window(self.window_steps,
                                            self.window_seconds)
        n = len(recent)
        status = OK
        if self.failing is not None and n >= self.failing:
            status = FAILING
        elif self.degraded is not None and n >= self.degraded:
            status = DEGRADED
        out = {"status": status, "value": n,
               "window_steps": self.window_steps,
               "degraded_at": self.degraded, "failing_at": self.failing}
        if recent:
            worst = max(recent, key=lambda e: e["seq"])
            out["detail"] = (f"last: {worst['fn']}({worst['signature']})"
                             + (f" cause={worst['cause']['cause']}"
                                if worst.get("cause") else ""))
        return out


# --------------------------------------------------------- process wiring
_global_watch: Optional[CompileWatch] = None
_watch_lock = threading.Lock()
_listener_registered = False


def global_compile_watch() -> CompileWatch:
    """THE process-wide watch every built-in probe records into."""
    global _global_watch
    if _global_watch is None:
        with _watch_lock:
            if _global_watch is None:
                _global_watch = CompileWatch()
    return _global_watch


def reset_global_compile_watch() -> CompileWatch:
    global _global_watch
    with _watch_lock:
        _global_watch = CompileWatch()
    return _global_watch


def _on_compile_duration(event: str, duration: float, **kw) -> None:
    if not event.endswith("backend_compile_duration"):
        return
    if not compile_watch_enabled():
        return
    global_registry().histogram(
        "dl4j_compile_seconds",
        "XLA backend compile durations (process-wide jax.monitoring "
        "events; attributed best-effort to the last traced entry point)"
    ).observe(duration)
    watch = global_compile_watch()
    if not watch.attribute_duration(duration):
        # sharding-only recompile (no retrace): ring-record it if a
        # declared cause is waiting to be claimed
        watch.note_untraced_compile(duration)


def _ensure_listener() -> None:
    """Register the jax.monitoring duration listener once per process.
    Registration is permanent in jax, so the callback re-checks the kill
    switch per event instead of deregistering."""
    global _listener_registered
    with _watch_lock:
        if _listener_registered:
            return
        _listener_registered = True
    try:
        import jax.monitoring as _mon
        _mon.register_event_duration_secs_listener(_on_compile_duration)
    except Exception:       # older jax without the API: counts still work
        pass


# cost-model AOT re-lowerings re-enter the jitted bodies on a jaxpr-cache
# miss; their traces compile nothing, so the probes must stay silent for
# the duration (thread-local: the lowering happens on the caller's thread)
_suppress_tls = threading.local()


@contextlib.contextmanager
def suppress_probes():
    """``with suppress_probes(): f.lower(...)`` — body re-entries inside
    the block are not counted as compiles (cost_model's AOT lowering)."""
    prev = getattr(_suppress_tls, "active", False)
    _suppress_tls.active = True
    try:
        yield
    finally:
        _suppress_tls.active = prev


def probes_suppressed() -> bool:
    return getattr(_suppress_tls, "active", False)


def note_trace(fn: str, *arg_trees, **attrs) -> None:
    """Module-level probe the jitted bodies call (see CompileWatch)."""
    if not compile_watch_enabled() or probes_suppressed():
        return
    _ensure_listener()
    global_compile_watch().note_trace(fn, *arg_trees, **attrs)


def note_cause(cause: str, **attrs) -> None:
    """Module-level cause hint (see CompileWatch.note_cause)."""
    global_compile_watch().note_cause(cause, **attrs)


@on_registry_reset
def _clear_watch():
    # a fresh registry restarts the step clock — events stamped against
    # the old clock would all read "recent" forever (test isolation)
    if _global_watch is not None:
        _global_watch.clear()
