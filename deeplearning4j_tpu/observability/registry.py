"""Process-wide metrics registry — the measurement substrate every layer
publishes into (tentpole of the observability PR; design follows the
TensorFlow position that monitoring is core infrastructure, Abadi et al.
arXiv:1605.08695 §9, and the Prometheus data model).

Three instrument kinds, all label-aware and thread-safe:

- :class:`Counter`   — monotonically increasing float (events, bytes)
- :class:`Gauge`     — last-written value (queue depth, in-flight requests)
- :class:`Histogram` — fixed-bucket counts (Prometheus ``_bucket`` series)
  PLUS a bounded reservoir for quantile summaries (p50/p95/p99) — the
  fixed buckets serve scrapes cheaply, the reservoir serves in-process
  latency introspection exactly.

Kill switch: ``DL4J_TPU_METRICS=0`` turns every instrument into a no-op at
*creation* time — the hot-path cost degenerates to one attribute lookup and
one short-circuit branch, keeping instrumented-by-default overhead honest
(acceptance: <5% on the lenet step, benchmarks/obs_overhead.py).
"""
from __future__ import annotations

import bisect
import math
import os
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


def metrics_enabled() -> bool:
    """The documented kill switch (read per call so tests can flip it)."""
    return os.environ.get("DL4J_TPU_METRICS", "1") != "0"


def _validate_labels(names: Sequence[str]):
    for n in names:
        if not n or not all(c.isalnum() or c == "_" for c in n):
            raise ValueError(f"invalid label name {n!r}")


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_labels(names: Sequence[str], values: Sequence[str],
                extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = list(zip(names, values)) + list(extra)
    if not pairs:
        return ""
    body = ",".join(
        '%s="%s"' % (k, str(v).replace("\\", r"\\").replace('"', r'\"')
                     .replace("\n", r"\n"))
        for k, v in pairs)
    return "{" + body + "}"


class _Instrument:
    """Shared label-child bookkeeping. A child is the per-label-value
    series; the unlabeled instrument IS its own sole child."""

    kind = "untyped"

    def __init__(self, name: str, description: str = "",
                 label_names: Sequence[str] = (), _enabled: bool = True):
        self.name = name
        self.description = description
        self.label_names = tuple(label_names)
        _validate_labels(self.label_names)
        self._children: Dict[Tuple[str, ...], _Instrument] = {}
        self._lock = threading.Lock()
        self._enabled = _enabled

    def labels(self, *values, **kw):
        """Child instrument for one label-value combination (prometheus
        client idiom: ``counter.labels(op="add").inc()``)."""
        if kw:
            try:
                values = tuple(str(kw[n]) for n in self.label_names)
            except KeyError as e:
                raise ValueError(f"missing label {e} for {self.name}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {values}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._make_child()
                self._children[values] = child
        return child

    def _make_child(self):
        raise NotImplementedError

    def _series(self) -> List[Tuple[Tuple[str, ...], "_Instrument"]]:
        with self._lock:
            return list(self._children.items())

    def series(self) -> List[Tuple[Tuple[str, ...], "_Instrument"]]:
        """Live ``(label_values, child)`` pairs WITHOUT creating any — the
        unlabeled instrument is its own sole child. The public
        enumeration surface for renderers and SLO rules."""
        if self.label_names:
            return self._series()
        return [((), self)]


class Counter(_Instrument):
    kind = "counter"

    def __init__(self, name="", description="", label_names=(), _enabled=True):
        super().__init__(name, description, label_names, _enabled)
        self._value = 0.0

    def _make_child(self) -> "Counter":
        return Counter(_enabled=self._enabled)

    def inc(self, amount: float = 1.0):
        if not self._enabled:
            return
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value


class Gauge(_Instrument):
    kind = "gauge"

    def __init__(self, name="", description="", label_names=(), _enabled=True):
        super().__init__(name, description, label_names, _enabled)
        self._value = 0.0

    def _make_child(self) -> "Gauge":
        return Gauge(_enabled=self._enabled)

    def set(self, value: float):
        if not self._enabled:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        if not self._enabled:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    def set_to_current_time(self):
        self.set(time.time())

    @property
    def value(self) -> float:
        return self._value


#: default duration buckets (seconds) — spans 0.1 ms .. 60 s, the range a
#: training step / inference request / checkpoint save actually lands in
DEFAULT_BUCKETS = (1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

_RESERVOIR_MAX = 2048


class Histogram(_Instrument):
    kind = "histogram"

    def __init__(self, name="", description="", label_names=(),
                 buckets: Sequence[float] = DEFAULT_BUCKETS, _enabled=True):
        super().__init__(name, description, label_names, _enabled)
        b = sorted(float(x) for x in buckets)
        if not b:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = tuple(b)
        self._counts = [0] * (len(b) + 1)      # +Inf bucket at the end
        self._sum = 0.0
        self._count = 0
        self._reservoir: List[float] = []
        self._res_i = 0                        # ring cursor once full
        # last exemplar per bucket index: (value, labels, unix_ts) —
        # memory bounded by bucket count; a tail bucket's exemplar carries
        # the trace_id of a request that actually landed there, linking a
        # /metrics scrape straight to its trace (OpenMetrics exemplars)
        self._exemplars: Dict[int, Tuple[float, Dict[str, str], float]] = {}

    def _make_child(self) -> "Histogram":
        return Histogram(buckets=self.buckets, _enabled=self._enabled)

    def observe(self, value: float,
                exemplar: Optional[Dict[str, str]] = None):
        if not self._enabled:
            return
        value = float(value)
        idx = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._count += 1
            if exemplar:
                self._exemplars[idx] = (value, dict(exemplar), time.time())
            if len(self._reservoir) < _RESERVOIR_MAX:
                self._reservoir.append(value)
            else:   # ring overwrite: bounded memory, recency-biased
                self._reservoir[self._res_i] = value
                self._res_i = (self._res_i + 1) % _RESERVOIR_MAX

    def exemplars(self) -> Dict[int, Tuple[float, Dict[str, str], float]]:
        """Snapshot of the per-bucket-index exemplars."""
        with self._lock:
            return dict(self._exemplars)

    def time(self):
        """``with hist.time(): ...`` — observe the block's wall seconds."""
        return _HistogramTimer(self)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def bucket_counts(self) -> List[int]:
        with self._lock:
            return list(self._counts)

    def quantile(self, q: float) -> float:
        """Reservoir quantile (exact over the retained window)."""
        with self._lock:
            data = sorted(self._reservoir)
        if not data:
            return float("nan")
        if q <= 0:
            return data[0]
        if q >= 1:
            return data[-1]
        pos = q * (len(data) - 1)
        lo = int(pos)
        frac = pos - lo
        hi = min(lo + 1, len(data) - 1)
        return data[lo] * (1 - frac) + data[hi] * frac

    def percentiles(self, qs: Iterable[float] = (0.5, 0.95, 0.99)
                    ) -> Dict[float, float]:
        return {q: self.quantile(q) for q in qs}


class _HistogramTimer:
    def __init__(self, hist: Histogram):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    """Instrument factory + Prometheus text renderer.

    ``counter/gauge/histogram`` are get-or-create: repeated calls with the
    same name return the SAME instrument, so independent modules publish
    into shared series without coordination (the process-wide contract).
    """

    def __init__(self, enabled: Optional[bool] = None):
        self._instruments: Dict[str, _Instrument] = {}
        self._lock = threading.Lock()
        self._enabled_override = enabled

    @property
    def enabled(self) -> bool:
        if self._enabled_override is not None:
            return self._enabled_override
        return metrics_enabled()

    def _get_or_create(self, cls, name, description, label_names, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{inst.kind}, not {cls.kind}")
                return inst
            inst = cls(name, description, tuple(label_names),
                       _enabled=self.enabled, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, description: str = "",
                label_names: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, description, label_names)

    def gauge(self, name: str, description: str = "",
              label_names: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, description, label_names)

    def histogram(self, name: str, description: str = "",
                  label_names: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, description, label_names,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._instruments)

    def clear(self):
        """Drop every instrument (test isolation; live handles detach)."""
        with self._lock:
            self._instruments.clear()

    # --------------------------------------------------- prometheus render
    def render_prometheus(self, openmetrics: bool = False) -> str:
        """Text exposition (the /metrics payload). Default is strict
        format 0.0.4 — exemplars are NOT legal there and would fail a real
        Prometheus scrape, so they only render under ``openmetrics=True``
        (the OpenMetrics-flavored output, ``# EOF``-terminated), which
        UIServer serves on Accept-header negotiation."""
        out: List[str] = []
        with self._lock:
            insts = [self._instruments[n] for n in sorted(self._instruments)]
        for inst in insts:
            # OpenMetrics names counter FAMILIES without the _total suffix
            # (samples keep it); a strict OM parser rejects a suffix-less
            # counter sample, which would take the whole target down
            family = inst.name
            if (openmetrics and inst.kind == "counter"
                    and family.endswith("_total")):
                family = family[:-len("_total")]
            out.append(f"# HELP {family} {inst.description or inst.name}")
            out.append(f"# TYPE {family} {inst.kind}")
            for lvals, child in inst.series():
                if inst.kind == "histogram":
                    self._render_histogram(out, inst, lvals, child,
                                           exemplars=openmetrics)
                else:
                    out.append(
                        f"{inst.name}"
                        f"{_fmt_labels(inst.label_names, lvals)} "
                        f"{_fmt_value(child.value)}")
        if openmetrics:
            out.append("# EOF")
        return "\n".join(out) + ("\n" if out else "")

    @staticmethod
    def _fmt_exemplar(ex) -> str:
        """OpenMetrics exemplar suffix: `` # {labels} value timestamp``.
        Appended only to bucket lines that have one; plain lines keep the
        0.0.4 shape, and the suffix still ends in a float so naive
        line-splitting scrapers keep working."""
        if ex is None:
            return ""
        value, labels, ts = ex
        body = _fmt_labels((), (), tuple(labels.items()))
        return f" # {body} {_fmt_value(value)} {ts:.3f}"

    @classmethod
    def _render_histogram(cls, out: List[str], inst, lvals, child: Histogram,
                          exemplars: bool = False):
        cum = 0
        counts = child.bucket_counts()
        exs = child.exemplars() if exemplars else {}
        for i, (bound, c) in enumerate(zip(child.buckets, counts)):
            cum += c
            out.append(
                f"{inst.name}_bucket"
                f"{_fmt_labels(inst.label_names, lvals, (('le', _fmt_value(bound)),))}"
                f" {cum}{cls._fmt_exemplar(exs.get(i))}")
        cum += counts[-1]
        out.append(
            f"{inst.name}_bucket"
            f"{_fmt_labels(inst.label_names, lvals, (('le', '+Inf'),))}"
            f" {cum}{cls._fmt_exemplar(exs.get(len(child.buckets)))}")
        out.append(f"{inst.name}_sum"
                   f"{_fmt_labels(inst.label_names, lvals)}"
                   f" {_fmt_value(child.sum)}")
        out.append(f"{inst.name}_count"
                   f"{_fmt_labels(inst.label_names, lvals)}"
                   f" {child.count}")


_global_registry: Optional[MetricsRegistry] = None
_global_lock = threading.Lock()


def global_registry() -> MetricsRegistry:
    """THE process-wide registry every built-in instrumentation point uses."""
    global _global_registry
    if _global_registry is None:
        with _global_lock:
            if _global_registry is None:
                _global_registry = MetricsRegistry()
    return _global_registry


_reset_hooks: List = []


def on_registry_reset(fn):
    """Register a callback fired by :func:`reset_global_registry` — modules
    that cache label-bound handles use it to drop them so they re-bind."""
    _reset_hooks.append(fn)
    return fn


def reset_global_registry():
    """Fresh global registry (test isolation)."""
    global _global_registry
    with _global_lock:
        _global_registry = MetricsRegistry()
    for fn in list(_reset_hooks):
        fn()
