"""Flight recorder: hang watchdog + postmortem bundles.

A hung or crashed run used to leave nothing to debug from — the span ring,
the metrics registry, and every thread's stack die with the process (or
spin silently forever). The flight recorder is the always-on black box
(the production-monitoring posture of Abadi et al. arXiv:1605.08695 §9;
the postmortem decomposition mirrors the characterization data of Awan et
al. arXiv:1810.11112):

- **Watchdog**: training fit loops and ``ParallelInference`` requests
  *arm* the recorder while work is logically in flight and *progress* it
  on every completed step / device batch. An armed operation with no
  progress on ITS channels (``_PROGRESS_CHANNELS``: fits listen to
  train_step, requests to inference_batch — serving traffic completing
  cannot mask a wedged collective) for ``DL4J_TPU_HANG_SECONDS``
  (default 300) ⇒ one postmortem bundle per operation per stall episode.
  Idle processes (armed count 0) never false-positive.
- **Crash hooks**: ``sys.excepthook`` / ``threading.excepthook`` wrappers
  dump on fatal exceptions (then chain to the previous hooks), and an
  ``atexit`` hook dumps when ``DL4J_TPU_POSTMORTEM_ON_EXIT=1``.
- **Manual**: :meth:`FlightRecorder.dump` any time; ``UIServer`` exposes
  it at ``GET /debug/dump`` for live triage.

A bundle is a directory under ``DL4J_TPU_POSTMORTEM_DIR`` (default
``<tmpdir>/dl4j-tpu-postmortem``) containing:

- ``trace.json``   — Chrome trace of the global span ring (open in Perfetto)
- ``metrics.prom`` — Prometheus snapshot of the global registry
- ``threads.txt``  — every thread's Python stack (``sys._current_frames``)
- ``config.json``  — reason, async_runtime knob snapshot, armed operations,
  progress counters, SLO health report, device-memory snapshot, and the
  ``DL4J_TPU_*`` environment
- ``compiles.json`` — compile-watch ring: every XLA trace of the jitted
  entry points with the arg signature that triggered it
- ``numerics.json`` — recent non-finite loss/grad events + last published
  numerics health per model kind
- ``resilience.json`` — fault plan + injection counts, circuit-breaker
  states, and the resilience event ring (retries, sheds, breaker
  transitions, restores, quarantines)
- ``tenants.json`` — multi-tenant QoS: per-tenant policies (weights,
  tiers, quotas), live bucket levels, and request/token/shed/cost
  counters (a death under load must name who was flooding)
- ``elastic.json`` — elastic posture: device-capacity view, mesh
  reshape history, and the sharded-manifest checkpoint stores
- ``deploy.json`` — versioned serving: deployed versions (lifecycle,
  warmup, in-flight), rollout stage/share and its SLO verdicts
- ``generation.json`` — the generative decode layer: per-pipeline slot
  tables (who was decoding, at which position), queue depth, cache size
- ``sessions.json`` — the durable generation sessions: journal
  attachment, per-session status/seq/fence (what a survivor can adopt)
- ``frontdoor.json`` — the HTTP serving front door: in-flight gate,
  lane routers, and the shared-store fleet view (multi-process mode)
- ``perf.json`` — the cost observatory: per-entry-point FLOPs/bytes,
  live MFU vs. its rolling baseline, and roofline verdicts (was the
  process slow BEFORE it died?)

Kill switch: ``DL4J_TPU_FLIGHT_RECORDER=0`` disables the watchdog and the
crash hooks; explicit ``dump()`` calls always work.
"""
from __future__ import annotations

import atexit
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import traceback
from typing import Dict, List, Optional

from deeplearning4j_tpu.observability.registry import global_registry

DEFAULT_HANG_SECONDS = 300.0
DEFAULT_KEEP_BUNDLES = 8


def _keep_bundles() -> int:
    try:
        return max(1, int(os.environ.get("DL4J_TPU_POSTMORTEM_KEEP",
                                         DEFAULT_KEEP_BUNDLES)))
    except (TypeError, ValueError):
        return DEFAULT_KEEP_BUNDLES


def recorder_enabled() -> bool:
    """Watchdog/hook kill switch (read per call so tests can flip it)."""
    return os.environ.get("DL4J_TPU_FLIGHT_RECORDER", "1") != "0"


def postmortem_dir() -> str:
    return (os.environ.get("DL4J_TPU_POSTMORTEM_DIR")
            or os.path.join(tempfile.gettempdir(), "dl4j-tpu-postmortem"))


#: which progress channels prove an armed operation is alive, keyed by the
#: category before the ":" in its arm kind. An armed fit is only alive if
#: TRAIN STEPS land — inference batches completing elsewhere in the process
#: must not mask a wedged collective (and vice versa). Unknown categories
#: fall back to any-progress.
_PROGRESS_CHANNELS = {
    "fit": ("train_step",),
    "inference_request": ("inference_batch",),
    "generation_request": ("generation_step",),
}


class _Armed:
    """``with recorder.arm("fit:MLN"):`` — armed for the block's duration."""

    __slots__ = ("_rec", "_kind")

    def __init__(self, rec: "FlightRecorder", kind: str):
        self._rec = rec
        self._kind = kind

    def __enter__(self):
        self._rec._arm(self._kind)
        return self._rec

    def __exit__(self, *exc):
        self._rec._disarm(self._kind)
        return False


class FlightRecorder:
    """See module doc. One process-wide instance via
    :func:`global_flight_recorder`; tests construct their own with short
    thresholds."""

    def __init__(self, hang_seconds: Optional[float] = None,
                 check_interval: Optional[float] = None,
                 out_dir: Optional[str] = None):
        if hang_seconds is None:
            try:
                hang_seconds = float(os.environ.get(
                    "DL4J_TPU_HANG_SECONDS", DEFAULT_HANG_SECONDS))
            except ValueError:
                hang_seconds = DEFAULT_HANG_SECONDS
        self.hang_seconds = max(0.05, hang_seconds)
        self.check_interval = (check_interval if check_interval is not None
                               else min(5.0, max(0.25,
                                                 self.hang_seconds / 4)))
        self._out_dir = out_dir
        self._lock = threading.Lock()
        self._armed: Dict[str, int] = {}
        self._armed_since: Dict[str, float] = {}
        self._progress_counts: Dict[str, int] = {}
        self._kind_progress: Dict[str, float] = {}   # channel -> monotonic
        self._last_progress = time.monotonic()       # any-channel fallback
        self._stalled_kinds: set = set()   # one dump per kind per episode
        self._watchdog: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._fatal: Optional[str] = None
        self._dump_seq = 0
        # bundle names carry a per-instance nonce: after
        # reset_global_flight_recorder() the new recorder's seq restarts
        # at 1, and without the nonce it would silently overwrite (and
        # later evict) the previous incident's postmortem-<pid>-001
        self._instance = os.urandom(3).hex()
        self.dumps: List[str] = []     # retained bundle paths, oldest first

    # ------------------------------------------------------------- arming
    def arm(self, kind: str) -> _Armed:
        """Declare work in flight: while any arm() block is open, the
        watchdog treats missing progress as a hang."""
        return _Armed(self, kind)

    def _arm(self, kind: str):
        now = time.monotonic()
        with self._lock:
            idle = not self._armed
            n = self._armed.get(kind, 0)
            self._armed[kind] = n + 1
            if n == 0:
                # a fresh operation starts its own stall clock — a process
                # idle for an hour is not already mid-hang
                self._armed_since[kind] = now
                self._stalled_kinds.discard(kind)
        # hook/watchdog setup is idempotent but takes process-global
        # locks — do it only on the idle→armed transition, not once per
        # serving request (every BATCHED output() arms)
        if idle and recorder_enabled():
            self.install()
            self._ensure_watchdog()

    def _disarm(self, kind: str):
        with self._lock:
            n = self._armed.get(kind, 0) - 1
            if n > 0:
                self._armed[kind] = n
            else:
                self._armed.pop(kind, None)
                self._armed_since.pop(kind, None)
                self._stalled_kinds.discard(kind)

    def progress(self, kind: str = "step"):
        """Heartbeat: a unit of work completed (fit step, device batch).
        ``kind`` is the progress CHANNEL the watchdog matches against
        armed operations (see ``_PROGRESS_CHANNELS``)."""
        now = time.monotonic()
        self._last_progress = now
        # racy writes are fine — these feed the watchdog's staleness read
        # and postmortem context, not accounting
        self._kind_progress[kind] = now
        self._progress_counts[kind] = self._progress_counts.get(kind, 0) + 1

    # ----------------------------------------------------------- watchdog
    def _ensure_watchdog(self):
        if self._watchdog is not None:
            return
        with self._lock:
            if self._watchdog is not None:
                return
            t = threading.Thread(target=self._watch, daemon=True,
                                 name="dl4j-flight-recorder")
            self._watchdog = t
        t.start()

    def _progress_baseline(self, kind: str) -> float:
        """Latest proof-of-life for one armed operation: its relevant
        progress channels (NOT any progress — inference completing must
        not mask a wedged fit) or, for unknown categories, any channel;
        floored at the moment it armed."""
        channels = _PROGRESS_CHANNELS.get(kind.split(":", 1)[0])
        if channels is None:
            last = self._last_progress
        else:
            last = max((self._kind_progress.get(c, 0.0) for c in channels),
                       default=0.0)
        return max(last, self._armed_since.get(kind, 0.0))

    def _watch(self):
        while not self._stop.wait(self.check_interval):
            if not recorder_enabled():
                continue
            now = time.monotonic()
            newly_stalled = []
            with self._lock:
                for kind in sorted(self._armed):
                    stalled_for = now - self._progress_baseline(kind)
                    if stalled_for > self.hang_seconds:
                        if kind not in self._stalled_kinds:
                            self._stalled_kinds.add(kind)
                            newly_stalled.append((kind, stalled_for))
                    else:       # progress resumed: a NEW stall may dump
                        self._stalled_kinds.discard(kind)
            for kind, stalled_for in newly_stalled:
                self._safe_dump(f"hang: no progress for {stalled_for:.1f}s "
                                f"while {kind!r} in flight")

    def stop(self):
        """Terminal: stop the watchdog thread (test teardown / reset) and
        detach from the process-wide crash hooks."""
        self._stop.set()
        global _hook_target
        with _hook_lock:
            if _hook_target is self:
                # fall back to the global recorder (if it isn't us) so a
                # reset never leaves fatal exceptions unrecorded
                _hook_target = (_global_recorder
                                if _global_recorder is not self else None)

    # -------------------------------------------------------- crash hooks
    def install(self) -> "FlightRecorder":
        """Become the target of the process-wide crash hooks. The
        sys/threading excepthook wrappers and the atexit callback are
        installed ONCE per process and dispatch to whichever recorder is
        current — resetting/replacing recorders re-points the dispatch
        instead of wrapping hooks around hooks (which would dump one
        bundle per generation and pin every old recorder alive)."""
        global _hook_target
        with _hook_lock:
            _hook_target = self
        _install_process_hooks()
        return self

    def _on_fatal(self, exc_type, exc):
        self._fatal = f"{exc_type.__name__}: {exc}"
        self._safe_dump(f"fatal_exception:{exc_type.__name__}")

    def _on_thread_fatal(self, args):
        self._fatal = (f"{args.exc_type.__name__} in thread "
                       f"{getattr(args.thread, 'name', '?')}")
        self._safe_dump(f"thread_exception:{args.exc_type.__name__}")

    def _at_exit(self):
        self.stop()
        if os.environ.get("DL4J_TPU_POSTMORTEM_ON_EXIT") == "1":
            self._safe_dump("atexit")

    def _safe_dump(self, reason: str) -> Optional[str]:
        try:
            return self.dump(reason)
        except Exception:       # a broken dump must never mask the crash
            return None

    # ------------------------------------------------------------ dumping
    def dump(self, reason: str = "manual") -> str:
        """Write one postmortem bundle; returns its directory. Sections
        are independent best-effort — a wedged subsystem cannot veto the
        thread stacks that would explain the wedge."""
        with self._lock:
            self._dump_seq += 1
            seq = self._dump_seq
        bundle = os.path.join(
            self._out_dir or postmortem_dir(),
            f"postmortem-{os.getpid()}-{self._instance}-{seq:03d}")
        os.makedirs(bundle, exist_ok=True)

        def section(fname: str, write):
            try:
                write(os.path.join(bundle, fname))
            except Exception as e:
                try:
                    with open(os.path.join(bundle, fname + ".error"),
                              "w") as f:
                        f.write(repr(e))
                except OSError:
                    pass

        from deeplearning4j_tpu.observability.tracing import global_trace_sink
        section("trace.json",
                lambda p: global_trace_sink().export_json(p))
        section("metrics.prom", self._write_metrics)
        section("threads.txt", self._write_threads)
        section("config.json", lambda p: self._write_config(p, reason))
        # the PR-4 observatory: which signatures compiled what (a hang
        # during a retrace storm is a compile, not a collective) and the
        # numerics health at the moment of death
        section("compiles.json", self._write_compiles)
        section("numerics.json", self._write_numerics)
        # the PR-5 resilience layer: what was injected, which circuits
        # were open, and the retry/shed/restore/quarantine event trail —
        # a hang during a chaos run must name the chaos
        section("resilience.json", self._write_resilience)
        # the multi-tenant QoS layer: policies, quota bucket levels,
        # per-tenant counters — a death under a flooding tenant must
        # name who was flooding and who was shed
        section("tenants.json", self._write_tenants)
        # the elastic layer: capacity view, reshape history, and the
        # manifest stores — a death mid-shrink must name the topology
        section("elastic.json", self._write_elastic)
        # the serving layer: deployed versions, rollout stage/share and
        # the SLO verdicts behind them — a death mid-canary must name
        # which model had the traffic
        section("deploy.json", self._write_deploy)
        # the PR-6 cost observatory: per-fn cost/MFU/roofline at the
        # moment of death — a postmortem for "it got slow, then it hung"
        section("perf.json", self._write_perf)
        # the generative decode layer: slot table, positions, queue depth
        # — a hang mid-generation must name which slots were decoding
        section("generation.json", self._write_generation)
        # the durable-session layer: journal attachment, per-session
        # status/seq/fence — a death mid-stream must name which
        # sessions a survivor can adopt (section absent with
        # DL4J_TPU_SESSIONS=0 never exercised)
        section("sessions.json", self._write_sessions)
        # the HTTP front door: in-flight gate, lane routers, and (multi-
        # process mode) the shared fleet view — a death under load must
        # name what the wire surface was doing
        section("frontdoor.json", self._write_frontdoor)
        # the fleet robustness layer: leader lease/term, demotions,
        # store corruption/rebuild evidence, idempotency journal — a
        # death during a fleet chaos run must name who led, under which
        # term, and what was (or was not) executed twice
        section("fleet.json", self._write_fleet)
        # the trace-intelligence layer: the incident's pinned trace ids
        # assembled FLEET-WIDE (via the installed assembler) — a
        # coordinated capture ships the full cross-process request
        # story, not one worker's ring slice
        from deeplearning4j_tpu.observability.trace_store import (
            trace_store_enabled)
        if trace_store_enabled():
            section("traces.json", self._write_traces)
        # the watchtower layer: the ringed registry timeseries (the
        # minutes BEFORE the trip) and the alert lifecycle state at the
        # moment of death — section absent with the switch off
        from deeplearning4j_tpu.observability.timeseries import (
            watchtower_enabled)
        if watchtower_enabled():
            section("timeseries.json", self._write_timeseries)
        if reason.startswith("incident:"):
            # a coordinated peer capture: stamp the fleet-wide incident
            # id INTO the bundle so a postmortem directory groups every
            # worker's view of the same event
            inc_id = reason.split(":", 1)[1].strip()
            section("incident.json", lambda p: _write_json_file(p, {
                "incident_id": inc_id, "reason": reason,
                "pid": os.getpid(), "unix_time": time.time()}))
        try:
            global_registry().counter(
                "dl4j_postmortem_dumps_total",
                "flight-recorder bundles written, by trigger",
                label_names=("trigger",)).labels(
                    trigger=reason.split(":")[0].strip()).inc()
        except Exception:
            pass
        # bounded retention: a polled /debug/dump, a flapping watchdog, or
        # a crash-looping supervisor must not fill the disk — evict the
        # oldest postmortem-* dirs beyond DL4J_TPU_POSTMORTEM_KEEP
        # (default 8) by scanning the DIRECTORY, so bundles from earlier
        # recorder instances / process runs are bounded too
        keep = _keep_bundles()
        base = os.path.dirname(bundle)
        try:
            entries = [os.path.join(base, e) for e in os.listdir(base)
                       if e.startswith("postmortem-")
                       and os.path.isdir(os.path.join(base, e))]
            entries.sort(key=lambda p: (os.path.getmtime(p), p))
            for old in entries[:-keep]:
                shutil.rmtree(old, ignore_errors=True)
        except OSError:
            pass
        with self._lock:
            self.dumps.append(bundle)
            self.dumps = [p for p in self.dumps if os.path.isdir(p)]
        pub = _incident_publisher
        if pub is not None:
            try:
                pub(reason, bundle)
            except Exception:   # a broken publisher never masks the dump
                pass
        return bundle

    @staticmethod
    def _write_compiles(path: str):
        from deeplearning4j_tpu.observability.compile_watch import (
            global_compile_watch)
        with open(path, "w") as f:
            json.dump(global_compile_watch().snapshot(), f, indent=2,
                      default=str)

    @staticmethod
    def _write_numerics(path: str):
        from deeplearning4j_tpu.observability import numerics
        with open(path, "w") as f:
            json.dump(numerics.snapshot(), f, indent=2, default=str)

    @staticmethod
    def _write_resilience(path: str):
        from deeplearning4j_tpu import resilience
        with open(path, "w") as f:
            json.dump(resilience.snapshot(), f, indent=2, default=str)

    @staticmethod
    def _write_tenants(path: str):
        from deeplearning4j_tpu.resilience import qos
        with open(path, "w") as f:
            json.dump(qos.snapshot(), f, indent=2, default=str)

    @staticmethod
    def _write_elastic(path: str):
        from deeplearning4j_tpu.resilience import elastic
        with open(path, "w") as f:
            json.dump(elastic.snapshot(), f, indent=2, default=str)

    @staticmethod
    def _write_deploy(path: str):
        from deeplearning4j_tpu import serving
        with open(path, "w") as f:
            json.dump(serving.snapshot(), f, indent=2, default=str)

    @staticmethod
    def _write_perf(path: str):
        from deeplearning4j_tpu.observability.cost_model import (
            global_cost_model)
        with open(path, "w") as f:
            json.dump(global_cost_model().snapshot(), f, indent=2,
                      default=str)

    @staticmethod
    def _write_generation(path: str):
        # never IMPORT the generation stack from a (possibly wedged)
        # dump path — a process that never used it gets an empty
        # section, not a fresh module-import under the import lock
        import sys as _sys
        gen = _sys.modules.get("deeplearning4j_tpu.parallel.generation")
        pipelines = (gen.GenerationPipeline.live_snapshots()
                     if gen is not None else [])
        with open(path, "w") as f:
            json.dump({"pipelines": pipelines}, f, indent=2, default=str)

    @staticmethod
    def _write_sessions(path: str):
        # sys.modules guard, same rationale as _write_generation
        import sys as _sys
        sm = _sys.modules.get("deeplearning4j_tpu.serving.session")
        payload = (sm.snapshot() if sm is not None
                   else {"enabled": None, "sessions": []})
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, default=str)

    @staticmethod
    def _write_frontdoor(path: str):
        # sys.modules guard, same rationale as _write_generation
        import sys as _sys
        fdm = _sys.modules.get("deeplearning4j_tpu.serving.frontdoor")
        payload = (fdm.snapshot_all() if fdm is not None
                   else {"frontdoors": []})
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, default=str)

    @staticmethod
    def _write_fleet(path: str):
        # sys.modules guard, same rationale as _write_generation
        import sys as _sys
        fdm = _sys.modules.get("deeplearning4j_tpu.serving.frontdoor")
        if fdm is not None:
            payload = fdm.fleet_snapshot()
        else:
            idm = _sys.modules.get(
                "deeplearning4j_tpu.serving.idempotency")
            payload = {"idempotency": (idm.snapshot() if idm is not None
                                       else {}),
                       "frontdoors": []}
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, default=str)

    @staticmethod
    def _write_traces(path: str):
        from deeplearning4j_tpu.observability.trace_store import (
            global_trace_store)
        store = global_trace_store()
        pinned = store.pinned_ids()
        assembler = _trace_assembler
        traces = {}
        for tid in pinned:
            doc = None
            if assembler is not None:
                try:
                    doc = assembler(tid)
                except Exception as e:
                    doc = {"error": repr(e)}
            if doc is None:
                doc = store.get(tid)    # single-process fallback
            if doc is not None:
                traces[tid] = doc
        with open(path, "w") as f:
            json.dump({"pinned": pinned, "recent": store.recent(),
                       "traces": traces}, f, indent=2, default=str)

    @staticmethod
    def _write_timeseries(path: str):
        from deeplearning4j_tpu.observability.timeseries import (
            global_timeseries)
        # sys.modules guard for the watchtower (same rationale as
        # _write_generation): a process that never beat it gets None,
        # not a fresh import under the import lock
        import sys as _sys
        wt = _sys.modules.get(
            "deeplearning4j_tpu.observability.watchtower")
        payload = global_timeseries().snapshot()
        payload["alerts"] = (wt.global_watchtower().alerts.snapshot()
                             if wt is not None else None)
        with open(path, "w") as f:
            json.dump(payload, f, indent=2, default=str)

    @staticmethod
    def _write_metrics(path: str):
        with open(path, "w") as f:
            f.write(global_registry().render_prometheus())

    @staticmethod
    def _write_threads(path: str):
        names = {t.ident: t.name for t in threading.enumerate()}
        lines = []
        for tid, frame in sys._current_frames().items():
            lines.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
            lines.extend(l.rstrip("\n")
                         for l in traceback.format_stack(frame))
            lines.append("")
        with open(path, "w") as f:
            f.write("\n".join(lines))

    def _write_config(self, path: str, reason: str):
        from deeplearning4j_tpu import async_runtime
        with self._lock:
            armed = dict(self._armed)
            progress = dict(self._progress_counts)
        cfg = {
            "reason": reason,
            "unix_time": time.time(),
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "fatal": self._fatal,
            "armed": armed,
            "progress_counts": progress,
            "seconds_since_progress": time.monotonic() - self._last_progress,
            "async_runtime": async_runtime.snapshot(),
            "env": {k: v for k, v in os.environ.items()
                    if k.startswith("DL4J_TPU_")},
        }
        try:        # the SLO view of the moment of death
            from deeplearning4j_tpu.observability.slo import global_slo_engine
            cfg["health"] = global_slo_engine().evaluate()
        except Exception as e:
            cfg["health"] = {"error": repr(e)}
        try:        # HBM at the moment of death (None per device on CPU)
            from deeplearning4j_tpu.observability import device_memory
            cfg["device_memory"] = device_memory.snapshot()
        except Exception as e:
            cfg["device_memory"] = {"error": repr(e)}
        with open(path, "w") as f:
            json.dump(cfg, f, indent=2, default=str)


def _write_json_file(path: str, payload: dict) -> None:
    with open(path, "w") as f:
        json.dump(payload, f, indent=2, default=str)


_global_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()

# coordinated incident capture (fleet observability plane): a process-
# wide hook called after every bundle write with (reason, bundle_path).
# The serving front door wires this to the shared-store incident ledger
# so the LEADER can fan the capture out to every live worker.
_incident_publisher = None


def set_incident_publisher(fn) -> None:
    """Install (or clear, with None) the post-dump incident hook.  The
    hook runs OUTSIDE the recorder's lock, best-effort: a broken
    publisher must never mask the dump that tripped it."""
    global _incident_publisher
    _incident_publisher = fn


# fleet trace assembly for the bundle's traces.json: installed alongside
# the incident publisher (federation.install_incident_publisher); takes
# a trace id, returns the assembled cross-worker doc or None (then the
# local store payload is used)
_trace_assembler = None


def set_trace_assembler(fn) -> None:
    """Install (or clear, with None) the fleet trace assembler the
    bundle's ``traces.json`` section uses for pinned trace ids."""
    global _trace_assembler
    _trace_assembler = fn

# process-wide crash-hook plumbing: ONE set of excepthook wrappers + one
# atexit callback, dispatching to the currently-installed recorder
_hook_target: Optional[FlightRecorder] = None
_hook_lock = threading.Lock()
_process_hooks_installed = False


def _install_process_hooks():
    global _process_hooks_installed
    with _hook_lock:
        if _process_hooks_installed:
            return
        _process_hooks_installed = True
    prev_sys = sys.excepthook

    def _sys_hook(exc_type, exc, tb):
        target = _hook_target
        if (target is not None and recorder_enabled()
                and not issubclass(exc_type,
                                   (KeyboardInterrupt, SystemExit))):
            target._on_fatal(exc_type, exc)
        prev_sys(exc_type, exc, tb)

    sys.excepthook = _sys_hook
    prev_thread = threading.excepthook

    def _thread_hook(args):
        target = _hook_target
        if (target is not None and recorder_enabled()
                and args.exc_type is not SystemExit):
            target._on_thread_fatal(args)
        prev_thread(args)

    threading.excepthook = _thread_hook

    def _at_exit():
        target = _hook_target
        if target is not None:
            target._at_exit()

    atexit.register(_at_exit)


def global_flight_recorder() -> FlightRecorder:
    """THE process-wide recorder every built-in arm/progress point uses."""
    global _global_recorder
    if _global_recorder is None:
        with _recorder_lock:
            if _global_recorder is None:
                _global_recorder = FlightRecorder()
    return _global_recorder


def reset_global_flight_recorder(**kw) -> FlightRecorder:
    """Fresh recorder (test isolation); the old watchdog is stopped and
    the process crash hooks — if installed — re-point to the new one."""
    global _global_recorder, _hook_target
    with _recorder_lock:
        if _global_recorder is not None:
            _global_recorder.stop()
        _global_recorder = FlightRecorder(**kw)
        with _hook_lock:
            if _process_hooks_installed and _hook_target is None:
                _hook_target = _global_recorder
    return _global_recorder
