"""Unified observability core (SURVEY §5.5/J12 north star): a process-wide
metrics registry + structured tracing that every layer — training loops,
``ParallelInference`` serving, data pipeline, collectives, checkpoints —
publishes into, with Prometheus exposition on ``UIServer /metrics`` and
Chrome-trace JSON export for Perfetto.

Quick tour::

    from deeplearning4j_tpu.observability import metrics, span, trace_sink

    reqs = metrics().counter("my_requests_total", "requests", ("route",))
    reqs.labels(route="/infer").inc()

    with span("preprocess", batch=32):
        ...

    print(metrics().render_prometheus())      # scrape payload
    trace_sink().export_json("/tmp/trace.json")   # load in Perfetto

Kill switch: ``DL4J_TPU_METRICS=0`` (instruments and spans become no-ops).
"""
from deeplearning4j_tpu.observability.registry import (
    Counter, Gauge, Histogram, MetricsRegistry, DEFAULT_BUCKETS,
    global_registry, metrics_enabled, on_registry_reset,
    reset_global_registry)
from deeplearning4j_tpu.observability.tracing import (
    Span, SpanRecord, TraceSink, current_span, global_trace_sink,
    reset_global_trace_sink, span)
from deeplearning4j_tpu.observability.straggler import StragglerDetector

#: ergonomic aliases
metrics = global_registry
trace_sink = global_trace_sink

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "DEFAULT_BUCKETS",
    "global_registry", "metrics", "metrics_enabled", "on_registry_reset",
    "reset_global_registry",
    "Span", "SpanRecord", "TraceSink", "current_span", "global_trace_sink",
    "reset_global_trace_sink", "span", "trace_sink",
    "StragglerDetector", "MetricsReportingListener",
]


def __getattr__(name):
    # lazy: MetricsReportingListener lives on the listener bus
    # (optim.listeners) which itself publishes into this package — a lazy
    # re-export avoids the import cycle
    if name == "MetricsReportingListener":
        from deeplearning4j_tpu.optim.listeners import (
            MetricsReportingListener)
        return MetricsReportingListener
    raise AttributeError(name)
