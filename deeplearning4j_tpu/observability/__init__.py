"""Unified observability core (SURVEY §5.5/J12 north star): a process-wide
metrics registry + causal tracing that every layer — training loops,
``ParallelInference`` serving, data pipeline, collectives, checkpoints —
publishes into, with Prometheus exposition on ``UIServer /metrics`` and
Chrome-trace JSON export for Perfetto.

Three pillars:

- **Metrics** (`registry.py`): labeled counters/gauges/histograms with
  reservoir quantiles and OpenMetrics exemplars (tail buckets carry the
  trace_id of a request that landed there).
- **Causal tracing** (`tracing.py`): nested ``span()`` with
  trace_id/span_id/parent_id, explicit cross-thread propagation
  (``current_context`` / ``trace_context`` / ``record_span``), and
  Chrome-trace export with flow events so Perfetto draws request arrows
  across the serving pipeline and prefetch threads.
- **Health** (`slo.py`, `flight_recorder.py`): declarative SLO rules
  driving ``/health`` (503 on failing) and ``/alerts``, plus a hang
  watchdog / crash hook that dumps postmortem bundles (span ring, metrics
  snapshot, all thread stacks, async-runtime config, compile ring,
  numerics snapshot, device memory).
- **Training-health observatory** (`compile_watch.py`, `numerics.py`,
  `device_memory.py`): XLA trace/retrace accounting with the triggering
  arg signatures (``GET /debug/compiles``, retrace-storm SLO rule),
  in-graph non-finite/grad-norm/update-ratio health fused into the train
  step (divergence SLO rule, opt-in skip-on-nonfinite policy), and
  per-device HBM gauges from ``Device.memory_stats()``.
- **Performance observatory** (`cost_model.py`, `profile_capture.py`):
  per-entry-point FLOPs/bytes from ``cost_analysis()`` on every
  (re)compile, live MFU + roofline verdicts against an env-overridable
  peak table (``GET /debug/perf``, perf-regression SLO rule), and
  on-demand device profiling (``GET /debug/profile?steps=N``).

Quick tour::

    from deeplearning4j_tpu.observability import metrics, span, trace_sink

    reqs = metrics().counter("my_requests_total", "requests", ("route",))
    reqs.labels(route="/infer").inc()

    with span("preprocess", batch=32):
        ...

    print(metrics().render_prometheus())      # scrape payload
    trace_sink().export_json("/tmp/trace.json")   # load in Perfetto

Kill switches: ``DL4J_TPU_METRICS=0`` (instruments and spans become
no-ops), ``DL4J_TPU_TRACE=0`` (spans only), ``DL4J_TPU_FLIGHT_RECORDER=0``
(watchdog + crash hooks), ``DL4J_TPU_COMPILE_WATCH=0`` (trace/compile
accounting), ``DL4J_TPU_NUMERICS=0`` (in-graph numerics terms). The full
knob table lives in README "Environment knob reference"
(lint: tools/check_env_knobs.py).
"""
from deeplearning4j_tpu.observability.registry import (
    Counter, Gauge, Histogram, MetricsRegistry, DEFAULT_BUCKETS,
    global_registry, metrics_enabled, on_registry_reset,
    reset_global_registry)
from deeplearning4j_tpu.observability.tracing import (
    Span, SpanRecord, TraceContext, TraceSink, current_context,
    current_span, global_trace_sink, now_us, record_span,
    reset_global_trace_sink, span, trace_context, tracing_enabled)
from deeplearning4j_tpu.observability.trace_store import (
    TraceStore, global_trace_store, reset_global_trace_store,
    store_span_close, store_span_open, trace_store_enabled)
from deeplearning4j_tpu.observability.straggler import StragglerDetector
from deeplearning4j_tpu.observability.flight_recorder import (
    FlightRecorder, global_flight_recorder, reset_global_flight_recorder)
from deeplearning4j_tpu.observability.slo import (
    ErrorRateRule, GaugeThresholdRule, LatencyQuantileRule, SLOEngine,
    SLORule, default_rules, global_slo_engine, reset_global_slo_engine)
from deeplearning4j_tpu.observability.compile_watch import (
    CompileWatch, RetraceStormRule, compile_watch_enabled,
    global_compile_watch, reset_global_compile_watch)
from deeplearning4j_tpu.observability.numerics import (
    DivergenceRule, numerics_enabled, skip_on_nonfinite)
from deeplearning4j_tpu.observability import device_memory
from deeplearning4j_tpu.observability.cost_model import (
    CostModel, cost_model_enabled, global_cost_model,
    reset_global_cost_model)
from deeplearning4j_tpu.observability.slo import PerfRegressionRule
from deeplearning4j_tpu.observability.profile_capture import (
    ProfileCapture, global_profile_capture, profile_enabled,
    reset_global_profile_capture)
from deeplearning4j_tpu.observability.timeseries import (
    TimeseriesStore, global_timeseries, reset_global_timeseries,
    watchtower_enabled)
from deeplearning4j_tpu.observability.watchtower import (
    AlertManager, BurnRateDetector, ChangePointDetector, Detector,
    ThresholdDetector, Watchtower, default_detectors, global_watchtower,
    reset_global_watchtower)

#: ergonomic aliases
metrics = global_registry
trace_sink = global_trace_sink

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "DEFAULT_BUCKETS",
    "global_registry", "metrics", "metrics_enabled", "on_registry_reset",
    "reset_global_registry",
    "Span", "SpanRecord", "TraceContext", "TraceSink", "current_context",
    "current_span", "global_trace_sink", "now_us", "record_span",
    "reset_global_trace_sink", "span", "trace_context", "tracing_enabled",
    "trace_sink",
    "TraceStore", "global_trace_store", "reset_global_trace_store",
    "store_span_close", "store_span_open", "trace_store_enabled",
    "StragglerDetector", "MetricsReportingListener",
    "FlightRecorder", "global_flight_recorder",
    "reset_global_flight_recorder",
    "ErrorRateRule", "GaugeThresholdRule", "LatencyQuantileRule",
    "SLOEngine", "SLORule", "default_rules", "global_slo_engine",
    "reset_global_slo_engine",
    "CompileWatch", "RetraceStormRule", "compile_watch_enabled",
    "global_compile_watch", "reset_global_compile_watch",
    "DivergenceRule", "numerics_enabled", "skip_on_nonfinite",
    "device_memory",
    "CostModel", "cost_model_enabled", "global_cost_model",
    "reset_global_cost_model", "PerfRegressionRule",
    "ProfileCapture", "global_profile_capture", "profile_enabled",
    "reset_global_profile_capture",
    "TimeseriesStore", "global_timeseries", "reset_global_timeseries",
    "watchtower_enabled",
    "AlertManager", "BurnRateDetector", "ChangePointDetector", "Detector",
    "ThresholdDetector", "Watchtower", "default_detectors",
    "global_watchtower", "reset_global_watchtower",
]


def __getattr__(name):
    # lazy: MetricsReportingListener lives on the listener bus
    # (optim.listeners) which itself publishes into this package — a lazy
    # re-export avoids the import cycle
    if name == "MetricsReportingListener":
        from deeplearning4j_tpu.optim.listeners import (
            MetricsReportingListener)
        return MetricsReportingListener
    raise AttributeError(name)
