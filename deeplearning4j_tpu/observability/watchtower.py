"""Watchtower: continuous burn-rate + anomaly detection over the live
registry, feeding an alert lifecycle that closes the detect→capture loop.

Before this module, incidents only opened when the flight-recorder
watchdog tripped on a *hang*, and ``SLOEngine.alerts()`` was a stateless
point-in-time scrape — a latency regression, error burst, or MFU slide
under live traffic went unnoticed until a human read ``/debug/fleet``.
The watchtower is the machine operator (the continuous watch-and-alarm
posture of Abadi et al. arXiv:1605.08695 §9 at serving scale): detectors
run on the sync beat (never the request hot path), alerts walk an
explicit lifecycle, and a firing page-severity alert pins the offending
traces, opens the trace store's incident retention window, and dumps a
flight-recorder bundle whose publisher hook fans the capture fleet-wide
under ONE incident id.

Three detector shapes:

- :class:`BurnRateDetector` — multi-window error-budget burn (the SRE
  fast+slow window pair, env-scaled via ``DL4J_TPU_WATCHTOWER_FAST_S`` /
  ``_SLOW_S`` so drills run in seconds).  Delta-aware over cumulative
  counters; fires only when BOTH windows burn above threshold, so a
  transient blip (fast window only) and a long-ago burst still inside
  the slow window (slow only) both stay quiet.
- :class:`ChangePointDetector` — rolling EWMA mean/variance z-score
  over any sampled value (throughput, p99, shed rate, queue depth,
  train/decode MFU).  The baseline freezes (tiny adoption rate) while
  anomalous so the anomaly cannot absorb itself into the mean, and the
  detector needs ``sustain`` consecutive anomalous samples to fire.
- :class:`ThresholdDetector` — a plain bound on a live value.

Alert lifecycle (:class:`AlertManager`): pending → firing → resolved.
A detector must hold for ``DL4J_TPU_WATCHTOWER_HOLD_S`` before its
pending alert promotes to firing (hold-down), and must stay quiet for
``DL4J_TPU_WATCHTOWER_CLEAR_S`` before a firing alert resolves (flap
damping).  Alerts dedup on their literal rule name (graftlint's
``detector-rule-names`` checker keeps the name set closed); transitions
bump ``dl4j_alerts_total{rule,state}``.

Kill switch: ``DL4J_TPU_WATCHTOWER=0`` (read live, shared with
``timeseries.py``) makes every beat a no-op and restores pre-watchtower
behavior byte-identically.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deeplearning4j_tpu.observability.registry import (global_registry,
                                                       on_registry_reset)
from deeplearning4j_tpu.observability.timeseries import (global_timeseries,
                                                         watchtower_enabled)
from deeplearning4j_tpu.observability.trace_store import (
    global_trace_store, trace_store_enabled)

__all__ = [
    "PAGE", "WARN", "PENDING", "FIRING", "RESOLVED",
    "watchtower_enabled", "watchtower_interval_s", "fast_window_s",
    "slow_window_s", "hold_s", "clear_s", "incident_cooldown_s",
    "Detector", "BurnRateDetector", "ChangePointDetector",
    "ThresholdDetector", "AlertManager", "Watchtower",
    "default_detectors", "global_watchtower", "reset_global_watchtower",
]

#: alert severities — a firing PAGE alert opens an incident (pin traces,
#: open the retention window, dump bundles fleet-wide); WARN only alerts
PAGE, WARN = "page", "warn"

#: alert lifecycle states
PENDING, FIRING, RESOLVED = "pending", "firing", "resolved"


def _env_float(name: str, default: float, floor: float) -> float:
    try:
        return max(floor, float(os.environ.get(name, default)))
    except (TypeError, ValueError):
        return default


def watchtower_interval_s() -> float:
    """Seconds between detector evaluations (rides the sync beat)."""
    return _env_float("DL4J_TPU_WATCHTOWER_INTERVAL_S", 1.0, 0.05)


def fast_window_s() -> float:
    """Burn-rate FAST window (``DL4J_TPU_WATCHTOWER_FAST_S``)."""
    return _env_float("DL4J_TPU_WATCHTOWER_FAST_S", 60.0, 0.5)


def slow_window_s() -> float:
    """Burn-rate SLOW window (``DL4J_TPU_WATCHTOWER_SLOW_S``)."""
    return _env_float("DL4J_TPU_WATCHTOWER_SLOW_S", 300.0, 1.0)


def hold_s() -> float:
    """Hold-down: continuous firing required before pending → firing."""
    return _env_float("DL4J_TPU_WATCHTOWER_HOLD_S", 5.0, 0.0)


def clear_s() -> float:
    """Flap damping: continuous quiet required before firing → resolved."""
    return _env_float("DL4J_TPU_WATCHTOWER_CLEAR_S", 30.0, 0.0)


def incident_cooldown_s() -> float:
    """Minimum seconds between alert-opened incidents on one process —
    page alerts firing inside this window coalesce onto the first
    incident instead of dump-storming the fleet."""
    return _env_float("DL4J_TPU_WATCHTOWER_COOLDOWN_S", 120.0, 0.0)


# lazily-bound alert transition counter (registry-reset safe; created
# only on the first transition, so the OFF path makes no series)
_alert_obs_cache = None
_alert_obs_lock = threading.Lock()
_alert_children: Dict[Tuple[str, str], object] = {}


def _alert_total(rule: str, state: str):
    global _alert_obs_cache
    child = _alert_children.get((rule, state))
    if child is None:
        inst = _alert_obs_cache
        if inst is None:
            with _alert_obs_lock:
                inst = _alert_obs_cache
                if inst is None:
                    inst = global_registry().counter(
                        "dl4j_alerts_total",
                        "watchtower alert lifecycle transitions, by rule "
                        "and entered state",
                        label_names=("rule", "state"))
                    _alert_obs_cache = inst
        child = inst.labels(rule=rule, state=state)
        _alert_children[(rule, state)] = child
    return child


@on_registry_reset
def _drop_alert_obs():
    global _alert_obs_cache
    _alert_obs_cache = None
    _alert_children.clear()


# ------------------------------------------------------------- detectors

class Detector:
    """One named watch rule; subclasses implement :meth:`_evaluate`
    returning ``{"firing": bool, "value": float|None, "detail": str}``.
    The rule name is a LITERAL at every construction site (lint:
    ``detector-rule-names``) — dedup keys and drill grading depend on a
    closed name set."""

    def __init__(self, rule: str, description: str = "",
                 severity: str = WARN):
        if severity not in (PAGE, WARN):
            raise ValueError(f"severity must be {PAGE!r} or {WARN!r}")
        self.rule = rule
        self.description = description
        self.severity = severity

    def observe(self, now: float) -> dict:
        try:
            result = self._evaluate(now)
        # graftlint: disable=typed-errors — a typo'd detector must keep
        # alerting the others, not crash the beat
        except Exception as e:
            result = {"firing": False, "detail": f"detector error: {e!r}"}
        result.setdefault("firing", False)
        result["rule"] = self.rule
        result["severity"] = self.severity
        if self.description:
            result.setdefault("description", self.description)
        return result

    def _evaluate(self, now: float) -> dict:
        raise NotImplementedError


class BurnRateDetector(Detector):
    """Multi-window error-budget burn over cumulative counters.

    Each evaluation samples ``(errors_cum, requests_cum)`` — by default
    the 5xx children vs all children of ``requests_metric``, or a
    custom ``totals_fn`` (the fleet detectors sum a federated scrape) —
    into an internal ring, then grades the windowed error ratio against
    ``budget`` for the fast AND slow windows.  ``burn = ratio/budget``;
    both windows must burn ≥ ``threshold`` with ≥ ``min_requests`` in
    the fast window to fire."""

    def __init__(self, rule: str, requests_metric: str =
                 "dl4j_http_requests_total",
                 errors_metric: Optional[str] = None,
                 totals_fn: Optional[Callable[[], Tuple[float, float]]]
                 = None,
                 budget: float = 0.02, threshold: float = 10.0,
                 fast_s: Optional[float] = None,
                 slow_s: Optional[float] = None,
                 min_requests: float = 10.0,
                 description: str = "", severity: str = PAGE):
        super().__init__(rule, description or
                         f"error-budget burn of {requests_metric} "
                         f"(budget {budget:.2%})", severity)
        self.requests_metric = requests_metric
        self.errors_metric = errors_metric
        self.totals_fn = totals_fn
        self.budget = float(budget)
        self.threshold = float(threshold)
        self._fast_s = fast_s
        self._slow_s = slow_s
        self.min_requests = float(min_requests)
        self._ring: deque = deque(maxlen=4096)

    @staticmethod
    def _counter_total(registry, name: str,
                       only_5xx: bool = False) -> float:
        inst = registry.get(name)
        if inst is None:
            return 0.0
        total = 0.0
        if only_5xx:
            idx = (inst.label_names.index("code")
                   if "code" in inst.label_names else None)
            for lvals, child in inst.series():
                if idx is not None and str(lvals[idx]).startswith("5"):
                    total += child.value
            return total
        return sum(child.value for _l, child in inst.series())

    def _totals(self) -> Tuple[float, float]:
        if self.totals_fn is not None:
            return self.totals_fn()
        reg = global_registry()
        requests = self._counter_total(reg, self.requests_metric)
        if self.errors_metric is not None:
            errors = self._counter_total(reg, self.errors_metric)
        else:
            errors = self._counter_total(reg, self.requests_metric,
                                         only_5xx=True)
        return errors, requests

    def _window_ratio(self, seconds: float,
                      now: float) -> Tuple[Optional[float], float]:
        """(error_ratio, request_delta) over the window, reset-aware:
        a cumulative total dropping (registry reset) truncates the
        window at the reset point."""
        cutoff = now - seconds
        samples = [s for s in self._ring if s[0] >= cutoff]
        if len(samples) < 2:
            return None, 0.0
        base_e, base_r = samples[0][1], samples[0][2]
        d_err = d_req = 0.0
        prev_e, prev_r = base_e, base_r
        for _ts, e, r in samples[1:]:
            if r >= prev_r and e >= prev_e:
                d_err += e - prev_e
                d_req += r - prev_r
            prev_e, prev_r = e, r
        if d_req <= 0:
            return None, 0.0
        return d_err / d_req, d_req

    def _evaluate(self, now: float) -> dict:
        errors, requests = self._totals()
        self._ring.append((now, float(errors), float(requests)))
        slow = self._slow_s if self._slow_s is not None else slow_window_s()
        fast = self._fast_s if self._fast_s is not None else fast_window_s()
        while self._ring and self._ring[0][0] < now - 2 * slow:
            self._ring.popleft()
        fast_ratio, fast_req = self._window_ratio(fast, now)
        slow_ratio, _slow_req = self._window_ratio(slow, now)
        if fast_ratio is None or slow_ratio is None \
                or fast_req < self.min_requests:
            return {"firing": False, "detail": "insufficient data"}
        fast_burn = fast_ratio / self.budget
        slow_burn = slow_ratio / self.budget
        firing = (fast_burn >= self.threshold
                  and slow_burn >= self.threshold)
        return {"firing": firing, "value": fast_burn,
                "fast_burn": round(fast_burn, 3),
                "slow_burn": round(slow_burn, 3),
                "threshold": self.threshold,
                "detail": f"burn fast={fast_burn:.1f}x "
                          f"slow={slow_burn:.1f}x of {self.budget:.2%} "
                          f"budget"}


class ChangePointDetector(Detector):
    """Rolling EWMA z-score change-point over any sampled value.

    ``value_fn`` returns the current value (None = no data this beat).
    After ``min_samples`` warmup, a sample more than ``z`` deviations
    from the EWMA mean in ``direction`` is anomalous; ``sustain``
    consecutive anomalous samples fire.  While anomalous the baseline
    adopts at ``alpha/20`` so a step change cannot absorb itself into
    the mean before the alert fires — but a genuinely new regime is
    eventually adopted and the alert resolves."""

    def __init__(self, rule: str, value_fn: Callable[[float],
                                                     Optional[float]],
                 direction: str = "up", z: float = 4.0,
                 alpha: float = 0.25, min_samples: int = 12,
                 sustain: int = 3, min_sigma: float = 1e-9,
                 rel_floor: float = 0.05,
                 description: str = "", severity: str = WARN):
        if direction not in ("up", "down"):
            raise ValueError("direction must be 'up' or 'down'")
        super().__init__(rule, description, severity)
        self.value_fn = value_fn
        self.direction = direction
        self.z = float(z)
        self.alpha = float(alpha)
        self.min_samples = int(min_samples)
        self.sustain = int(sustain)
        self.min_sigma = float(min_sigma)
        self.rel_floor = float(rel_floor)
        self._mean: Optional[float] = None
        self._var = 0.0
        self._n = 0
        self._streak = 0

    def _evaluate(self, now: float) -> dict:
        value = self.value_fn(now)
        if value is None or value != value:
            return {"firing": False, "detail": "no data"}
        value = float(value)
        if self._mean is None:
            self._mean, self._var, self._n = value, 0.0, 1
            return {"firing": False, "value": value, "detail": "warmup"}
        sigma = max(self._var ** 0.5, self.rel_floor * abs(self._mean),
                    self.min_sigma)
        score = (value - self._mean) / sigma
        anomalous = (self._n >= self.min_samples
                     and (score >= self.z if self.direction == "up"
                          else score <= -self.z))
        # EWMA update — frozen to a trickle while anomalous so the
        # anomaly cannot vote itself into the baseline
        alpha = self.alpha / 20.0 if anomalous else self.alpha
        delta = value - self._mean
        self._mean += alpha * delta
        self._var = (1 - alpha) * (self._var + alpha * delta * delta)
        self._n += 1
        self._streak = self._streak + 1 if anomalous else 0
        firing = self._streak >= self.sustain
        return {"firing": firing, "value": value,
                "zscore": round(score, 2), "mean": self._mean,
                "streak": self._streak,
                "detail": f"value {value:.4g} vs EWMA {self._mean:.4g} "
                          f"(z={score:+.1f}, {self.direction})"}


class ThresholdDetector(Detector):
    """A plain live-value bound: fires while the value crosses it."""

    def __init__(self, rule: str, value_fn: Callable[[float],
                                                     Optional[float]],
                 firing_above: Optional[float] = None,
                 firing_below: Optional[float] = None,
                 description: str = "", severity: str = WARN):
        if (firing_above is None) == (firing_below is None):
            raise ValueError("exactly one of firing_above/firing_below")
        super().__init__(rule, description, severity)
        self.value_fn = value_fn
        self.firing_above = firing_above
        self.firing_below = firing_below

    def _evaluate(self, now: float) -> dict:
        value = self.value_fn(now)
        if value is None or value != value:
            return {"firing": False, "detail": "no data"}
        value = float(value)
        if self.firing_above is not None:
            firing = value > self.firing_above
            bound = f"> {self.firing_above:g}"
        else:
            firing = value < self.firing_below
            bound = f"< {self.firing_below:g}"
        return {"firing": firing, "value": value,
                "detail": f"value {value:.4g} (fires {bound})"}


# --------------------------------------------------------- default rules

def _http_p99(now: float) -> Optional[float]:
    return global_timeseries().latest("dl4j_http_latency_seconds:p99")


def _http_throughput(now: float) -> Optional[float]:
    return global_timeseries().rate("dl4j_http_requests_total",
                                    slow_window_s(), now)


def _shed_rate(now: float) -> Optional[float]:
    ts = global_timeseries()
    window = fast_window_s()
    shed = sum(filter(None, (
        ts.delta("dl4j_http_shed_total", window, now),
        ts.delta("dl4j_inference_shed_total", window, now),
        ts.delta("dl4j_decode_shed_total", window, now))))
    req = ts.delta("dl4j_http_requests_total", window, now)
    if req is None or req + shed <= 0:
        return None
    return shed / (req + shed)


def _queue_depth(now: float) -> Optional[float]:
    ts = global_timeseries()
    depths = [d for d in (ts.latest("dl4j_inference_queue_depth"),
                          ts.latest("dl4j_decode_queue_depth"))
              if d is not None]
    return max(depths) if depths else None


def _worst_mfu_ratio(now: float) -> Optional[float]:
    """Worst live-MFU / rolling-baseline ratio across timed entry points
    (train steps and decode loops both land here via the cost model)."""
    from deeplearning4j_tpu.observability.cost_model import (
        global_cost_model)
    worst = None
    for _fn, mfu, baseline, samples in global_cost_model(
            ).regression_view():
        if samples < 8 or not baseline:
            continue
        ratio = mfu / baseline
        if worst is None or ratio < worst:
            worst = ratio
    return worst


def default_detectors() -> List[Detector]:
    """The per-process watch rules every serving worker runs: the HTTP
    error-budget burn (page), change-points on throughput / p99 / shed
    rate / queue depth / MFU, and a hard queue-depth threshold."""
    return [
        BurnRateDetector(
            "watch_http_error_burn",
            description="front-door 5xx burn over the fast+slow window "
                        "pair (2% error budget)",
            severity=PAGE),
        ChangePointDetector(
            "watch_p99_shift", _http_p99, direction="up",
            description="front-door p99 latency step change vs its own "
                        "rolling baseline",
            severity=PAGE),
        ChangePointDetector(
            "watch_throughput_drop", _http_throughput, direction="down",
            description="front-door request rate collapsed vs its own "
                        "rolling baseline",
            severity=WARN),
        ChangePointDetector(
            "watch_shed_rate_spike", _shed_rate, direction="up",
            description="admission sheds (door + serving queue + decode "
                        "queue) spiking vs baseline",
            severity=WARN),
        ChangePointDetector(
            "watch_queue_depth_spike", _queue_depth, direction="up",
            description="serving/decode queue depth step change",
            severity=WARN),
        ChangePointDetector(
            "watch_mfu_slide", _worst_mfu_ratio, direction="down",
            description="worst entry-point MFU sliding under its rolling "
                        "baseline (train/decode perf regression)",
            severity=WARN),
        ThresholdDetector(
            "watch_queue_depth_limit", _queue_depth, firing_above=256,
            description="serving/decode queue depth past the hard bound "
                        "(the SLO failing threshold)",
            severity=WARN),
    ]


# --------------------------------------------------------- alert lifecycle

class AlertManager:
    """The pending → firing → resolved state machine, dedup-keyed on
    the literal rule name, with hold-down and flap damping."""

    _RESOLVED_KEEP = 16
    _TRANSITIONS_KEEP = 64

    def __init__(self):
        self._lock = threading.Lock()
        self._active: Dict[str, dict] = {}     # rule -> alert record
        self._resolved: deque = deque(maxlen=self._RESOLVED_KEEP)
        self._transitions: deque = deque(maxlen=self._TRANSITIONS_KEEP)

    def _transition(self, alert: dict, to: str, now: float) -> dict:
        rec = {"rule": alert["rule"], "from": alert.get("state"),
               "to": to, "at": now, "severity": alert["severity"]}
        alert["state"] = to
        alert["since"] = now
        self._transitions.append(rec)
        try:
            _alert_total(alert["rule"], to).inc()
        # graftlint: disable=typed-errors — metrics must never break the
        # lifecycle walk
        except Exception:
            pass
        return rec

    def observe(self, results: Sequence[dict],
                now: Optional[float] = None) -> List[dict]:
        """Feed one beat of detector results; returns the transitions
        that happened this beat."""
        if now is None:
            now = time.time()
        out: List[dict] = []
        with self._lock:
            for res in results:
                rule = res.get("rule")
                if not rule:
                    continue
                firing = bool(res.get("firing"))
                alert = self._active.get(rule)
                if alert is None:
                    if not firing:
                        continue
                    alert = {"rule": rule, "state": None,
                             "severity": res.get("severity", WARN),
                             "started": now, "last_firing": now}
                    self._active[rule] = alert
                    out.append(self._transition(alert, PENDING, now))
                alert["value"] = res.get("value")
                alert["detail"] = res.get("detail")
                if res.get("description"):
                    alert["description"] = res["description"]
                if firing:
                    alert["last_firing"] = now
                state = alert["state"]
                if state == PENDING:
                    if not firing:
                        # blip shorter than the hold-down: drop silently
                        del self._active[rule]
                    elif now - alert["started"] >= hold_s():
                        out.append(self._transition(alert, FIRING, now))
                elif state == FIRING:
                    if not firing and \
                            now - alert["last_firing"] >= clear_s():
                        out.append(self._transition(alert, RESOLVED, now))
                        alert["resolved_at"] = now
                        self._resolved.append(alert)
                        del self._active[rule]
        return out

    def firing(self) -> List[dict]:
        with self._lock:
            return [dict(a) for a in self._active.values()
                    if a["state"] == FIRING]

    def snapshot(self) -> dict:
        with self._lock:
            active = [dict(a) for a in self._active.values()]
            return {
                "firing": [a for a in active if a["state"] == FIRING],
                "pending": [a for a in active if a["state"] == PENDING],
                "resolved": [dict(a) for a in self._resolved],
                "transitions": list(self._transitions),
            }

    def clear(self):
        with self._lock:
            self._active.clear()
            self._resolved.clear()
            self._transitions.clear()


# -------------------------------------------------------------- watchtower

class Watchtower:
    """Detectors + alert lifecycle + the detect→capture closure.

    ``beat()`` rides the front door's sync loop (and the alert routes,
    throttled) — it scrapes the timeseries rings, evaluates every
    detector, walks the alert lifecycle, and on a page-severity alert
    entering ``firing`` pins the offending retained traces, opens the
    trace store's incident window, and dumps a flight-recorder bundle
    with ``reason="alert:<rule>"`` — the recorder's incident-publisher
    hook (fleet mode) turns that into ONE shared incident the leader
    fans out."""

    def __init__(self, detectors: Optional[Sequence[Detector]] = None,
                 scrape: bool = True):
        self.detectors: List[Detector] = list(
            detectors if detectors is not None else default_detectors())
        self.alerts = AlertManager()
        self._scrape = bool(scrape)
        self._beat_lock = threading.Lock()
        self._last_beat = 0.0
        self._incident_at = 0.0
        self.last_incident_reason: Optional[str] = None

    def beat(self, now: Optional[float] = None,
             force: bool = False) -> List[dict]:
        """One throttled evaluation pass; returns this beat's alert
        transitions (empty when throttled or killed)."""
        if not watchtower_enabled():
            return []
        if now is None:
            now = time.time()
        with self._beat_lock:
            if not force and now - self._last_beat \
                    < watchtower_interval_s():
                return []
            self._last_beat = now
        if self._scrape:
            global_timeseries().maybe_scrape(now)
        results = [d.observe(now) for d in self.detectors]
        transitions = self.alerts.observe(results, now)
        self._close_loop(transitions, now)
        return transitions

    # ------------------------------------------------ detect→capture loop
    def _offending_trace_ids(self, limit: int = 8) -> List[str]:
        """Recent retained traces kept for cause (error / slow / tail —
        anything but a plain sample): the evidence a page should pin."""
        ids: List[str] = []
        for rec in global_trace_store().recent(limit=64):
            reason = str(rec.get("reason") or "")
            if rec.get("error") or reason.startswith(("error", "slow",
                                                      "tail")):
                ids.append(rec["trace_id"])
                if len(ids) >= limit:
                    break
        return ids

    def _close_loop(self, transitions: List[dict], now: float):
        pages = [t for t in transitions
                 if t["to"] == FIRING and t.get("severity") == PAGE]
        if not pages:
            return
        if now - self._incident_at < incident_cooldown_s():
            return                      # coalesce onto the open incident
        self._incident_at = now
        reason = "alert:" + pages[0]["rule"]
        self.last_incident_reason = reason
        if trace_store_enabled():
            st = global_trace_store()
            for tid in self._offending_trace_ids():
                st.pin(tid)
            st.open_incident_window()
        try:
            from deeplearning4j_tpu.observability.flight_recorder import (
                global_flight_recorder, recorder_enabled)
            if recorder_enabled():
                global_flight_recorder().dump(reason)
        # graftlint: disable=typed-errors — an unwritable postmortem dir
        # must not break the alert lifecycle
        except Exception:
            pass

    # ------------------------------------------------------------- queries
    def snapshot(self) -> dict:
        return {
            "enabled": watchtower_enabled(),
            "interval_s": watchtower_interval_s(),
            "detectors": [{"rule": d.rule, "severity": d.severity,
                           "description": d.description}
                          for d in self.detectors],
            "last_incident_reason": self.last_incident_reason,
            **self.alerts.snapshot(),
        }


_global_tower: Optional[Watchtower] = None
_tower_lock = threading.Lock()


def global_watchtower() -> Watchtower:
    """THE process-wide watchtower the sync beat and alert routes use."""
    global _global_tower
    if _global_tower is None:
        with _tower_lock:
            if _global_tower is None:
                _global_tower = Watchtower()
    return _global_tower


def reset_global_watchtower(**kw) -> Watchtower:
    global _global_tower
    with _tower_lock:
        _global_tower = Watchtower(**kw)
    return _global_tower


@on_registry_reset
def _clear_tower_state():
    # fresh registry = fresh cumulative totals; stale detector baselines
    # and alert since-timestamps would span two lifetimes
    if _global_tower is not None:
        _global_tower.alerts.clear()
