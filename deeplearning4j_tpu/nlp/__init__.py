"""NLP: word/paragraph embeddings, tokenization, vocab
(ref: deeplearning4j-nlp — SURVEY D15)."""
from deeplearning4j_tpu.nlp.tokenization import (CommonPreprocessor,
                                                 DefaultTokenizerFactory)
from deeplearning4j_tpu.nlp.sentence import (BasicLineIterator,
                                             CollectionSentenceIterator)
from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import Word2Vec
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.glove import Glove
from deeplearning4j_tpu.nlp.fasttext import FastText
from deeplearning4j_tpu.nlp.serializer import WordVectorSerializer

__all__ = ["DefaultTokenizerFactory", "CommonPreprocessor",
           "BasicLineIterator", "CollectionSentenceIterator",
           "VocabCache", "VocabWord", "Word2Vec", "ParagraphVectors",
           "Glove", "FastText", "WordVectorSerializer"]
