"""Word2Vec: SkipGram + CBOW with negative sampling.

Reference: ``org.deeplearning4j.models.word2vec.Word2Vec`` (+ Builder) whose
hot loop is the native ``sg``/``cbow`` declarable ops in libnd4j (SURVEY D15,
N3). TPU-first replacement: training pairs are generated on the host in
large batches, and the SGNS update is ONE jitted program per batch — embed
gathers, a (B, neg+1) dot-product block on the MXU, and scatter-add updates —
instead of per-word native calls.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.sentence import (CollectionSentenceIterator,
                                             SentenceIterator)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache


def _pad_batch(chunk, batch_size, negative, V, table, rng):
    """Pad a trailing partial batch to the fixed batch size with zero-weight
    rows — the jitted step then compiles exactly once per batch shape."""
    negs = rng.choice(V, size=(len(chunk), negative), p=table).astype(np.int32)
    n = len(chunk)
    weights = np.ones(n, dtype=np.float32)
    if n < batch_size:
        pad = batch_size - n
        chunk = np.concatenate([chunk, np.zeros((pad, 2), np.int32)])
        negs = np.concatenate([negs, np.zeros((pad, negative), np.int32)])
        weights = np.concatenate([weights, np.zeros(pad, np.float32)])
    return chunk, negs, weights


def _cos(a: np.ndarray, b: np.ndarray) -> float:
    """Cosine similarity with zero-vector guard (shared by the nlp lookups)."""
    return float(a @ b / (np.linalg.norm(a) * np.linalg.norm(b) + 1e-12))


class Word2Vec:
    """Builder-configured trainer + lookup table (ref API: Word2Vec.Builder
    ... .build(); fit(); wordsNearest; similarity; getWordVectorMatrix)."""

    def __init__(self, layer_size=100, window_size=5, min_word_frequency=1,
                 iterations=1, epochs=1, negative=5, learning_rate=0.025,
                 min_learning_rate=1e-4, sample=1e-3, seed=42,
                 batch_size=2048, cbow=False,
                 iterator: Optional[SentenceIterator] = None,
                 tokenizer_factory=None):
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.iterations = iterations
        self.epochs = epochs
        self.negative = negative
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.sample = sample
        self.seed = seed
        self.batch_size = batch_size
        self.cbow = cbow
        self.iterator = iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[np.ndarray] = None     # (V, D) word vectors
        self.syn1neg: Optional[np.ndarray] = None  # (V, D) output vectors

    # ---------------------------------------------------------------- builder
    class Builder:
        def __init__(self):
            self._kw = {}

        def _set(self, k, v):
            self._kw[k] = v
            return self

        def layer_size(self, v): return self._set("layer_size", v)
        layerSize = layer_size
        def window_size(self, v): return self._set("window_size", v)
        windowSize = window_size
        def min_word_frequency(self, v): return self._set("min_word_frequency", v)
        minWordFrequency = min_word_frequency
        def iterations(self, v): return self._set("iterations", v)
        def epochs(self, v): return self._set("epochs", v)
        def negative_sample(self, v): return self._set("negative", v)
        negativeSample = negative_sample
        def learning_rate(self, v): return self._set("learning_rate", v)
        learningRate = learning_rate
        def min_learning_rate(self, v): return self._set("min_learning_rate", v)
        minLearningRate = min_learning_rate
        def sampling(self, v): return self._set("sample", v)
        def seed(self, v): return self._set("seed", v)
        def batch_size(self, v): return self._set("batch_size", v)
        batchSize = batch_size
        def elements_learning_algorithm(self, name):
            return self._set("cbow", str(name).lower() == "cbow")
        elementsLearningAlgorithm = elements_learning_algorithm
        def iterate(self, it): return self._set("iterator", it)
        def tokenizer_factory(self, tf): return self._set("tokenizer_factory", tf)
        tokenizerFactory = tokenizer_factory

        def build(self) -> "Word2Vec":
            return Word2Vec(**self._kw)

    # ---------------------------------------------------------------- training
    def _corpus_indices(self, token_streams) -> List[np.ndarray]:
        sents = []
        for toks in token_streams:
            idx = [self.vocab.index_of(t) for t in toks]
            idx = np.array([i for i in idx if i >= 0], dtype=np.int32)
            if len(idx) >= 2:
                sents.append(idx)
        return sents

    def _training_pairs(self, sents, rng) -> np.ndarray:
        """(N, 2) [center, context] pairs with dynamic window + subsampling."""
        keep = self.vocab.subsample_keep_prob(self.sample)
        pairs = []
        for idx in sents:
            if keep is not None:
                idx = idx[rng.rand(len(idx)) < keep[idx]]
            n = len(idx)
            if n < 2:
                continue
            # dynamic window like word2vec.c: b ~ U[1, window]
            for pos in range(n):
                w = rng.randint(1, self.window_size + 1)
                lo, hi = max(0, pos - w), min(n, pos + w + 1)
                for c in range(lo, hi):
                    if c != pos:
                        pairs.append((idx[pos], idx[c]))
        if not pairs:
            return np.zeros((0, 2), dtype=np.int32)
        return np.asarray(pairs, dtype=np.int32)

    def _build_step(self):
        import jax
        import jax.numpy as jnp

        def sg_step(syn0, syn1, acc0, acc1, center, context, negs, lr,
                    weights):
            """One SGNS batch: B centers, B contexts, (B, neg) negatives.

            Per-pair gradients are scatter-summed per table row and applied
            with Adagrad row scaling. The reference's native kernel applies
            pairs sequentially against fresh vectors; a plain stale-vector
            sum multiplies the effective lr by a word's hit count (divergence
            on small vocabs) while a plain mean starves it — Adagrad's
            sqrt-accumulator normalization handles both regimes."""
            v_c = syn0[center]                         # (B, D)
            tgt = jnp.concatenate([context[:, None], negs], axis=1)  # (B,1+neg)
            v_t = syn1[tgt]                            # (B, 1+neg, D)
            score = jnp.einsum("bd,bkd->bk", v_c, v_t)
            label = jnp.zeros_like(score).at[:, 0].set(1.0)
            g = label - jax.nn.sigmoid(score)          # (B, 1+neg)
            # drop negatives that collide with the true context (word2vec.c's
            # `if target == word continue` — matters a lot for small vocabs)
            collide = jnp.concatenate(
                [jnp.zeros((negs.shape[0], 1), bool),
                 negs == context[:, None]], axis=1)
            g = jnp.where(collide, 0.0, g)
            g = g * weights[:, None]   # zero rows padding the last batch
            d_vc = jnp.einsum("bk,bkd->bd", g, v_t)
            d_vt = jnp.einsum("bk,bd->bkd", g, v_c).reshape(-1, v_c.shape[-1])
            flat_t = tgt.reshape(-1)
            G0 = jnp.zeros_like(syn0).at[center].add(d_vc)
            G1 = jnp.zeros_like(syn1).at[flat_t].add(d_vt)
            acc0 = acc0 + G0 * G0
            acc1 = acc1 + G1 * G1
            syn0 = syn0 + lr * G0 * jax.lax.rsqrt(acc0 + 1e-10)
            syn1 = syn1 + lr * G1 * jax.lax.rsqrt(acc1 + 1e-10)
            return syn0, syn1, acc0, acc1

        def cbow_step(syn0, syn1, acc0, acc1, center, context, negs, lr,
                      weights):
            """CBOW with window collapsed to one context word per pair keeps
            the same batch layout; mean-of-window is approximated by the
            pair-expansion (each context contributes an update)."""
            return sg_step(syn0, syn1, acc0, acc1, context, center, negs, lr,
                           weights)

        return jax.jit(cbow_step if self.cbow else sg_step,
                       donate_argnums=(0, 1, 2, 3))

    def fit(self):
        """Build vocab + train (ref: Word2Vec#fit)."""
        import jax.numpy as jnp
        rng = np.random.RandomState(self.seed)
        token_streams = [self.tokenizer_factory.create(s).get_tokens()
                         for s in self.iterator]
        self.vocab = VocabCache.build(token_streams, self.min_word_frequency)
        V, D = self.vocab.num_words(), self.layer_size
        if V == 0:
            raise ValueError("empty vocabulary")
        syn0 = jnp.asarray((rng.rand(V, D).astype(np.float32) - 0.5) / D)
        syn1 = jnp.zeros((V, D), dtype=jnp.float32)
        acc0 = jnp.zeros((V, D), dtype=jnp.float32)
        acc1 = jnp.zeros((V, D), dtype=jnp.float32)
        table = self.vocab.unigram_table()
        step = self._build_step()
        sents = self._corpus_indices(token_streams)
        total_steps = max(self.epochs * self.iterations, 1)
        done = 0
        for _ in range(self.epochs):
            for _ in range(self.iterations):
                lr = max(self.min_learning_rate,
                         self.learning_rate * (1 - done / total_steps))
                pairs = self._training_pairs(sents, rng)
                for off in range(0, len(pairs), self.batch_size):
                    chunk = pairs[off:off + self.batch_size]
                    chunk, negs, weights = _pad_batch(
                        chunk, self.batch_size, self.negative, V, table, rng)
                    syn0, syn1, acc0, acc1 = step(
                        syn0, syn1, acc0, acc1,
                        jnp.asarray(chunk[:, 0]),
                        jnp.asarray(chunk[:, 1]),
                        jnp.asarray(negs),
                        np.float32(lr),
                        jnp.asarray(weights))
                done += 1
        self.syn0 = np.asarray(syn0)
        self.syn1neg = np.asarray(syn1)
        return self

    # ----------------------------------------------------------------- lookup
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self.syn0[i]

    getWordVector = get_word_vector
    getWordVectorMatrix = get_word_vector

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    hasWord = has_word

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        return _cos(va, vb)

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            v = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
        else:
            v = np.asarray(word_or_vec)
            exclude = set()
        if v is None:
            return []
        norms = self.syn0 / (np.linalg.norm(self.syn0, axis=1, keepdims=True)
                             + 1e-12)
        sims = norms @ (v / (np.linalg.norm(v) + 1e-12))
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at_index(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out

    wordsNearest = words_nearest

    @staticmethod
    def from_sentences(sentences: Sequence[str], **kwargs) -> "Word2Vec":
        """Convenience: build + fit from raw sentences."""
        w2v = Word2Vec(iterator=CollectionSentenceIterator(sentences), **kwargs)
        return w2v.fit()
