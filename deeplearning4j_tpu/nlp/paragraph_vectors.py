"""ParagraphVectors (doc2vec), PV-DBOW flavor.

Reference: ``org.deeplearning4j.models.paragraphvectors.ParagraphVectors``
(SURVEY D15). PV-DBOW: each label/document vector is trained to predict the
words of its document via the same SGNS objective as Word2Vec — here the doc
vectors simply join the jitted SGNS batch as extra "center" rows.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word2vec import Word2Vec, _pad_batch


class LabelledDocument:
    """ref: text.documentiterator.LabelledDocument."""

    def __init__(self, content: str, labels):
        self.content = content
        self.labels = [labels] if isinstance(labels, str) else list(labels)


class ParagraphVectors(Word2Vec):
    def __init__(self, documents: Optional[Sequence[LabelledDocument]] = None,
                 **kwargs):
        iterator = kwargs.pop("iterator", None)
        super().__init__(**kwargs)
        self.documents = list(documents or [])
        if iterator is not None and not self.documents:
            # reference behavior: iterate(SentenceIterator) labels each
            # sentence as its own document DOC_<n>
            self.documents = [LabelledDocument(s, f"DOC_{i}")
                              for i, s in enumerate(iterator)]
        self.doc_vectors: Dict[str, np.ndarray] = {}

    class Builder(Word2Vec.Builder):
        def iterate_documents(self, docs):
            return self._set("documents", docs)

        def build(self) -> "ParagraphVectors":
            return ParagraphVectors(**self._kw)

    def fit(self):
        import jax.numpy as jnp
        if self.cbow:
            # cbow_step swaps center/context, which would index doc rows
            # (>= V) into the V-row syn1 table
            raise NotImplementedError(
                "ParagraphVectors implements PV-DBOW only; PV-DM (cbow) is "
                "not supported — construct without cbow=True")
        rng = np.random.RandomState(self.seed)
        tf = self.tokenizer_factory
        doc_tokens = [tf.create(d.content).get_tokens() for d in self.documents]
        self.vocab = VocabCache.build(doc_tokens, self.min_word_frequency)
        V, D = self.vocab.num_words(), self.layer_size
        if V == 0:
            raise ValueError("empty vocabulary (no documents, or all words "
                             "below min_word_frequency)")
        labels = []
        for d in self.documents:
            labels.extend(l for l in d.labels if l not in labels)
        L = len(labels)
        self._labels = labels
        # rows [0,V) = words, rows [V, V+L) = doc vectors — one table, one
        # jitted step for both (the reference trains them jointly too)
        syn0 = jnp.asarray((rng.rand(V + L, D).astype(np.float32) - 0.5) / D)
        syn1 = jnp.zeros((V, D), dtype=jnp.float32)
        acc0 = jnp.zeros((V + L, D), dtype=jnp.float32)
        acc1 = jnp.zeros((V, D), dtype=jnp.float32)
        table = self.vocab.unigram_table()
        step = self._build_step()

        label_idx = {l: V + i for i, l in enumerate(labels)}
        keep = self.vocab.subsample_keep_prob(self.sample)
        base_pairs = []
        for d, toks in zip(self.documents, doc_tokens):
            widx = [self.vocab.index_of(t) for t in toks]
            widx = [i for i in widx if i >= 0]
            for l in d.labels:
                li = label_idx[l]
                base_pairs.extend((li, w) for w in widx)
        base_pairs = np.asarray(base_pairs, dtype=np.int32)
        for _ in range(max(self.epochs, 1) * max(self.iterations, 1)):
            if keep is not None and len(base_pairs):
                # frequent-word subsampling per pass, as Word2Vec does —
                # without it every doc vector aligns with the stopwords
                mask = rng.rand(len(base_pairs)) < keep[base_pairs[:, 1]]
                pairs = base_pairs[mask]
            else:
                pairs = base_pairs.copy()
            rng.shuffle(pairs)
            for off in range(0, len(pairs), self.batch_size):
                chunk = pairs[off:off + self.batch_size]
                chunk, negs, weights = _pad_batch(
                    chunk, self.batch_size, self.negative, V, table, rng)
                syn0, syn1, acc0, acc1 = step(
                    syn0, syn1, acc0, acc1, jnp.asarray(chunk[:, 0]),
                    jnp.asarray(chunk[:, 1]), jnp.asarray(negs),
                    np.float32(self.learning_rate), jnp.asarray(weights))
        full = np.asarray(syn0)
        self.syn0 = full[:V]
        self.syn1neg = np.asarray(syn1)
        self.doc_vectors = {l: full[V + i] for i, l in enumerate(labels)}
        return self

    # ---------------------------------------------------------------- lookup
    def get_looked_up_vector(self, label: str) -> Optional[np.ndarray]:
        return self.doc_vectors.get(label)

    lookupVector = get_looked_up_vector

    def infer_vector(self, text: str, steps: int = 50,
                     lr: float = 0.05) -> np.ndarray:
        """Gradient-fit a fresh doc vector against frozen word outputs
        (ref: ParagraphVectors#inferVector)."""
        import jax
        import jax.numpy as jnp
        toks = self.tokenizer_factory.create(text).get_tokens()
        widx = np.array([self.vocab.index_of(t) for t in toks])
        widx = widx[widx >= 0].astype(np.int32)
        rng = np.random.RandomState(self.seed)
        v = jnp.asarray((rng.rand(self.layer_size).astype(np.float32) - 0.5)
                        / self.layer_size)
        syn1 = jnp.asarray(self.syn1neg)
        table = self.vocab.unigram_table()
        V = self.vocab.num_words()

        @jax.jit
        def step(v, words, negs):
            def loss_fn(v):
                pos = syn1[words] @ v
                neg = jnp.einsum("nkd,d->nk", syn1[negs], v)
                # mask negatives colliding with the positive word (same
                # guard as the training step)
                neg_term = jnp.where(negs == words[:, None], 0.0,
                                     jax.nn.log_sigmoid(-neg))
                return -(jnp.sum(jax.nn.log_sigmoid(pos))
                         + jnp.sum(neg_term))
            g = jax.grad(loss_fn)(v)
            return v - lr * g

        for _ in range(steps):
            if len(widx) == 0:
                break
            negs = rng.choice(V, size=(len(widx), self.negative),
                              p=table).astype(np.int32)
            v = step(v, jnp.asarray(widx), jnp.asarray(negs))
        return np.asarray(v)

    inferVector = infer_vector

    def nearest_labels(self, text_or_vec, top_n: int = 5) -> List[str]:
        v = (self.infer_vector(text_or_vec)
             if isinstance(text_or_vec, str) else np.asarray(text_or_vec))
        from deeplearning4j_tpu.nlp.word2vec import _cos
        sims = [(l, _cos(v, dv)) for l, dv in self.doc_vectors.items()]
        sims.sort(key=lambda p: -p[1])
        return [l for l, _ in sims[:top_n]]

    nearestLabels = nearest_labels
