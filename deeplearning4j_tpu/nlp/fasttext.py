"""FastText — subword (character n-gram) SGNS word vectors.

Reference: ``org.deeplearning4j.models.fasttext.FastText`` (a JFastText
wrapper — SURVEY D15). Since the reference delegates to a native library,
this is a from-scratch TPU-native implementation of the fastText skipgram
model (Bojanowski et al.): a word's input vector is the MEAN of its word
embedding and its character n-gram embeddings (hashed into a fixed bucket
table), trained with negative sampling. The batch step is one jitted
program: gather (B, 1+max_ngrams, D) subword rows, mean, the SGNS logit
block on the MXU, and scatter-add updates back to word + bucket tables.

OOV words get vectors from their n-grams alone — the capability that
motivates fastText over word2vec.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.sentence import (CollectionSentenceIterator,
                                             SentenceIterator)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word2vec import _cos


def _fnv1a(s: str) -> int:
    """FNV-1a 32-bit — fastText's n-gram hashing function."""
    h = 2166136261
    for ch in s.encode("utf-8"):
        h = (h ^ ch) * 16777619 & 0xFFFFFFFF
    return h


class FastText:
    """Builder-configured fastText trainer (ref API surface: FastText.Builder
    ... .build(); fit(); getWordVector works for OOV words)."""

    def __init__(self, layer_size=100, window_size=5, min_word_frequency=1,
                 epochs=1, negative=5, learning_rate=0.05, min_n=3, max_n=6,
                 bucket=2_000_000, sample=1e-3, seed=42, batch_size=1024,
                 max_ngrams=20,
                 iterator: Optional[SentenceIterator] = None,
                 tokenizer_factory=None):
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.epochs = epochs
        self.negative = negative
        self.learning_rate = learning_rate
        self.min_n = min_n
        self.max_n = max_n
        self.bucket = bucket
        self.sample = sample
        self.seed = seed
        self.batch_size = batch_size
        self.max_ngrams = max_ngrams
        self.iterator = iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        # input table rows: [0, V) words, [V, V+bucket) n-gram buckets
        self.syn0: Optional[np.ndarray] = None
        self.syn1neg: Optional[np.ndarray] = None
        self._word_subwords: Optional[np.ndarray] = None  # (V, 1+max_ngrams)
        self._word_subword_mask: Optional[np.ndarray] = None

    class Builder:
        def __init__(self):
            self._kw = {}

        def _set(self, k, v):
            self._kw[k] = v
            return self

        def layer_size(self, v): return self._set("layer_size", v)
        def window_size(self, v): return self._set("window_size", v)
        def min_word_frequency(self, v): return self._set("min_word_frequency", v)
        def epochs(self, v): return self._set("epochs", v)
        def negative_sample(self, v): return self._set("negative", v)
        def learning_rate(self, v): return self._set("learning_rate", v)
        def min_n(self, v): return self._set("min_n", v)
        def max_n(self, v): return self._set("max_n", v)
        def bucket(self, v): return self._set("bucket", v)
        def seed(self, v): return self._set("seed", v)
        def batch_size(self, v): return self._set("batch_size", v)
        def iterate(self, it): return self._set("iterator", it)
        def tokenizer_factory(self, tf): return self._set("tokenizer_factory", tf)

        layerSize = layer_size
        windowSize = window_size
        minWordFrequency = min_word_frequency
        learningRate = learning_rate
        batchSize = batch_size
        tokenizerFactory = tokenizer_factory

        def build(self) -> "FastText":
            return FastText(**self._kw)

    # ---------------------------------------------------------------- ngrams
    def _ngram_ids(self, word: str) -> List[int]:
        """Hashed bucket ids for <word>'s character n-grams (rows offset by
        the vocab size)."""
        w = f"<{word}>"
        ids = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(w) - n + 1):
                ids.append(self._v + _fnv1a(w[i:i + n]) % self.bucket)
        return ids[: self.max_ngrams]

    def _subword_table(self):
        """(V, 1+max_ngrams) subword-row ids per word + float mask."""
        V = self._v
        k = 1 + self.max_ngrams
        tbl = np.zeros((V, k), np.int32)
        msk = np.zeros((V, k), np.float32)
        for i in range(V):
            ids = [i] + self._ngram_ids(self.vocab.word_at_index(i))
            tbl[i, :len(ids)] = ids
            msk[i, :len(ids)] = 1.0
        return tbl, msk

    # -------------------------------------------------------------- training
    def _build_step(self):
        import jax
        import jax.numpy as jnp

        def step(syn0, syn1, acc0, acc1, sub_ids, sub_mask, context, negs,
                 lr, weights):
            """SGNS where the center vector is the masked mean of subword
            rows; the center gradient scatters back to every subword row."""
            rows = syn0[sub_ids]                         # (B, K, D)
            denom = jnp.sum(sub_mask, axis=1, keepdims=True)  # (B, 1)
            v_c = jnp.sum(rows * sub_mask[:, :, None], axis=1) / denom
            tgt = jnp.concatenate([context[:, None], negs], axis=1)
            v_t = syn1[tgt]                              # (B, 1+neg, D)
            score = jnp.einsum("bd,bkd->bk", v_c, v_t)
            label = jnp.zeros_like(score).at[:, 0].set(1.0)
            g = label - jax.nn.sigmoid(score)
            collide = jnp.concatenate(
                [jnp.zeros((negs.shape[0], 1), bool),
                 negs == context[:, None]], axis=1)
            g = jnp.where(collide, 0.0, g) * weights[:, None]
            d_vc = jnp.einsum("bk,bkd->bd", g, v_t)      # (B, D)
            d_rows = (d_vc[:, None, :] * sub_mask[:, :, None]
                      / denom[:, :, None])               # (B, K, D)
            d_vt = jnp.einsum("bk,bd->bkd", g, v_c).reshape(-1, v_c.shape[-1])
            G0 = jnp.zeros_like(syn0).at[sub_ids.reshape(-1)].add(
                d_rows.reshape(-1, v_c.shape[-1]))
            G1 = jnp.zeros_like(syn1).at[tgt.reshape(-1)].add(d_vt)
            acc0 = acc0 + G0 * G0
            acc1 = acc1 + G1 * G1
            syn0 = syn0 + lr * G0 * jax.lax.rsqrt(acc0 + 1e-10)
            syn1 = syn1 + lr * G1 * jax.lax.rsqrt(acc1 + 1e-10)
            return syn0, syn1, acc0, acc1

        return jax.jit(step, donate_argnums=(0, 1, 2, 3))

    def fit(self) -> "FastText":
        import jax.numpy as jnp

        rng = np.random.RandomState(self.seed)
        token_streams = [self.tokenizer_factory.create(s).get_tokens()
                         for s in self.iterator]
        self.vocab = VocabCache.build(token_streams, self.min_word_frequency)
        self._v = V = self.vocab.num_words()
        if V == 0:
            raise ValueError("empty vocabulary")
        D = self.layer_size
        rows = V + self.bucket
        self._word_subwords, self._word_subword_mask = self._subword_table()
        syn0 = jnp.asarray((rng.rand(rows, D).astype(np.float32) - 0.5) / D)
        syn1 = jnp.zeros((V, D), jnp.float32)
        acc0 = jnp.zeros((rows, D), jnp.float32)
        acc1 = jnp.zeros((V, D), jnp.float32)
        table = self.vocab.unigram_table()
        step = self._build_step()

        # reuse word2vec's host-side pair generation
        from deeplearning4j_tpu.nlp.word2vec import Word2Vec
        w2v = Word2Vec(window_size=self.window_size, sample=self.sample)
        w2v.vocab = self.vocab
        sents = w2v._corpus_indices(token_streams)
        B = self.batch_size
        for _ in range(self.epochs):
            pairs = w2v._training_pairs(sents, rng)
            for off in range(0, len(pairs), B):
                chunk = pairs[off:off + B]
                n = len(chunk)
                negs = rng.choice(V, size=(n, self.negative),
                                  p=table).astype(np.int32)
                weights = np.ones(n, np.float32)
                if n < B:
                    pad = B - n
                    chunk = np.concatenate([chunk,
                                            np.zeros((pad, 2), np.int32)])
                    negs = np.concatenate(
                        [negs, np.zeros((pad, self.negative), np.int32)])
                    weights = np.concatenate([weights,
                                              np.zeros(pad, np.float32)])
                sub_ids = self._word_subwords[chunk[:, 0]]
                sub_mask = self._word_subword_mask[chunk[:, 0]]
                syn0, syn1, acc0, acc1 = step(
                    syn0, syn1, acc0, acc1,
                    jnp.asarray(sub_ids), jnp.asarray(sub_mask),
                    jnp.asarray(chunk[:, 1]), jnp.asarray(negs),
                    np.float32(self.learning_rate), jnp.asarray(weights))
        self.syn0 = np.asarray(syn0)
        self.syn1neg = np.asarray(syn1)
        return self

    # ----------------------------------------------------------------- lookup
    def _word_vector_rows(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word) if self.vocab is not None else -1
        if i >= 0:
            ids = self._word_subwords[i]
            msk = self._word_subword_mask[i]
            return (self.syn0[ids] * msk[:, None]).sum(0) / msk.sum()
        ids = self._ngram_ids(word)          # OOV: n-grams only
        if not ids:
            return None
        return self.syn0[np.asarray(ids)].mean(0)

    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        return self._word_vector_rows(word)

    getWordVector = get_word_vector

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    hasWord = has_word

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        return _cos(va, vb)

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            v = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
        else:
            v = np.asarray(word_or_vec)
            exclude = set()
        if v is None:
            return []
        V = self.vocab.num_words()
        mat = np.stack([self._word_vector_rows(self.vocab.word_at_index(i))
                        for i in range(V)])
        norms = mat / (np.linalg.norm(mat, axis=1, keepdims=True) + 1e-12)
        sims = norms @ (v / (np.linalg.norm(v) + 1e-12))
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at_index(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out

    wordsNearest = words_nearest

    @staticmethod
    def from_sentences(sentences: Sequence[str], **kwargs) -> "FastText":
        return FastText(iterator=CollectionSentenceIterator(sentences),
                        **kwargs).fit()
