"""Tokenizers (ref: org.deeplearning4j.text.tokenization.tokenizerfactory.
DefaultTokenizerFactory + preprocessor.CommonPreprocessor, SURVEY D15)."""
from __future__ import annotations

import re
from typing import List, Optional


class CommonPreprocessor:
    """Lowercase + strip punctuation/digits (ref: CommonPreprocessor)."""

    _PATTERN = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token: str) -> str:
        return self._PATTERN.sub("", token).lower()

    preProcess = pre_process


class Tokenizer:
    def __init__(self, text: str, preprocessor=None):
        toks = text.split()
        if preprocessor is not None:
            toks = [preprocessor.pre_process(t) for t in toks]
        self._tokens = [t for t in toks if t]
        self._pos = 0

    def count_tokens(self) -> int:
        return len(self._tokens)

    countTokens = count_tokens

    def has_more_tokens(self) -> bool:
        return self._pos < len(self._tokens)

    hasMoreTokens = has_more_tokens

    def next_token(self) -> str:
        t = self._tokens[self._pos]
        self._pos += 1
        return t

    nextToken = next_token

    def get_tokens(self) -> List[str]:
        return list(self._tokens)

    getTokens = get_tokens


class DefaultTokenizerFactory:
    """Whitespace tokenizer factory (ref: DefaultTokenizerFactory)."""

    def __init__(self):
        self._preprocessor = None

    def set_token_pre_processor(self, p):
        self._preprocessor = p

    setTokenPreProcessor = set_token_pre_processor

    def create(self, text: str) -> Tokenizer:
        return Tokenizer(text, self._preprocessor)


class NGramTokenizerFactory(DefaultTokenizerFactory):
    """ref: NGramTokenizerFactory — emits n-grams joined by spaces."""

    def __init__(self, min_n: int = 1, max_n: int = 2):
        super().__init__()
        self.min_n = min_n
        self.max_n = max_n

    def create(self, text: str) -> Tokenizer:
        base = Tokenizer(text, self._preprocessor).get_tokens()
        grams = []
        for n in range(self.min_n, self.max_n + 1):
            for i in range(len(base) - n + 1):
                grams.append(" ".join(base[i:i + n]))
        t = Tokenizer("", None)
        t._tokens = grams
        return t
