"""Word-vector serialization (ref: org.deeplearning4j.models.embeddings.
loader.WordVectorSerializer — the classic word2vec text format)."""
from __future__ import annotations

import gzip
from typing import Optional

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


class WordVectorSerializer:
    @staticmethod
    def write_word_vectors(model: Word2Vec, path: str):
        """word2vec text format: header 'V D', then 'word v1 .. vD'."""
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "wt") as f:
            V, D = model.syn0.shape
            f.write(f"{V} {D}\n")
            for i in range(V):
                w = model.vocab.word_at_index(i)
                vec = " ".join(f"{v:.6f}" for v in model.syn0[i])
                f.write(f"{w} {vec}\n")

    writeWordVectors = write_word_vectors

    @staticmethod
    def read_word_vectors(path: str) -> Word2Vec:
        """ref: WordVectorSerializer#readWord2VecModel (text)."""
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt") as f:
            header = f.readline().split()
            V, D = int(header[0]), int(header[1])
            words, vecs = [], np.zeros((V, D), dtype=np.float32)
            for i in range(V):
                parts = f.readline().rstrip("\n").split(" ")
                # parse from the right: n-gram tokens may contain spaces
                words.append(" ".join(parts[:-D]))
                vecs[i] = [float(x) for x in parts[-D:]]
        model = Word2Vec(layer_size=D)
        vc = VocabCache()
        for i, w in enumerate(words):
            vw = VocabWord(w, count=V - i, index=i)
            vc._words[w] = vw
            vc._by_index.append(vw)
        model.vocab = vc
        model.syn0 = vecs
        model.syn1neg = np.zeros_like(vecs)
        return model

    readWord2VecModel = read_word_vectors
    loadTxtVectors = read_word_vectors
