"""Word-vector serialization (ref: org.deeplearning4j.models.embeddings.
loader.WordVectorSerializer — the classic word2vec text format)."""
from __future__ import annotations

import gzip
from typing import Optional

import numpy as np

from deeplearning4j_tpu.nlp.vocab import VocabCache, VocabWord
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


def _model_from(words, vecs) -> Word2Vec:
    """Assemble a lookup-only Word2Vec from (words, vectors) — shared tail
    of the text and binary readers. Synthetic counts preserve rank order."""
    V, D = vecs.shape
    model = Word2Vec(layer_size=D)
    vc = VocabCache()
    for i, w in enumerate(words):
        vw = VocabWord(w, count=V - i, index=i)
        vc._words[w] = vw
        vc._by_index.append(vw)
    model.vocab = vc
    model.syn0 = vecs
    model.syn1neg = np.zeros_like(vecs)
    return model


class WordVectorSerializer:
    @staticmethod
    def write_word_vectors(model: Word2Vec, path: str):
        """word2vec text format: header 'V D', then 'word v1 .. vD'."""
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "wt") as f:
            V, D = model.syn0.shape
            f.write(f"{V} {D}\n")
            for i in range(V):
                w = model.vocab.word_at_index(i)
                vec = " ".join(f"{v:.6f}" for v in model.syn0[i])
                f.write(f"{w} {vec}\n")

    writeWordVectors = write_word_vectors

    @staticmethod
    def read_word_vectors(path: str) -> Word2Vec:
        """ref: WordVectorSerializer#readWord2VecModel (text)."""
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt") as f:
            header = f.readline().split()
            V, D = int(header[0]), int(header[1])
            words, vecs = [], np.zeros((V, D), dtype=np.float32)
            for i in range(V):
                parts = f.readline().rstrip("\n").split(" ")
                # parse from the right: n-gram tokens may contain spaces
                words.append(" ".join(parts[:-D]))
                vecs[i] = [float(x) for x in parts[-D:]]
        return _model_from(words, vecs)

    readWord2VecModel = read_word_vectors
    loadTxtVectors = read_word_vectors

    # ------------------------------------------------- word2vec binary (.bin)
    @staticmethod
    def write_binary(model: Word2Vec, path: str):
        """The original word2vec.c binary format (ref:
        WordVectorSerializer#writeWordVectors binary mode): ASCII header
        'V D\n', then per word 'word ' + D little-endian float32s + '\n'."""
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "wb") as f:
            V, D = model.syn0.shape
            f.write(f"{V} {D}\n".encode())
            for i in range(V):
                w = model.vocab.word_at_index(i)
                f.write(w.encode("utf-8") + b" ")
                f.write(np.asarray(model.syn0[i], "<f4").tobytes())
                f.write(b"\n")

    writeBinary = write_binary

    @staticmethod
    def read_binary(path: str) -> Word2Vec:
        """ref: WordVectorSerializer#loadGoogleModel(binary=true) — reads
        GoogleNews-style .bin files."""
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rb") as f:
            header = f.readline().split()
            V, D = int(header[0]), int(header[1])
            words, vecs = [], np.zeros((V, D), dtype=np.float32)
            for i in range(V):
                chars = bytearray()
                while True:
                    c = f.read(1)
                    if not c or c == b" ":
                        break
                    if c != b"\n":          # some writers pad with newline
                        chars.extend(c)
                words.append(chars.decode("utf-8"))
                vecs[i] = np.frombuffer(f.read(4 * D), dtype="<f4")
        return _model_from(words, vecs)

    loadGoogleModel = read_binary
