"""Sentence iterators (ref: org.deeplearning4j.text.sentenceiterator.*)."""
from __future__ import annotations

from typing import Iterable, List


class SentenceIterator:
    def next_sentence(self) -> str:
        raise NotImplementedError

    nextSentence = next_sentence

    def has_next(self) -> bool:
        raise NotImplementedError

    hasNext = has_next

    def reset(self):
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_sentence()


class CollectionSentenceIterator(SentenceIterator):
    """ref: CollectionSentenceIterator — in-memory sentences."""

    def __init__(self, sentences: Iterable[str]):
        self._sentences: List[str] = list(sentences)
        self._pos = 0

    def next_sentence(self):
        s = self._sentences[self._pos]
        self._pos += 1
        return s

    def has_next(self):
        return self._pos < len(self._sentences)

    def reset(self):
        self._pos = 0


class BasicLineIterator(SentenceIterator):
    """ref: BasicLineIterator — one sentence per file line."""

    def __init__(self, path: str):
        self.path = path
        self._lines = None
        self._pos = 0
        self.reset()

    def reset(self):
        with open(self.path) as f:
            self._lines = [l.rstrip("\n") for l in f if l.strip()]
        self._pos = 0

    def next_sentence(self):
        s = self._lines[self._pos]
        self._pos += 1
        return s

    def has_next(self):
        return self._pos < len(self._lines)
