"""Vocabulary cache (ref: org.deeplearning4j.models.word2vec.wordstore.
inmemory.AbstractCache + VocabWord, SURVEY D15)."""
from __future__ import annotations

from typing import Dict, Iterable, List, Optional

import numpy as np


class VocabWord:
    """ref: models.word2vec.VocabWord."""

    def __init__(self, word: str, count: int = 1, index: int = -1):
        self.word = word
        self.count = count
        self.index = index

    def __repr__(self):
        return f"VocabWord({self.word!r}, count={self.count}, idx={self.index})"


class VocabCache:
    """Frequency-ordered vocab with min-frequency filtering."""

    def __init__(self):
        self._words: Dict[str, VocabWord] = {}
        self._by_index: List[VocabWord] = []

    @staticmethod
    def build(token_streams: Iterable[List[str]],
              min_word_frequency: int = 1) -> "VocabCache":
        counts: Dict[str, int] = {}
        for toks in token_streams:
            for t in toks:
                counts[t] = counts.get(t, 0) + 1
        vc = VocabCache()
        ordered = sorted(((c, w) for w, c in counts.items()
                          if c >= min_word_frequency),
                         key=lambda p: (-p[0], p[1]))
        for i, (c, w) in enumerate(ordered):
            vw = VocabWord(w, c, i)
            vc._words[w] = vw
            vc._by_index.append(vw)
        return vc

    def num_words(self) -> int:
        return len(self._by_index)

    numWords = num_words

    def contains_word(self, word: str) -> bool:
        return word in self._words

    containsWord = contains_word

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.index if vw else -1

    indexOf = index_of

    def word_at_index(self, idx: int) -> str:
        return self._by_index[idx].word

    wordAtIndex = word_at_index

    def word_frequency(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.count if vw else 0

    wordFrequency = word_frequency

    def words(self) -> List[str]:
        return [vw.word for vw in self._by_index]

    def counts(self) -> np.ndarray:
        return np.array([vw.count for vw in self._by_index], dtype=np.float64)

    def unigram_table(self, power: float = 0.75) -> np.ndarray:
        """Negative-sampling distribution ∝ count^0.75 (Mikolov 2013; the
        reference builds the same table natively in the sg/cbow kernels)."""
        p = self.counts() ** power
        return p / p.sum()

    def subsample_keep_prob(self, sample: float) -> Optional[np.ndarray]:
        """Word-keep probabilities for frequent-word subsampling
        (ref: Word2Vec `sampling` config; word2vec.c formula)."""
        if not sample:
            return None
        freqs = self.counts()
        ratio = freqs / freqs.sum() / sample
        keep = (np.sqrt(ratio) + 1.0) / np.maximum(ratio, 1e-12)
        return np.minimum(keep, 1.0)
