"""GloVe — global co-occurrence-factorisation word vectors.

Reference: ``org.deeplearning4j.models.glove.Glove`` (+ Builder,
``AbstractCoOccurrences`` for the count pass) — SURVEY D15. The reference
trains per-pair on the host with AdaGrad; TPU-first redesign: the
co-occurrence pass stays on the host (string work), the weighted
least-squares updates run as ONE jitted program per shuffled batch of
nonzero co-occurrence cells — embed gathers, fused elementwise loss, and
scatter-add AdaGrad updates, the same shape of program as Word2Vec's SGNS
step.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.nlp.sentence import (CollectionSentenceIterator,
                                             SentenceIterator)
from deeplearning4j_tpu.nlp.tokenization import DefaultTokenizerFactory
from deeplearning4j_tpu.nlp.vocab import VocabCache
from deeplearning4j_tpu.nlp.word2vec import _cos


class Glove:
    """Builder-configured GloVe trainer (ref API: Glove.Builder ... .build();
    fit(); similarity/wordsNearest like Word2Vec)."""

    def __init__(self, layer_size=100, window_size=5, min_word_frequency=1,
                 epochs=5, learning_rate=0.05, x_max=100.0, alpha=0.75,
                 symmetric=True, shuffle=True, seed=42, batch_size=4096,
                 iterator: Optional[SentenceIterator] = None,
                 tokenizer_factory=None):
        self.layer_size = layer_size
        self.window_size = window_size
        self.min_word_frequency = min_word_frequency
        self.epochs = epochs
        self.learning_rate = learning_rate
        self.x_max = x_max
        self.alpha = alpha
        self.symmetric = symmetric
        self.shuffle = shuffle
        self.seed = seed
        self.batch_size = batch_size
        self.iterator = iterator
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab: Optional[VocabCache] = None
        self.syn0: Optional[np.ndarray] = None   # final vectors: w + w̃

    # ---------------------------------------------------------------- builder
    class Builder:
        def __init__(self):
            self._kw = {}

        def _set(self, k, v):
            self._kw[k] = v
            return self

        def layer_size(self, v): return self._set("layer_size", v)
        def window_size(self, v): return self._set("window_size", v)
        def min_word_frequency(self, v): return self._set("min_word_frequency", v)
        def epochs(self, v): return self._set("epochs", v)
        def learning_rate(self, v): return self._set("learning_rate", v)
        def x_max(self, v): return self._set("x_max", v)
        def alpha(self, v): return self._set("alpha", v)
        def symmetric(self, v): return self._set("symmetric", v)
        def shuffle(self, v): return self._set("shuffle", v)
        def seed(self, v): return self._set("seed", v)
        def batch_size(self, v): return self._set("batch_size", v)
        def iterate(self, it): return self._set("iterator", it)
        def tokenizer_factory(self, tf): return self._set("tokenizer_factory", tf)

        # camelCase reference aliases
        layerSize = layer_size
        windowSize = window_size
        minWordFrequency = min_word_frequency
        learningRate = learning_rate
        xMax = x_max
        batchSize = batch_size
        tokenizerFactory = tokenizer_factory

        def build(self) -> "Glove":
            return Glove(**self._kw)

    # ----------------------------------------------------------- cooccurrence
    def _cooccurrences(self, token_streams) -> Tuple[np.ndarray, np.ndarray]:
        """Nonzero co-occurrence cells: (N, 2) [i, j] int32 + (N,) float32
        counts, 1/distance weighting within the window (ref:
        AbstractCoOccurrences)."""
        counts: Dict[Tuple[int, int], float] = {}
        for toks in token_streams:
            idx = [self.vocab.index_of(t) for t in toks]
            idx = [i for i in idx if i >= 0]
            n = len(idx)
            for pos in range(n):
                for off in range(1, self.window_size + 1):
                    c = pos + off
                    if c >= n:
                        break
                    w = 1.0 / off
                    key = (idx[pos], idx[c])
                    counts[key] = counts.get(key, 0.0) + w
                    if self.symmetric:
                        key_r = (idx[c], idx[pos])
                        counts[key_r] = counts.get(key_r, 0.0) + w
        if not counts:
            return np.zeros((0, 2), np.int32), np.zeros((0,), np.float32)
        cells = np.asarray(list(counts.keys()), dtype=np.int32)
        vals = np.asarray(list(counts.values()), dtype=np.float32)
        return cells, vals

    # -------------------------------------------------------------- training
    def _build_step(self):
        import jax
        import jax.numpy as jnp

        x_max, alpha = self.x_max, self.alpha

        def step(W, Wc, b, bc, accW, accWc, accb, accbc,
                 wi, wj, logx, fx, lr, weights):
            """One AdaGrad batch over co-occurrence cells:
            J = Σ f(X_ij)·(w_i·w̃_j + b_i + b̃_j − log X_ij)²."""
            vi = W[wi]                       # (B, D)
            vj = Wc[wj]                      # (B, D)
            diff = (jnp.einsum("bd,bd->b", vi, vj) + b[wi] + bc[wj] - logx)
            g = fx * diff * weights          # (B,)
            d_vi = g[:, None] * vj
            d_vj = g[:, None] * vi
            GW = jnp.zeros_like(W).at[wi].add(d_vi)
            GWc = jnp.zeros_like(Wc).at[wj].add(d_vj)
            Gb = jnp.zeros_like(b).at[wi].add(g)
            Gbc = jnp.zeros_like(bc).at[wj].add(g)
            accW = accW + GW * GW
            accWc = accWc + GWc * GWc
            accb = accb + Gb * Gb
            accbc = accbc + Gbc * Gbc
            W = W - lr * GW * jax.lax.rsqrt(accW + 1e-8)
            Wc = Wc - lr * GWc * jax.lax.rsqrt(accWc + 1e-8)
            b = b - lr * Gb * jax.lax.rsqrt(accb + 1e-8)
            bc = bc - lr * Gbc * jax.lax.rsqrt(accbc + 1e-8)
            loss = 0.5 * jnp.sum(fx * diff * diff * weights)
            return W, Wc, b, bc, accW, accWc, accb, accbc, loss

        return jax.jit(step, donate_argnums=tuple(range(8)))

    def fit(self) -> "Glove":
        import jax.numpy as jnp

        rng = np.random.RandomState(self.seed)
        token_streams = [self.tokenizer_factory.create(s).get_tokens()
                         for s in self.iterator]
        self.vocab = VocabCache.build(token_streams, self.min_word_frequency)
        V, D = self.vocab.num_words(), self.layer_size
        if V == 0:
            raise ValueError("empty vocabulary")
        cells, vals = self._cooccurrences(token_streams)
        if len(cells) == 0:
            raise ValueError("no co-occurrences (corpus too small?)")
        logx_all = np.log(vals)
        fx_all = np.minimum((vals / self.x_max) ** self.alpha, 1.0).astype(
            np.float32)

        W = jnp.asarray((rng.rand(V, D).astype(np.float32) - 0.5) / D)
        Wc = jnp.asarray((rng.rand(V, D).astype(np.float32) - 0.5) / D)
        b = jnp.zeros((V,), jnp.float32)
        bc = jnp.zeros((V,), jnp.float32)
        accW = jnp.zeros((V, D), jnp.float32)
        accWc = jnp.zeros((V, D), jnp.float32)
        accb = jnp.zeros((V,), jnp.float32)
        accbc = jnp.zeros((V,), jnp.float32)
        step = self._build_step()
        B = self.batch_size
        self.losses: List[float] = []
        for _ in range(self.epochs):
            order = rng.permutation(len(cells)) if self.shuffle else np.arange(
                len(cells))
            ep_loss = 0.0
            for off in range(0, len(order), B):
                sel = order[off:off + B]
                n = len(sel)
                wi = np.zeros(B, np.int32)
                wj = np.zeros(B, np.int32)
                logx = np.zeros(B, np.float32)
                fx = np.zeros(B, np.float32)
                weights = np.zeros(B, np.float32)
                wi[:n] = cells[sel, 0]
                wj[:n] = cells[sel, 1]
                logx[:n] = logx_all[sel]
                fx[:n] = fx_all[sel]
                weights[:n] = 1.0
                (W, Wc, b, bc, accW, accWc, accb, accbc, loss) = step(
                    W, Wc, b, bc, accW, accWc, accb, accbc,
                    jnp.asarray(wi), jnp.asarray(wj), jnp.asarray(logx),
                    jnp.asarray(fx), np.float32(self.learning_rate),
                    jnp.asarray(weights))
                ep_loss += float(loss)
            self.losses.append(ep_loss)
        # GloVe paper: final vectors are the sum of the two tables
        self.syn0 = np.asarray(W) + np.asarray(Wc)
        return self

    # ----------------------------------------------------------------- lookup
    def get_word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.index_of(word)
        return None if i < 0 else self.syn0[i]

    getWordVector = get_word_vector

    def has_word(self, word: str) -> bool:
        return self.vocab is not None and self.vocab.contains_word(word)

    hasWord = has_word

    def similarity(self, a: str, b: str) -> float:
        va, vb = self.get_word_vector(a), self.get_word_vector(b)
        if va is None or vb is None:
            return float("nan")
        return _cos(va, vb)

    def words_nearest(self, word_or_vec, top_n: int = 10) -> List[str]:
        if isinstance(word_or_vec, str):
            v = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
        else:
            v = np.asarray(word_or_vec)
            exclude = set()
        if v is None:
            return []
        norms = self.syn0 / (np.linalg.norm(self.syn0, axis=1, keepdims=True)
                             + 1e-12)
        sims = norms @ (v / (np.linalg.norm(v) + 1e-12))
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at_index(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= top_n:
                break
        return out

    wordsNearest = words_nearest

    @staticmethod
    def from_sentences(sentences: Sequence[str], **kwargs) -> "Glove":
        return Glove(iterator=CollectionSentenceIterator(sentences),
                     **kwargs).fit()
