"""Resilience layer: fault injection, deadlines, retries, circuit
breaking, admission control, and self-healing training.

The observability stack (PR 1/3/4) can *see* failures; this package lets
the system *survive* them — and lets tests drive every failure path
deterministically:

- :mod:`~deeplearning4j_tpu.resilience.faults` — seeded fault-injection
  registry (``DL4J_TPU_FAULTS`` spec / programmatic plans) with named
  points threaded through the hot paths; every injection counted
  (``dl4j_faults_injected_total{point,kind}``), traced, and logged to the
  shared resilience event ring.
- :mod:`~deeplearning4j_tpu.resilience.policy` — :class:`RetryPolicy`
  (backoff + jitter under a token-bucket retry budget),
  :class:`Deadline` / :class:`DeadlineExceeded`, :class:`CircuitBreaker`
  (``dl4j_circuit_state{op}`` + :class:`CircuitOpenRule` on ``/health``),
  and the typed failure taxonomy (:class:`ShutdownError`,
  :class:`ShedError`, :class:`CircuitOpenError`, ...).
- :mod:`~deeplearning4j_tpu.resilience.recovery` —
  :class:`ResilientTrainer` (restore newest checkpoint → fast-forward →
  resume, bounded restarts) and :class:`SkippingIterator` (quarantine
  repeatedly failing batches, ``dl4j_data_quarantined_total``).

Admission control (bounded-queue load shedding, per-request deadlines,
fail-fast circuit gating) lives in ``parallel/inference.py`` and publishes
``dl4j_inference_shed_total{reason}``.

Kill switch: ``DL4J_TPU_RESILIENCE=0`` disarms everything — behavior is
byte-identical to the pre-resilience tree. :func:`snapshot` feeds the
flight recorder's ``resilience.json`` bundle section and
``UIServer GET /debug/resilience``.
"""
from deeplearning4j_tpu.resilience import faults
from deeplearning4j_tpu.resilience.faults import (FaultPlan, FaultSpec,
                                                  InjectedFault,
                                                  resilience_enabled)
from deeplearning4j_tpu.resilience.policy import (CircuitBreaker,
                                                  CircuitOpenError,
                                                  CircuitOpenRule, Deadline,
                                                  DeadlineExceeded,
                                                  ResilienceError,
                                                  RestartBudgetExhausted,
                                                  RetryBudget, RetryPolicy,
                                                  ShedError, ShutdownError,
                                                  TransientError,
                                                  default_deadline_ms,
                                                  is_transient)

__all__ = [
    "faults", "FaultPlan", "FaultSpec", "InjectedFault",
    "resilience_enabled",
    "CircuitBreaker", "CircuitOpenError", "CircuitOpenRule", "Deadline",
    "DeadlineExceeded", "ResilienceError", "RestartBudgetExhausted",
    "RetryBudget", "RetryPolicy", "ShedError", "ShutdownError",
    "TransientError", "default_deadline_ms", "is_transient",
    "ResilientTrainer", "SkippingIterator", "newest_checkpoint",
    "ElasticCheckpointer", "HostLostError", "elastic_enabled",
    "snapshot",
]


def snapshot() -> dict:
    """Everything a postmortem needs about the resilience layer: fault
    plan + injection counts, live circuit-breaker states, the default
    deadline, the elastic posture, and the recent event ring
    (injections, retries, sheds, breaker transitions, restores,
    reshapes, quarantines)."""
    from deeplearning4j_tpu.resilience import elastic, policy, qos
    return {
        "enabled": resilience_enabled(),
        "faults": faults.snapshot(),
        "circuits": policy.circuit_snapshot(),
        "default_deadline_ms": policy.default_deadline_ms(),
        "elastic": {"enabled": elastic.elastic_enabled(),
                    "capacity": elastic.global_capacity().snapshot()},
        # per-tenant QoS breakdown (policies, bucket levels, counters) —
        # the tenant-shed events in the ring need this to mean anything
        "tenants": qos.snapshot(),
        "events": faults.events(),
    }


def __getattr__(name):
    # recovery imports the data/listener layers — lazy so importing the
    # resilience package from those layers' hot paths can never cycle
    if name in ("ResilientTrainer", "SkippingIterator", "newest_checkpoint"):
        from deeplearning4j_tpu.resilience import recovery
        return getattr(recovery, name)
    if name in ("ElasticCheckpointer", "HostLostError", "elastic_enabled"):
        from deeplearning4j_tpu.resilience import elastic
        return getattr(elastic, name)
    raise AttributeError(name)
