"""Deterministic, seeded fault injection for chaos testing the hot paths.

The reference stack's fault-tolerance story is "Spark task retry plus
periodic checkpoints" (SURVEY §5.3) and is only ever exercised by *real*
failures. Production systems treat failure as a first-class input
(fault-tolerant execution is a design axis of TensorFlow, Abadi et al.
arXiv:1605.08695 §3.3/§4.2; straggler/fault characterization dominates at
scale, Awan et al. arXiv:1810.11112): every failure-handling path must be
drivable on demand, deterministically, in tests and in staging chaos runs.

Named injection points are threaded through the hot paths:

=========================== =================================================
``data.next_batch``         DataSetIterator ``__next__`` (all iterators)
``inference.dispatch``      ParallelInference dispatcher, before the forward
``inference.device_execute``ParallelInference completer / sync serve loop
``serving.canary``          ServingRouter, on the canary version's path only
``generation.step``         GenerationPipeline decode loop, once per step
                            boundary (prefill joins + the decode step)
``generation.adopt``        FrontDoor orphan-session adoption (the lease-
                            fenced store takeover before a resume)
``http.request``            FrontDoor, at the door of every ``/v1/*``
                            request (after admission, before routing)
``store.read``              SharedStore document read (routing falls back
                            to its cached view; sync retries next beat)
``store.write``             SharedStore atomic commit (sync merges its
                            window counters back and retries)
``train.step``              MLN/CG ``_fit_batch`` before the jitted step
``checkpoint.save``         CheckpointListener / preemption / recovery saves
``checkpoint.restore``      ResilientTrainer checkpoint restore
``allreduce``               ShardedTrainer sharded step entry
=========================== =================================================

Fault kinds:

- ``error``   — raise a *transient* :class:`InjectedFault` (retryable)
- ``crash``   — raise a *non-transient* :class:`InjectedFault` (forces the
  restore-from-checkpoint path instead of in-place retry)
- ``latency`` — sleep ``latency_seconds`` (default 0.05)
- ``nan``     — corrupt the batch/inputs to NaN (composes with the PR-4
  numerics health: ``DL4J_TPU_NUMERICS_SKIP=1`` skips the poisoned update).
  Only valid at the points that own an array (``data.next_batch``,
  ``train.step``) — specs naming other points are rejected at parse

Configuration: ``DL4J_TPU_FAULTS="point:kind:rate[:count]"`` (comma-
separated specs; ``rate`` is the per-call injection probability, ``count``
caps total injections), or programmatically for tests::

    from deeplearning4j_tpu.resilience import faults
    plan = faults.FaultPlan([faults.FaultSpec("train.step", "crash",
                                              rate=1.0, count=1)], seed=7)
    with faults.active(plan):
        ...

Determinism: each spec owns a ``random.Random`` seeded from
``(plan.seed, point, kind, index)`` — the same call sequence injects the
same faults. Every injection is counted
(``dl4j_faults_injected_total{point,kind}``), recorded in the resilience
event ring (→ flight-recorder ``resilience.json``), and traced as a
``fault_injected`` span parented into the caller's live trace, so chaos
runs are auditable end to end.

Kill switch: ``DL4J_TPU_RESILIENCE=0`` disarms all injection AND the
policy layer (deadlines, shedding, circuit breaking, self-healing) —
behavior is byte-identical to the pre-resilience tree.
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

log = logging.getLogger("deeplearning4j_tpu")

POINTS = ("data.next_batch", "inference.dispatch", "inference.device_execute",
          "serving.canary", "generation.step", "generation.adopt",
          "http.request", "train.step",
          "checkpoint.save", "checkpoint.restore", "checkpoint.manifest",
          "store.read", "store.write", "allreduce")
KINDS = ("error", "crash", "latency", "nan", "host_loss")
# nan corrupts a batch, so it only fires at points that own an array —
# accepting it elsewhere would validate a chaos spec that never injects
NAN_POINTS = ("data.next_batch", "train.step")
# host_loss simulates losing devices mid-step, so it only fires at the
# points a sharded step actually crosses; it needs the elastic layer to
# mean anything (DL4J_TPU_ELASTIC=0 disarms it — pre-elastic behavior)
HOST_LOSS_POINTS = ("train.step", "allreduce")


def resilience_enabled() -> bool:
    """THE resilience kill switch (read per call so tests can flip it).
    ``0`` disarms fault injection and every policy the layer adds."""
    return os.environ.get("DL4J_TPU_RESILIENCE", "1") != "0"


class InjectedFault(RuntimeError):
    """A deliberately injected failure. ``transient`` marks it retryable
    (kind ``error``); kind ``crash`` is non-transient and must take the
    restore-from-checkpoint path."""

    def __init__(self, point: str, kind: str = "error",
                 transient: Optional[bool] = None):
        self.point = point
        self.kind = kind
        self.transient = (kind == "error") if transient is None else transient
        super().__init__(f"injected fault at {point!r} (kind={kind}, "
                         f"transient={self.transient})")


class FaultSpec:
    """One injection rule: at ``point``, inject ``kind`` with probability
    ``rate`` per call, at most ``count`` times (None = unbounded)."""

    __slots__ = ("point", "kind", "rate", "count", "latency_seconds")

    def __init__(self, point: str, kind: str, rate: float = 1.0,
                 count: Optional[int] = None,
                 latency_seconds: float = 0.05):
        if point not in POINTS:
            raise ValueError(f"unknown fault point {point!r}; "
                             f"one of {POINTS}")
        if kind not in KINDS:
            raise ValueError(f"unknown fault kind {kind!r}; one of {KINDS}")
        if kind == "nan" and point not in NAN_POINTS:
            raise ValueError(
                f"kind 'nan' corrupts a batch and only fires at "
                f"{NAN_POINTS}; point {point!r} owns no array — use "
                "'error', 'crash', or 'latency' there")
        if kind == "host_loss" and point not in HOST_LOSS_POINTS:
            raise ValueError(
                f"kind 'host_loss' loses devices mid-step and only fires "
                f"at {HOST_LOSS_POINTS}; point {point!r} never crosses "
                "the mesh")
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        self.point = point
        self.kind = kind
        self.rate = float(rate)
        self.count = None if count is None else int(count)
        self.latency_seconds = float(latency_seconds)

    def __repr__(self):
        return (f"FaultSpec({self.point}:{self.kind}:{self.rate}"
                + (f":{self.count}" if self.count is not None else "") + ")")


class FaultPlan:
    """A set of specs plus the seed their draw sequences derive from."""

    def __init__(self, specs: Sequence[FaultSpec], seed: int = 0):
        self.specs = tuple(specs)
        self.seed = int(seed)

    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        """``"point:kind:rate[:count][,point:kind:rate[:count]...]"`` —
        the ``DL4J_TPU_FAULTS`` wire format."""
        specs = []
        for part in (p.strip() for p in text.split(",") if p.strip()):
            fields = part.split(":")
            if len(fields) < 2:
                raise ValueError(f"fault spec {part!r}: need point:kind")
            point, kind = fields[0], fields[1]
            rate = float(fields[2]) if len(fields) > 2 else 1.0
            count = int(fields[3]) if len(fields) > 3 else None
            specs.append(FaultSpec(point, kind, rate=rate, count=count))
        return cls(specs, seed=seed)


class _SpecState:
    """Per-spec live state: the seeded draw stream + injections so far."""

    __slots__ = ("spec", "rng", "fired")

    def __init__(self, spec: FaultSpec, seed: int, index: int):
        self.spec = spec
        self.rng = random.Random(f"{seed}:{spec.point}:{spec.kind}:{index}")
        self.fired = 0


# ---------------------------------------------------------------- event ring
# ONE bounded ring for the whole resilience layer (injections, retries,
# sheds, breaker transitions, restores, quarantines) — the flight recorder
# folds it into each postmortem bundle as resilience.json, and
# UIServer GET /debug/resilience serves it live.
_events: deque = deque(maxlen=256)
_events_lock = threading.Lock()


def record_event(category: str, **attrs):
    evt = {"t": time.time(), "category": category}
    evt.update(attrs)
    with _events_lock:
        _events.append(evt)


def events() -> List[dict]:
    with _events_lock:
        return list(_events)


def clear_events():
    with _events_lock:
        _events.clear()


# ------------------------------------------------------------------ registry
class FaultRegistry:
    """Resolves the active plan (programmatic wins over the env spec),
    draws deterministically, and fires faults at the named points."""

    def __init__(self):
        self._lock = threading.RLock()
        self._plan: Optional[FaultPlan] = None
        self._states: Dict[str, List[_SpecState]] = {}
        # cumulative process-lifetime injections ("point:kind" -> n): the
        # postmortem view must survive plans being cleared/replaced
        self._injected_total: Dict[str, int] = {}
        # env-spec cache: (raw env string, states-by-point); rebuilt only
        # when the string changes so check() stays cheap per call
        self._env_raw: Optional[str] = None
        self._env_states: Dict[str, List[_SpecState]] = {}
        self._env_warned: Optional[str] = None

    # -------------------------------------------------------- plan control
    def install(self, plan: FaultPlan):
        with self._lock:
            self._plan = plan
            self._states = self._build_states(plan)

    def clear(self):
        with self._lock:
            self._plan = None
            self._states = {}

    @staticmethod
    def _build_states(plan: FaultPlan) -> Dict[str, List[_SpecState]]:
        out: Dict[str, List[_SpecState]] = {}
        for i, spec in enumerate(plan.specs):
            out.setdefault(spec.point, []).append(
                _SpecState(spec, plan.seed, i))
        return out

    def _active_states(self) -> Dict[str, List[_SpecState]]:
        if self._plan is not None:
            return self._states
        raw = os.environ.get("DL4J_TPU_FAULTS", "")
        if raw != self._env_raw:
            with self._lock:
                if raw != self._env_raw:
                    states: Dict[str, List[_SpecState]] = {}
                    if raw:
                        try:
                            states = self._build_states(FaultPlan.parse(raw))
                        except ValueError as e:
                            # a typo'd chaos spec must not crash training —
                            # warn once per distinct bad value and inject
                            # nothing
                            if raw != self._env_warned:
                                self._env_warned = raw
                                log.warning("ignoring malformed "
                                            "DL4J_TPU_FAULTS=%r: %s", raw, e)
                    self._env_states = states
                    self._env_raw = raw
        return self._env_states

    def armed(self) -> bool:
        """Fast path for the hot-path call sites: False unless resilience
        is on AND some fault plan (programmatic or env) exists."""
        if not resilience_enabled():
            return False
        if self._plan is not None:
            return True
        return bool(os.environ.get("DL4J_TPU_FAULTS"))

    # ------------------------------------------------------------- drawing
    def _draw(self, st: _SpecState) -> bool:
        spec = st.spec
        if spec.count is not None and st.fired >= spec.count:
            return False
        fire = spec.rate >= 1.0 or st.rng.random() < spec.rate
        if fire:
            st.fired += 1
        return fire

    def _note(self, point: str, kind: str):
        key = f"{point}:{kind}"
        with self._lock:
            self._injected_total[key] = self._injected_total.get(key, 0) + 1
        _injected_counter(point, kind).inc()
        record_event("fault_injected", point=point, kind=kind)
        try:
            from deeplearning4j_tpu.observability.tracing import (
                current_context, now_us, record_span)
            record_span("fault_injected", now_us(), ctx=current_context(),
                        point=point, kind=kind)
        except Exception:  # graftlint: disable=typed-errors — tracing is
            pass           # best-effort; no request outcome flows here

    def check(self, point: str):
        """Fire error/crash/latency faults configured at ``point``.
        Raises :class:`InjectedFault` or sleeps; nan faults are handled by
        :meth:`corrupt` at the sites that own an array."""
        if not self.armed():
            return
        for st in self._active_states().get(point, ()):
            kind = st.spec.kind
            if kind == "nan":
                continue
            if kind == "host_loss":
                # a host-loss fault only means something when the elastic
                # layer can act on it; under DL4J_TPU_ELASTIC=0 the spec
                # is inert (byte-identical pre-elastic behavior)
                from deeplearning4j_tpu.resilience import elastic as _el
                if not _el.elastic_enabled():
                    continue
                with self._lock:
                    fire = self._draw(st)
                if not fire:
                    continue
                # capacity drops BEFORE the error propagates: the
                # recovery path reads the shrunken capacity when it
                # decides the new mesh size. When no device CAN be lost
                # (already down to one survivor) nothing happened — the
                # injection is not counted and no error is raised, the
                # same never-count-a-no-op rule as the nan kind
                lost = _el.global_capacity().mark_host_loss()
                if lost <= 0:
                    continue
                self._note(point, kind)
                raise _el.HostLostError(point, lost=lost)
            with self._lock:
                fire = self._draw(st)
            if not fire:
                continue
            self._note(point, kind)
            if kind == "latency":
                time.sleep(st.spec.latency_seconds)
            else:
                raise InjectedFault(point, kind)

    def corrupt(self, point: str, value):
        """Apply any nan fault configured at ``point`` to ``value`` (an
        array, or a tuple/list of arrays). Returns the possibly-poisoned
        value; non-float arrays pass through untouched."""
        if not self.armed():
            return value
        for st in self._active_states().get(point, ()):
            if st.spec.kind != "nan":
                continue
            with self._lock:
                fire = self._draw(st)
            if fire:
                if not _nanifiable(value):
                    # nothing to poison (e.g. integer token ids): counting
                    # the injection would report a corruption that never
                    # happened
                    return value
                self._note(point, "nan")
                return _nanify(value)
        return value

    def corrupt_dataset(self, point: str, ds):
        """nan-corrupt a DataSet/MultiDataSet's features in place of the
        original (shallow copy — the caller's object is never mutated)."""
        if not self.armed():
            return ds
        for st in self._active_states().get(point, ()):
            if st.spec.kind != "nan":
                continue
            with self._lock:
                fire = self._draw(st)
            if fire:
                if not _nanifiable(ds.features):
                    return ds
                self._note(point, "nan")
                import copy
                out = copy.copy(ds)
                out.features = _nanify(out.features)
                return out
        return ds

    def snapshot(self) -> dict:
        with self._lock:
            # process-lifetime totals, NOT the live plan's counters — a
            # postmortem taken after a chaos plan was cleared must still
            # name what was injected
            injected = dict(self._injected_total)
        return {
            "enabled": resilience_enabled(),
            "env_spec": os.environ.get("DL4J_TPU_FAULTS", ""),
            "programmatic_plan": self._plan is not None,
            "injected": injected,
        }


def _nanify(value):
    if isinstance(value, (tuple, list)):
        return type(value)(_nanify(v) for v in value)
    arr = np.asarray(value)
    if np.issubdtype(arr.dtype, np.floating):
        return np.full(arr.shape, np.nan, arr.dtype)
    return value


def _nanifiable(value) -> bool:
    """True when ``value`` holds at least one float array ``_nanify``
    would actually poison."""
    if isinstance(value, (tuple, list)):
        return any(_nanifiable(v) for v in value)
    return np.issubdtype(np.asarray(value).dtype, np.floating)


# ------------------------------------------------------------ metric handles
# ONE label-bound-handle cache for the whole resilience layer (policy and
# recovery register through it too) — a registry reset drops every handle
# in one place instead of three private caches drifting apart
_handle_cache: Dict[Tuple, object] = {}
_handle_lock = threading.Lock()


def cached_metric_handle(key: Tuple, make):
    """Double-checked cache of a label-bound instrument handle; ``make``
    runs at most once per key per registry generation."""
    handle = _handle_cache.get(key)
    if handle is None:
        with _handle_lock:
            handle = _handle_cache.get(key)
            if handle is None:
                handle = _handle_cache[key] = make()
    return handle


def _injected_counter(point: str, kind: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_faults_injected_total",
            "faults injected by the chaos registry, by injection point "
            "and kind", label_names=("point", "kind")).labels(
                point=point, kind=kind)
    return cached_metric_handle(("faults", point, kind), make)


def _on_registry_reset():
    with _handle_lock:
        _handle_cache.clear()


try:
    from deeplearning4j_tpu.observability import on_registry_reset
    on_registry_reset(_on_registry_reset)
except Exception:            # pragma: no cover - observability always present
    pass


# --------------------------------------------------------- module-level API
_registry = FaultRegistry()


def install(plan: FaultPlan):
    _registry.install(plan)


def clear():
    _registry.clear()


def reset():
    """Full test-isolation reset: uninstall the plan AND forget the
    process-lifetime injection totals + event ring (production code never
    calls this — postmortems rely on the totals surviving clears)."""
    with _registry._lock:
        _registry.clear()
        _registry._injected_total.clear()
    clear_events()


@contextmanager
def active(plan: FaultPlan):
    """``with faults.active(plan): ...`` — scoped programmatic injection."""
    install(plan)
    try:
        yield _registry
    finally:
        clear()


def armed() -> bool:
    return _registry.armed()


def check(point: str):
    _registry.check(point)


def corrupt(point: str, value):
    return _registry.corrupt(point, value)


def corrupt_dataset(point: str, ds):
    return _registry.corrupt_dataset(point, ds)


def snapshot() -> dict:
    return _registry.snapshot()
