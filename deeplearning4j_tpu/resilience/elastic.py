"""Elastic training: async sharded checkpoints, topology-reshaping
restore, and mesh shrink/resume/re-expand capacity tracking.

Production TPU pods run on preemptible capacity: hosts and devices
disappear mid-run and come back minutes later. The reference's answer was
the Spark parameter-server layer's fault-tolerant ``SharedTrainingMaster``
(PAPER.md); the PR-5 resilience layer restores single-file checkpoints
onto the SAME topology only. This module makes topology itself a
restorable dimension:

- :class:`ElasticCheckpointer` — **async sharded saves**: the training
  state (params / opt-state / batchnorm states / grad-compression
  residuals) is snapshotted to host on the caller thread (cheap memcpy;
  device buffers are donation-unsafe to hold) and serialized, digested,
  fsynced, and committed on a background thread — the step loop never
  waits on disk. Each save is a set of ``shard_*.npz`` files plus an
  **atomic versioned manifest** (tmp + fsync + rename, the PR-5
  torn-zip-skip doctrine applied to a shard SET): the manifest records
  step, mesh topology, per-key dtypes, and content digests, so a torn
  or partial shard set is detected and skipped in favor of the newest
  complete one. Async saves go through a coalescing latest-slot queue:
  a slow writer never piles up snapshots in host memory, and the newest
  state is always the one committed.
- **Topology-reshaping restore** — :meth:`ElasticCheckpointer.restore`
  loads a checkpoint written on an N-replica mesh onto an M-replica
  mesh: replicated params/opt-state re-place onto the new mesh at the
  next ``ShardedTrainer._place``, and replica-keyed state (the PR-7
  error-feedback residuals) is re-bucketed mean-preservingly or
  re-seeded at zero with an explicit warning
  (``parallel.compression.reshape_state`` — replica-keyed state cannot
  survive a reshape byte-exactly).
- :class:`ElasticCapacity` — the process-wide view of how many devices
  are currently usable. A ``host_loss`` fault (``resilience/faults.py``)
  or a real capacity event shrinks it; after
  ``DL4J_TPU_ELASTIC_RECOVER_STEPS`` successful steps on the degraded
  mesh (or an explicit :meth:`restore_capacity`) it re-expands, and
  ``ResilientTrainer``'s elastic mode resizes the mesh to follow.

Grounding: sharded weight-update state per replica is the recipe of
"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv 2004.13336); moving a checkpoint between topologies is
the array-redistribution problem of arXiv 2112.01075 — here the
redistribution happens through the host filesystem because the source
topology no longer exists.

Kill switch: ``DL4J_TPU_ELASTIC=0`` (under the ``DL4J_TPU_RESILIENCE``
master) — saves no-op, ``host_loss`` faults are inert, and
``ResilientTrainer`` behaves byte-identically to the pre-elastic tree.
"""
from __future__ import annotations

import io
import json
import logging
import os
import threading
import time
import weakref
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.utils.serialization import fsync_dir as _fsync_dir

log = logging.getLogger("deeplearning4j_tpu")

MANIFEST_PREFIX = "manifest_"
MANIFEST_VERSION = 1
DEFAULT_RECOVER_STEPS = 8


def elastic_enabled() -> bool:
    """THE elastic kill switch (read per call so tests can flip it);
    inert whenever the resilience master is off."""
    return (_faults.resilience_enabled()
            and os.environ.get("DL4J_TPU_ELASTIC", "1") != "0")


def recover_steps() -> int:
    """Successful steps on a degraded mesh before lost capacity is
    assumed back (``DL4J_TPU_ELASTIC_RECOVER_STEPS``; 0 = never
    auto-recover, re-expansion then needs ``restore_capacity()``)."""
    try:
        return max(0, int(os.environ.get("DL4J_TPU_ELASTIC_RECOVER_STEPS",
                                         DEFAULT_RECOVER_STEPS)))
    except (TypeError, ValueError):
        return DEFAULT_RECOVER_STEPS


class HostLostError(RuntimeError):
    """A host/device dropped out mid-step. NON-transient (the buffers on
    the lost devices are gone — an in-place retry cannot succeed) but
    elastic-restorable: ``ResilientTrainer``'s elastic mode shrinks the
    mesh and restores from the sharded manifest instead of dying."""

    def __init__(self, point: str, lost: int = 0):
        self.point = point
        self.lost = int(lost)
        super().__init__(f"host loss at {point!r} ({lost} device(s) gone); "
                         "shrink the mesh and restore from the sharded "
                         "manifest")


# ------------------------------------------------------------------ capacity
class ElasticCapacity:
    """Process-wide device-capacity view. ``mark_host_loss`` shrinks it
    (a ``host_loss`` fault, or a real capacity event); ``note_step``
    counts healthy steps on the degraded mesh and restores capacity
    after :func:`recover_steps` of them — the test-deterministic model
    of "the pod scheduler gave the hosts back"."""

    def __init__(self):
        self._lock = threading.Lock()
        self._lost = 0
        self._good_steps = 0

    def total(self) -> int:
        import jax
        return len(jax.devices())

    def available(self) -> int:
        with self._lock:
            lost = self._lost
        return max(1, self.total() - lost)

    def degraded(self) -> bool:
        with self._lock:
            return self._lost > 0

    def mark_host_loss(self, lost: Optional[int] = None) -> int:
        """Lose ``lost`` devices (default: half of what is left, always
        leaving one). Returns how many were actually lost."""
        total = self.total()
        with self._lock:
            avail = max(1, total - self._lost)
            n = max(1, avail // 2) if lost is None else max(0, int(lost))
            n = min(n, avail - 1)
            if n <= 0:
                return 0
            self._lost += n
            self._good_steps = 0
        _faults.record_event("host_loss", lost=n,
                             available=max(1, total - self._lost))
        _mesh_gauge().set(max(1, total - self._lost))
        log.warning("host loss: %d device(s) gone, %d available", n,
                    max(1, total - self._lost))
        return n

    def note_step(self):
        """One healthy training step completed; on a degraded mesh,
        enough of these == capacity recovered."""
        k = recover_steps()
        with self._lock:
            if self._lost == 0:
                return
            self._good_steps += 1
            if k == 0 or self._good_steps < k:
                return
        self.restore_capacity()

    def restore_capacity(self):
        with self._lock:
            if self._lost == 0:
                return
            self._lost = 0
            self._good_steps = 0
        _faults.record_event("capacity_restored", available=self.total())
        _mesh_gauge().set(self.total())
        log.warning("capacity restored: %d device(s) available",
                    self.total())

    def reset(self):
        with self._lock:
            self._lost = 0
            self._good_steps = 0

    def snapshot(self) -> dict:
        with self._lock:
            lost, good = self._lost, self._good_steps
        return {"total_devices": self.total(), "lost": lost,
                "available": max(1, self.total() - lost),
                "good_steps_since_loss": good,
                "recover_steps": recover_steps()}


_capacity = ElasticCapacity()


def global_capacity() -> ElasticCapacity:
    return _capacity


# ------------------------------------------------- state <-> flat arrays
def snapshot_net_state(net) -> Tuple[Dict[str, np.ndarray], dict]:
    """Flatten a net's full training state to host arrays (caller
    thread: device buffers are donation-unsafe to hold across the next
    jitted step, so the device→host fetch is the only synchronous part
    of an async save). Returns ``(arrays, meta)``."""
    import jax
    arrays: Dict[str, np.ndarray] = {}
    for lkey in net._params:
        for pname, arr in net._params[lkey].items():
            arrays[f"params/{lkey}/{pname}"] = np.asarray(arr)
    for lkey in net._states:
        for sname, arr in net._states[lkey].items():
            arrays[f"states/{lkey}/{sname}"] = np.asarray(arr)
    if net._opt_state is not None:
        # CONTIGUOUS index over array leaves only — apply_net_state walks
        # the same convention (an enumerate index over ALL leaves would
        # leave gaps whenever the opt-state pytree carries a non-array
        # leaf, and restore would silently fall back to fresh state)
        j = 0
        for leaf in jax.tree.leaves(net._opt_state):
            if hasattr(leaf, "shape"):
                arrays[f"opt/leaf_{j}"] = np.asarray(leaf)
                j += 1
    comp = getattr(net, "_grad_compression_state", None)
    n_replica_state = 0
    if comp is not None:
        for i, r in enumerate(comp["residual"]):
            arrays[f"comp/residual_{i}"] = np.asarray(r)
        for i, t in enumerate(comp["threshold"]):
            arrays[f"comp/threshold_{i}"] = np.asarray(t)
        n_replica_state = int(np.shape(comp["residual"][0])[0]) \
            if comp["residual"] else 0
    meta = {"iteration": int(net._iteration), "epoch": int(net._epoch),
            "model_type": type(net).__name__,
            "replica_keyed_rows": n_replica_state}
    return arrays, meta


def apply_net_state(net, arrays: Dict[str, np.ndarray], meta: dict):
    """Restore a flat state dict into ``net`` (tolerant like
    ModelSerializer: missing/mismatched keys keep the fresh value with a
    warning). Replica-keyed compression state is attached AS SAVED — the
    next ``ShardedTrainer._place`` reshapes it onto the live mesh."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.utils import strengthen_dtypes
    if not net._initialized:
        net.init()
    params = {}
    for lkey in net._params:
        params[lkey] = {}
        for pname, fresh in net._params[lkey].items():
            saved = arrays.get(f"params/{lkey}/{pname}")
            if saved is None or tuple(saved.shape) != tuple(fresh.shape):
                log.warning("elastic restore: parameter %s/%s missing or "
                            "mismatched; keeping fresh value", lkey, pname)
                params[lkey][pname] = fresh
            else:
                params[lkey][pname] = jnp.asarray(saved)
    net.set_param_tree(params)
    states = {}
    for lkey in net._states:
        states[lkey] = {}
        for sname, fresh in net._states[lkey].items():
            saved = arrays.get(f"states/{lkey}/{sname}")
            if saved is not None and \
                    tuple(saved.shape) == tuple(fresh.shape):
                states[lkey][sname] = jnp.asarray(saved)
            else:
                states[lkey][sname] = fresh
    net._states = strengthen_dtypes(states)
    if net._opt_state is not None:
        ref_leaves = jax.tree.leaves(net._opt_state)
        n_saved = sum(1 for k in arrays if k.startswith("opt/leaf_"))
        if n_saved == sum(1 for l in ref_leaves if hasattr(l, "shape")):
            leaves, j = [], 0
            ok = True
            for ref in ref_leaves:
                if not hasattr(ref, "shape"):
                    leaves.append(ref)
                    continue
                saved = arrays.get(f"opt/leaf_{j}")
                j += 1
                if saved is None or tuple(saved.shape) != tuple(ref.shape):
                    ok = False
                    break
                leaves.append(jnp.asarray(saved).astype(ref.dtype))
            if ok:
                net._opt_state = jax.tree.unflatten(
                    jax.tree.structure(net._opt_state), leaves)
            else:
                log.warning("elastic restore: optimizer state mismatched; "
                            "keeping fresh state")
        else:
            log.warning("elastic restore: optimizer leaf count changed; "
                        "keeping fresh state")
    elif any(k.startswith("opt/leaf_") for k in arrays):
        # should not happen (init() above always builds an opt state) —
        # but dropping saved Adam moments SILENTLY would be a quality
        # regression nobody notices, so say it loudly
        log.warning("elastic restore: checkpoint carries optimizer state "
                    "but the net has none initialized; moments dropped")
    n_res = sum(1 for k in arrays if k.startswith("comp/residual_"))
    if n_res:
        net._grad_compression_state = {
            "residual": [jnp.asarray(arrays[f"comp/residual_{i}"])
                         for i in range(n_res)],
            "threshold": [jnp.asarray(arrays[f"comp/threshold_{i}"])
                          for i in range(n_res)],
        }
    else:
        net._grad_compression_state = None
    net._iteration = int(meta.get("iteration", 0))
    net._epoch = int(meta.get("epoch", net._epoch))
    # pending device-side fetches reference pre-restore buffers
    net._pending_score = None
    net._pending_health = []
    return net


# ----------------------------------------------------------- sharded store
def _digest(data: bytes) -> str:
    """Content digest for torn-shard-set detection. crc32, not a crypto
    hash: the threat model is a partial write / crashed writer, not an
    adversary, and the digest runs on the background thread for every
    shard of every save — crc32 is ~5× cheaper than sha256 and releases
    the GIL, which matters next to a busy train loop."""
    return "crc32:%08x" % (zlib.crc32(data) & 0xFFFFFFFF)


def _partition_shards(arrays: Dict[str, np.ndarray],
                      n_shards: int) -> List[List[str]]:
    """Deterministic size-balanced partition of keys into shard files
    (greedy smallest-bin; per-host shards at pod scale, per-file here)."""
    n_shards = max(1, int(n_shards))
    bins: List[List[str]] = [[] for _ in range(n_shards)]
    sizes = [0] * n_shards
    for key in sorted(arrays, key=lambda k: (-arrays[k].nbytes, k)):
        i = sizes.index(min(sizes))
        bins[i].append(key)
        sizes[i] += arrays[key].nbytes
    return [sorted(b) for b in bins if b]


# the live checkpointers, for /debug/elastic + elastic.json
_checkpointers: "weakref.WeakSet" = weakref.WeakSet()
_reshape_totals: Dict[str, int] = {}
_totals_lock = threading.Lock()


def count_reshape(direction: str):
    with _totals_lock:
        _reshape_totals[direction] = _reshape_totals.get(direction, 0) + 1
    _reshapes_counter(direction).inc()
    _faults.record_event("mesh_reshape", direction=direction)


class ElasticCheckpointer:
    """Async sharded checkpoint store with an atomic versioned manifest.

    Layout under ``directory``::

        shards_<step>/shard_000.npz ...   (content-digested shard files)
        manifest_<step>.json              (atomic: tmp + fsync + rename)

    A save is only trusted once its manifest names every shard with a
    matching digest — the manifest rename is the commit point, and the
    ``checkpoint.manifest`` fault point fires right before it so chaos
    tests can prove a crash there leaves the previous complete save in
    charge. Rotation keeps the newest ``max_to_keep`` manifests.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 n_shards: Optional[int] = None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_to_keep = max(1, int(max_to_keep))
        self._n_shards = n_shards
        # coalescing latest-slot queue: at most ONE pending async save —
        # a newer snapshot supersedes a not-yet-started older one (the
        # restore path only ever wants the newest manifest, and an
        # unbounded queue behind a slow writer would pile up full model
        # snapshots in host memory)
        self._cv = threading.Condition()
        self._pending: Optional[tuple] = None
        self._busy = False
        self._worker: Optional[threading.Thread] = None
        self._worker_lock = threading.Lock()
        # one writer at a time: a synchronous boundary save and an async
        # cadence save can target the SAME step (same shard dir + tmp
        # names) — unserialized, one rename steals the other's tmp file
        self._write_lock = threading.Lock()
        self.last_error: Optional[BaseException] = None
        self.last_step: Optional[int] = None
        _checkpointers.add(self)

    # ------------------------------------------------------------- saving
    def shard_count(self) -> int:
        if self._n_shards is not None:
            return max(1, int(self._n_shards))
        try:
            return max(1, int(os.environ.get("DL4J_TPU_ELASTIC_SHARDS", 0)))
        except (TypeError, ValueError):
            pass
        return 1

    def save(self, step: int, net, mesh=None, sync: bool = False) -> bool:
        """Checkpoint ``net``'s full training state as of now. The state
        is snapshotted to host immediately; serialization + fsync +
        manifest commit happen on the background thread unless ``sync``.
        No-op under the kill switch. Returns whether a save was queued
        or performed."""
        if not elastic_enabled():
            return False
        arrays, meta = snapshot_net_state(net)
        meta["step"] = int(step)
        meta["mesh"] = self._mesh_meta(mesh)
        if sync:
            self._write(int(step), arrays, meta)
            _saves_counter("sync").inc()
            return True
        self._ensure_worker()
        with self._cv:
            superseded = self._pending is not None
            self._pending = (int(step), arrays, meta)
            self._cv.notify_all()
        _saves_counter("async").inc()
        if superseded:
            # the older queued snapshot never hit disk: its successor
            # carries strictly newer state, so nothing restorable is lost
            _saves_counter("coalesced").inc()
        _pending_gauge().set(1)
        return True

    @staticmethod
    def _mesh_meta(mesh) -> dict:
        if mesh is None:
            return {"n_devices": 1, "n_replicas": 1, "axes": {}}
        from deeplearning4j_tpu.parallel import mesh as _mesh
        from deeplearning4j_tpu.parallel.mesh import DATA_AXIS
        axes = {str(a): _mesh.axis_size(mesh, a) for a in mesh.axis_names}
        return {"n_devices": int(mesh.size),
                "n_replicas": axes.get(DATA_AXIS, 1), "axes": axes}

    def _ensure_worker(self):
        if self._worker is not None and self._worker.is_alive():
            return
        with self._worker_lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._worker = threading.Thread(
                target=self._drain, daemon=True,
                name="dl4j-elastic-checkpointer")
            self._worker.start()

    def _drain(self):
        try:
            # the writer must never compete with the train step for CPU:
            # SCHED_IDLE (allowed unprivileged on Linux, per-thread) runs
            # it only in the scheduler's slack — on a host whose cores
            # the step saturates, a normal-priority writer would tax
            # every step it overlaps (observed +10% on a 2-core box;
            # idle-priority puts the delta at the noise floor). The save
            # just finishes a little later, which rotation tolerates.
            os.sched_setscheduler(0, os.SCHED_IDLE, os.sched_param(0))
        except (AttributeError, OSError, PermissionError):
            pass                     # non-Linux: keep default priority
        while True:
            with self._cv:
                while self._pending is None:
                    self._cv.wait()
                step, arrays, meta = self._pending
                self._pending = None
                self._busy = True
            try:
                self._write(step, arrays, meta)
            # graftlint: disable=typed-errors — deliberate durability
            # policy: the failure is counted, ringed, and surfaced via
            # last_error; fit()'s finally re-saves synchronously
            except BaseException as e:   # an async save failing must not
                self.last_error = e      # kill training — count + warn
                _save_failures_counter().inc()
                _faults.record_event("elastic_save_failed", step=step,
                                     error=type(e).__name__,
                                     detail=str(e)[:200])
                log.warning("async elastic save of step %d failed (%s: "
                            "%s)", step, type(e).__name__, e)
            finally:
                with self._cv:
                    self._busy = False
                    self._cv.notify_all()
                _pending_gauge().set(
                    1 if self._pending is not None else 0)

    def _write(self, step: int, arrays: Dict[str, np.ndarray], meta: dict):
        with self._write_lock:
            self._write_locked(step, arrays, meta)

    def _write_locked(self, step: int, arrays: Dict[str, np.ndarray],
                      meta: dict):
        t0 = time.perf_counter()
        shard_dir = os.path.join(self.directory, f"shards_{step}")
        os.makedirs(shard_dir, exist_ok=True)
        shards = []
        for i, keys in enumerate(_partition_shards(arrays,
                                                   self.shard_count())):
            buf = io.BytesIO()
            np.savez(buf, **{k: arrays[k] for k in keys})
            data = buf.getvalue()
            fname = f"shard_{i:03d}.npz"
            path = os.path.join(shard_dir, fname)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            shards.append({
                "file": f"shards_{step}/{fname}",
                "bytes": len(data),
                "digest": _digest(data),
                "keys": keys,
                "dtypes": {k: str(arrays[k].dtype) for k in keys},
            })
        _fsync_dir(shard_dir)
        manifest = {
            "format_version": MANIFEST_VERSION,
            "step": int(step),
            "written_unix": time.time(),
            "shards": shards,
            **meta,
        }
        mpath = os.path.join(self.directory, f"{MANIFEST_PREFIX}{step}.json")
        tmp = mpath + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=1)
            f.flush()
            os.fsync(f.fileno())
        # the commit point: everything the manifest names is already
        # durable (shard fsync + dir fsync above), so a crash fired HERE
        # leaves the previous complete manifest in charge and never a
        # torn one — the checkpoint.manifest chaos point proves it
        _faults.check("checkpoint.manifest")
        os.replace(tmp, mpath)
        _fsync_dir(self.directory)
        self.last_step = int(step)
        _save_seconds_hist().observe(time.perf_counter() - t0)
        _faults.record_event("elastic_save", step=step,
                             shards=len(shards),
                             bytes=sum(s["bytes"] for s in shards))
        self._rotate()

    def _rotate(self):
        import shutil
        steps = self.all_steps()
        for old in steps[:-self.max_to_keep]:
            try:
                os.remove(os.path.join(self.directory,
                                       f"{MANIFEST_PREFIX}{old}.json"))
            except OSError:
                pass
            shutil.rmtree(os.path.join(self.directory, f"shards_{old}"),
                          ignore_errors=True)
        # sweep ORPHANED shard dirs too: a save that died between the
        # shard writes and the manifest commit (checkpoint.manifest
        # fault, crash, full disk) left a manifest-less full model copy
        # that step-keyed rotation would otherwise never visit
        kept = set(self.all_steps())
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        for name in entries:
            if not name.startswith("shards_"):
                continue
            try:
                step = int(name[len("shards_"):])
            except ValueError:
                continue
            if step not in kept:
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)
        for name in entries:       # stale tmp manifests from dead writers
            if name.startswith(MANIFEST_PREFIX) and name.endswith(".tmp"):
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    def wait(self):
        """Block until the newest queued async save is committed (older
        queued snapshots may have been coalesced away — the newest one
        is always written)."""
        with self._cv:
            while self._pending is not None or self._busy:
                self._cv.wait()

    # ------------------------------------------------------------ restore
    def all_steps(self) -> List[int]:
        out = []
        try:
            for name in os.listdir(self.directory):
                if name.startswith(MANIFEST_PREFIX) and \
                        name.endswith(".json"):
                    try:
                        out.append(int(name[len(MANIFEST_PREFIX):-5]))
                    except ValueError:
                        pass
        except OSError:
            pass
        return sorted(out)

    def _parse_manifest(self, step: int) -> Optional[dict]:
        mpath = os.path.join(self.directory,
                             f"{MANIFEST_PREFIX}{step}.json")
        try:
            with open(mpath) as f:
                return json.load(f)
        except (OSError, ValueError) as e:
            log.warning("skipping unreadable elastic manifest %s (%r)",
                        mpath, e)
            return None

    def _verify(self, manifest: dict,
                arrays: Optional[Dict[str, np.ndarray]] = None) -> bool:
        """A manifest is only trusted when every shard it names exists
        with a matching content digest — the shard-set analog of the
        PR-5 torn-zip skip. With ``arrays`` given, the verified bytes
        are also DECODED into it, so verification and restore share one
        read of each shard."""
        for sh in manifest.get("shards", []):
            path = os.path.join(self.directory, sh["file"])
            try:
                with open(path, "rb") as f:
                    data = f.read()
            except OSError:
                return False
            if len(data) != sh["bytes"] or _digest(data) != sh["digest"]:
                return False
            if arrays is not None:
                with np.load(io.BytesIO(data)) as z:
                    for k in z.files:
                        arrays[k] = z[k]
        return True

    def _complete(self, decode: bool):
        """Yield ``(manifest, arrays_or_None)`` for verified-complete
        saves, NEWEST step first, skipping torn/partial sets with a
        warning — THE one manifest-trust policy (the restore path and
        the inspection surface must never disagree about which save is
        in charge). With ``decode`` the verified bytes are also loaded,
        sharing one read per shard."""
        for step in reversed(self.all_steps()):
            manifest = self._parse_manifest(step)
            if manifest is None:
                continue
            arrays: Optional[Dict[str, np.ndarray]] = {} if decode else None
            if not self._verify(manifest, arrays):
                log.warning("skipping torn/partial elastic shard set for "
                            "step %s under %s", step, self.directory)
                continue
            yield manifest, arrays

    def complete_manifests(self) -> List[dict]:
        """Parsed manifests with a verified-complete shard set, NEWEST
        step first (inspection surface — the restore path stops at the
        first complete one instead of verifying the whole window)."""
        return [m for m, _ in self._complete(decode=False)]

    def restore(self, net, min_iteration: int = 0,
                target_replicas: Optional[int] = None) -> Optional[int]:
        """Restore the newest COMPLETE save. Manifests are verified
        lazily newest-first and verification shares one read per shard
        with the load — a multi-GB recovery never re-reads older
        checkpoints it won't use. Steps are iteration-keyed, so the
        newest complete manifest is also the max-iteration one and
        trivially satisfies the ``min_iteration`` boundary rule whenever
        any manifest does (the parameter is kept for parity with the
        zip path's ranking contract). Reshaping is counted when the
        saving topology differs from ``target_replicas``; the actual
        residual re-bucketing happens at the next mesh placement.
        Returns the restored iteration, or None when no complete save
        exists."""
        self.wait()
        chosen = arrays = None
        for chosen, arrays in self._complete(decode=True):
            break
        if chosen is None:
            return None
        apply_net_state(net, arrays, chosen)
        saved_n = int(chosen.get("mesh", {}).get("n_replicas", 1))
        reshaped = (target_replicas is not None
                    and saved_n != int(target_replicas))
        if reshaped:
            log.warning(
                "topology-reshaping restore: checkpoint step %s was "
                "written on a %d-replica mesh, restoring onto %d replicas "
                "(replicated state re-places; replica-keyed state is "
                "re-bucketed or re-seeded at the next placement)",
                chosen["step"], saved_n, target_replicas)
        _restores_counter(reshaped).inc()
        _faults.record_event("elastic_restore", step=chosen["step"],
                             iteration=chosen.get("iteration"),
                             saved_replicas=saved_n,
                             target_replicas=target_replicas,
                             reshaped=reshaped)
        return int(chosen.get("iteration", 0))

    def snapshot(self) -> dict:
        with self._cv:
            pending = (1 if self._pending is not None else 0) \
                + (1 if self._busy else 0)
        return {"directory": self.directory,
                "steps": self.all_steps(),
                "last_step": self.last_step,
                "pending_saves": pending,
                "max_to_keep": self.max_to_keep,
                "shard_count": self.shard_count(),
                "last_error": (repr(self.last_error)
                               if self.last_error else None)}


# ------------------------------------------------------------- observability
def snapshot() -> dict:
    """The elastic posture for ``/debug/elastic`` and the flight
    recorder's ``elastic.json`` bundle section."""
    with _totals_lock:
        reshapes = dict(_reshape_totals)
    elastic_events = [e for e in _faults.events()
                      if e.get("category") in (
                          "host_loss", "capacity_restored", "mesh_reshape",
                          "elastic_save", "elastic_save_failed",
                          "elastic_restore")]
    return {
        "enabled": elastic_enabled(),
        "capacity": _capacity.snapshot(),
        "recover_steps": recover_steps(),
        "reshapes": reshapes,
        "checkpointers": [c.snapshot() for c in list(_checkpointers)],
        "events": elastic_events,
    }


def _mesh_gauge():
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().gauge(
            "dl4j_elastic_mesh_size",
            "devices in the elastic trainer's active mesh (shrinks on "
            "host loss, re-expands when capacity returns)")
    return _faults.cached_metric_handle(("elastic_mesh",), make)


def set_mesh_size(n: int):
    _mesh_gauge().set(int(n))


def _reshapes_counter(direction: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_elastic_reshapes_total",
            "elastic mesh reshapes performed, by direction",
            label_names=("direction",)).labels(direction=direction)
    return _faults.cached_metric_handle(("elastic_reshape", direction), make)


def _restores_counter(reshaped: bool):
    key = "true" if reshaped else "false"

    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_elastic_restores_total",
            "restores from the sharded elastic manifest, split by "
            "whether the mesh topology changed since the save",
            label_names=("reshaped",)).labels(reshaped=key)
    return _faults.cached_metric_handle(("elastic_restore", key), make)


def _saves_counter(mode: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_elastic_saves_total",
            "sharded elastic checkpoint saves, by mode",
            label_names=("mode",)).labels(mode=mode)
    return _faults.cached_metric_handle(("elastic_save", mode), make)


def _save_failures_counter():
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_elastic_save_failures_total",
            "async elastic saves that failed in the background (training "
            "continues; the previous complete manifest stays in charge)")
    return _faults.cached_metric_handle(("elastic_save_fail",), make)


def _save_seconds_hist():
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().histogram(
            "dl4j_elastic_save_seconds",
            "background wall time of one sharded elastic save "
            "(serialize + fsync + manifest commit)")
    return _faults.cached_metric_handle(("elastic_save_secs",), make)


def _pending_gauge():
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().gauge(
            "dl4j_elastic_pending_saves",
            "async elastic saves queued behind the background writer")
    return _faults.cached_metric_handle(("elastic_pending",), make)
