"""Self-healing training: checkpoint-restore-resume under a restart budget.

The reference's recovery story is "Spark retries the task and the job
restarts from the last periodic checkpoint" (SURVEY §5.3). Here that loop
is first-class and *local*: :class:`ResilientTrainer` wraps a
MultiLayerNetwork / ComputationGraph / ShardedTrainer ``fit`` and, when a
step fails,

1. retries **in place** if the failure is transient (injected ``error``
   faults, :class:`~deeplearning4j_tpu.resilience.policy.TransientError`)
   — the fault fired before the jitted step consumed its donated buffers,
   so re-running is safe;
2. otherwise **restores the newest checkpoint** (written by the
   :class:`~deeplearning4j_tpu.optim.listeners.CheckpointListener` the
   trainer attaches, or a ``preempt_final_*``/initial checkpoint —
   reusing the utils/preemption machinery), **fast-forwards** the data
   iterator to the restored iteration, and resumes — bounded by
   ``max_restarts`` per ``fit`` call
   (:class:`~deeplearning4j_tpu.resilience.policy.RestartBudgetExhausted`
   beyond it);
3. batches that fail ``quarantine_after`` times are **quarantined** by
   :class:`SkippingIterator` (``dl4j_data_quarantined_total``) instead of
   aborting the epoch — one poisoned shard must not kill the run.

Every restart/restore/quarantine lands in the resilience event ring (→
flight-recorder ``resilience.json``) and the metrics registry. Under
``DL4J_TPU_RESILIENCE=0`` the trainer delegates straight to the wrapped
``fit`` — byte-identical behavior.
"""
from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional

from deeplearning4j_tpu.data.iterators import DataSetIterator
from deeplearning4j_tpu.resilience import elastic as _elastic
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.policy import (RestartBudgetExhausted,
                                                  RetryPolicy, is_transient)
from deeplearning4j_tpu.utils.preemption import TrainingPreempted

log = logging.getLogger("deeplearning4j_tpu")


class SkippingIterator(DataSetIterator):
    """Quarantining wrapper: positions that fail ``quarantine_after``
    times are pulled-and-discarded on later passes instead of re-poisoning
    the epoch. Positions are epoch-relative batch indices, so quarantine
    persists across epochs only while the order is stable: a backing
    iterator advertising ``shuffle`` truthy re-permutes per epoch, and
    ``reset()`` then drops the quarantine state (the old positions name
    different batches; a still-poisoned batch re-earns quarantine at its
    new position)."""

    def __init__(self, backing: DataSetIterator, quarantine_after: int = 2):
        self._backing = backing
        self.quarantine_after = max(1, int(quarantine_after))
        self._failures: Dict[int, int] = {}
        self._quarantined: set = set()
        self._pos = 0                      # next position to pull

    def reset(self):
        self._backing.reset()
        if getattr(self._backing, "shuffle", False):
            # positions are epoch-relative: after a reshuffle they name
            # DIFFERENT batches, so carried-over quarantine would discard
            # healthy data and re-admit the poisoned batch. Start over —
            # a still-poisoned batch re-earns quarantine at its new
            # position. (reset_replay keeps state: same permutation.)
            self._failures.clear()
            self._quarantined.clear()
        self._pos = 0

    def reset_replay(self):
        """Rewind for a SAME-epoch replay after a restore: the fast-
        forward must see the exact batch order already applied, so
        delegate to the backing iterator's ``reset_replay`` (shuffling
        iterators re-present the interrupted pass's permutation; the
        base-class default is a plain ``reset()``, correct for any
        iterator deterministic across resets — see class doc)."""
        b = self._backing
        if hasattr(b, "reset_replay"):
            b.reset_replay()
        else:
            b.reset()
        self._pos = 0

    def has_next(self) -> bool:
        return self._backing.has_next()

    def next(self):
        while True:
            if not self._backing.has_next():
                raise StopIteration("SkippingIterator exhausted")
            ds = self._backing.next()
            pos = self._pos
            self._pos += 1
            if pos in self._quarantined:
                continue                   # pull-and-discard
            return ds

    def batch(self) -> int:
        return self._backing.batch()

    def position(self) -> int:
        """Epoch-relative index of the most recently pulled batch."""
        return self._pos - 1

    def note_failure(self, pos: int):
        if pos < 0:
            return
        n = self._failures.get(pos, 0) + 1
        self._failures[pos] = n
        if n >= self.quarantine_after and pos not in self._quarantined:
            self._quarantined.add(pos)
            _quarantined_counter().inc()
            _faults.record_event("quarantine", position=pos, failures=n)
            log.warning("quarantining batch %d after %d failures", pos, n)

    def quarantined(self):
        return sorted(self._quarantined)


class _ElasticSaveListener:
    """Cadence listener of the elastic posture: every N iterations,
    queue an ASYNC sharded save (state snapshot on this thread — cheap
    host fetch — serialization/fsync/manifest commit on the
    checkpointer's background thread). The zip CheckpointListener's
    elastic twin; chaos coverage of the save path stays via the
    ``checkpoint.save`` point."""

    def __init__(self, ckpt: "_elastic.ElasticCheckpointer", target,
                 every: int):
        self.ckpt = ckpt
        self.target = target
        self.every = max(1, int(every))

    def on_epoch_start(self, net, epoch):
        pass

    def on_epoch_end(self, net, epoch):
        pass

    def iteration_done(self, net, iteration, epoch, score):
        if iteration % self.every == 0:
            _faults.check("checkpoint.save")
            self.ckpt.save(iteration, net,
                           mesh=getattr(self.target, "mesh", None))


def newest_checkpoint(directory: str) -> Optional[str]:
    """Newest *readable* checkpoint zip in ``directory`` (mtime, then
    the CheckpointListener counter, then name — the shared
    ``checkpoint_candidates`` ranking; torn files are never trusted)."""
    from deeplearning4j_tpu.utils.serialization import checkpoint_candidates
    paths = checkpoint_candidates(directory)
    return paths[0] if paths else None


class ResilientTrainer:
    """Wrap a net or ShardedTrainer's ``fit`` with restore-resume healing.

    ``target`` is a MultiLayerNetwork, ComputationGraph, or ShardedTrainer
    (the underlying net is found via its ``net`` attribute). Checkpoints
    go to ``checkpoint_dir`` every ``checkpoint_every_iterations`` steps
    (default 1: exact resume — raise it for large models and accept
    replaying up to a cadence's worth of batches after a restore).

    Deliberate tradeoff: the resilient loop drives batches synchronously
    (no :class:`DevicePrefetchIterator` wrap) — a prefetch thread holding
    in-flight device batches across a restore would make the replayed
    batch order unverifiable, and restore-resume's exactness guarantee is
    the point of this class. Wrap the plain ``fit`` when overlap matters
    more than self-healing.
    """

    def __init__(self, target, checkpoint_dir: str, max_restarts: int = 3,
                 checkpoint_every_iterations: int = 1,
                 keep_checkpoints: int = 3,
                 retry_policy: Optional[RetryPolicy] = None,
                 quarantine_after: int = 2,
                 elastic: bool = False,
                 elastic_dir: Optional[str] = None):
        self.target = target
        self.net = getattr(target, "net", target)
        self.checkpoint_dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)
        self.max_restarts = max(0, int(max_restarts))
        self.checkpoint_every = max(1, int(checkpoint_every_iterations))
        self.keep_checkpoints = max(1, int(keep_checkpoints))
        self.retry = retry_policy if retry_policy is not None \
            else RetryPolicy(max_retries=2, base_delay_seconds=0.01)
        self.quarantine_after = max(1, int(quarantine_after))
        self.restarts = 0
        self._lock = threading.Lock()
        #: elastic mode (DL4J_TPU_ELASTIC=0 kill switch read live at each
        #: fit): async SHARDED manifest checkpoints instead of zip saves,
        #: and host/device loss (HostLostError) handled by shrinking the
        #: mesh, restoring the manifest onto the smaller topology, and
        #: re-expanding when capacity returns. Needs a ShardedTrainer
        #: target (mesh reshaping is meaningless on a bare net).
        self.elastic = bool(elastic)
        self.elastic_dir = elastic_dir or os.path.join(checkpoint_dir,
                                                       "elastic")
        self._elastic_ckpt: Optional[_elastic.ElasticCheckpointer] = None
        self._elastic_live = False     # resolved once per fit() call
        self._elastic_warned = False
        # the trainer's CONFIGURED device pool, recorded at the first
        # elastic fit: shrink/re-expand moves within this list only — a
        # trainer built on a device subset must never be "expanded" onto
        # devices it was not configured to use just because the host has
        # more (capacity is global, the pool is this trainer's)
        self._elastic_devices = None

    # ------------------------------------------------------------------ fit
    def fit(self, data, labels=None, epochs: int = 1):
        """Iterator-driven resilient fit (mirrors the wrapped surface:
        ``fit(x, y)`` / ``fit(DataSet)`` / ``fit(iterator)``). Non-
        iterator inputs and the kill switch delegate to the wrapped
        ``fit`` unchanged — restore-resume needs a re-pullable iterator."""
        if (not _faults.resilience_enabled()
                or not isinstance(data, DataSetIterator)):
            return self.target.fit(data, labels, epochs=epochs)
        from deeplearning4j_tpu.optim.listeners import CheckpointListener
        self.restarts = 0          # the budget is per fit() call
        net = self.net
        if not net._initialized:
            net.init()
        it = data if isinstance(data, SkippingIterator) \
            else SkippingIterator(data, quarantine_after=self.quarantine_after)
        from deeplearning4j_tpu.observability import span as _span
        from deeplearning4j_tpu.observability.flight_recorder import (
            global_flight_recorder as _flight)
        # elastic posture resolved ONCE per fit (kill switch read live);
        # elastic without a mesh-bearing target degrades to the plain
        # zip path with a warning — reshaping a bare net is meaningless
        self._elastic_live = (self.elastic and _elastic.elastic_enabled()
                              and hasattr(self.target, "resize_mesh"))
        if self.elastic and _elastic.elastic_enabled() \
                and not self._elastic_live and not self._elastic_warned:
            self._elastic_warned = True
            log.warning("elastic mode requested but the target has no "
                        "mesh to reshape; using plain zip checkpoints")
        if self._elastic_live:
            if self._elastic_devices is None:
                self._elastic_devices = list(self.target.mesh.devices.flat)
            if self._elastic_ckpt is None:
                # one shard file per mesh device — the single-host analog
                # of per-host shards at pod scale (keeps each file small
                # enough to stream, and a lost shard tears only its set)
                self._elastic_ckpt = _elastic.ElasticCheckpointer(
                    self.elastic_dir, max_to_keep=self.keep_checkpoints,
                    n_shards=self.target.mesh.size)
            ckpt = _ElasticSaveListener(self._elastic_ckpt, self.target,
                                        self.checkpoint_every)
            _elastic.set_mesh_size(self.target.mesh.size)
        else:
            ckpt = CheckpointListener(
                self.checkpoint_dir,
                save_every_n_iterations=self.checkpoint_every,
                keep_last=self.keep_checkpoints)
        net.addListeners(ckpt)
        try:
            # ONE root span + flight-recorder arm for the whole fit (the
            # public per-batch fit would re-arm and open a new root trace
            # for every batch — see _fit_one, which enters below it)
            with _flight().arm(f"fit:{type(net).__name__}"), \
                    _span("fit", model=type(net).__name__, epochs=epochs,
                          resilient=True):
                self._fit_epochs(it, epochs)
        finally:
            net._listeners.remove(ckpt)
            if self._elastic_live and self._elastic_ckpt is not None:
                # never leave the final async save in flight: fit()
                # returning promises the newest manifest is durable
                self._elastic_ckpt.wait()
                if self._elastic_ckpt.last_error is not None:
                    # an async failure is only a log line + counter while
                    # training runs — but here we are about to RETURN, so
                    # "durable" must be made true inline (one sync
                    # attempt; failing that, warn loudly rather than
                    # discard the completed training by raising)
                    self._elastic_ckpt.last_error = None
                    try:
                        self._elastic_ckpt.save(
                            net._iteration, net,
                            mesh=getattr(self.target, "mesh", None),
                            sync=True)
                    # graftlint: disable=typed-errors — durability
                    # promise: never raise away a COMPLETED fit over a
                    # failed final save; warned + last_error recorded
                    except Exception as e:
                        log.warning(
                            "final elastic save failed after an async "
                            "failure (%s: %s); the newest durable "
                            "manifest may predate the last steps",
                            type(e).__name__, e)
        # same return as the delegate branch above (the wrapped fit
        # returns its target) — callers chain identically in both postures
        return self.target

    def _fit_epochs(self, it: "SkippingIterator", epochs: int):
        net = self.net
        for _ in range(epochs):
            # the restore target must never predate the epoch about
            # to start: with cadence > 1 the newest cadence
            # checkpoint can sit mid-PREVIOUS-epoch, and a restore
            # past the boundary would silently drop that epoch's tail
            # (this epoch's replay loop cannot reach it)
            self._save_boundary_with_budget()
            for lst in net._listeners:
                lst.on_epoch_start(net, net._epoch)
            self._fit_epoch(it)
            net._sync_score()
            for lst in net._listeners:
                lst.on_epoch_end(net, net._epoch)
            net._epoch += 1
            _tm_for(net).epochs.inc()

    def _fit_epoch(self, it: SkippingIterator):
        net = self.net
        iter0 = net._iteration
        target = 0                 # next batch position still to apply
        first_pass = True
        while True:                # restart loop: re-enter after a restore
            if first_pass:
                it.reset()         # fresh epoch: shuffle may advance
                first_pass = False
            else:
                # replay: the SAME order as the interrupted pass, or the
                # fast-forward would skip a different permutation than
                # the batches actually applied
                it.reset_replay()
            step_iter0 = None      # iteration before the failing _step
            try:
                while True:
                    step_iter0 = None
                    try:
                        ds = next(it)
                    except StopIteration:
                        return
                    if it.position() < target:
                        continue   # fast-forward: already in the params
                    step_iter0 = net._iteration
                    self._step(ds)
                    target = it.position() + 1
                    self._elastic_heartbeat()
            except (TrainingPreempted, KeyboardInterrupt,
                    RestartBudgetExhausted):
                raise
            except Exception as e:
                # if the iteration counter moved, the batch's update
                # LANDED and the failure came from the post-update tail
                # (e.g. a checkpoint.save error in a listener) — the
                # batch is innocent and must not be blamed/quarantined
                landed = (step_iter0 is not None
                          and net._iteration != step_iter0)
                # a lost host is never the batch's fault either — the
                # same batch replays fine on the shrunken mesh
                host_lost = isinstance(e, _elastic.HostLostError)
                target = self._recover(e, it, iter0, target,
                                       blame_batch=(not landed
                                                    and not host_lost),
                                       host_lost=host_lost)

    def _fit_one(self, ds):
        """One batch through the per-batch entry BELOW the public fit:
        ``target.fit(ds)`` would re-arm the flight recorder and open a
        fresh root ``fit`` trace for every batch — the single arm + root
        span in :meth:`fit` covers the whole run instead. (train.step /
        allreduce fault injection lives inside ``_fit_batch``, so chaos
        coverage is unchanged.)"""
        target = self.target
        if target is not self.net:
            # ShardedTrainer: mirror its _fit_impl per-batch path
            if not target._placed:
                target._place()
            target._fit_batch(ds.features, ds.labels,
                              target._ds_mask(ds, "features"),
                              target._ds_mask(ds, "labels"))
            target._check_preemption()
            return
        from deeplearning4j_tpu.nn.graph import (ComputationGraph, _as_tuple,
                                                 _ds_masks)
        if isinstance(target, ComputationGraph):
            target._fit_batch(_as_tuple(ds.features), _as_tuple(ds.labels),
                              _ds_masks(ds, "features"),
                              _ds_masks(ds, "labels"))
        else:
            target._fit_batch(ds.features, ds.labels,
                              getattr(ds, "features_mask", None),
                              getattr(ds, "labels_mask", None))

    def _step(self, ds):
        """One batch through the wrapped fit, retrying transient failures
        in place — but ONLY while the iteration counter proves the update
        never landed (train.step faults fire before the jitted step
        consumes its donated buffers, so a rerun is exact; a transient
        failure AFTER the update — e.g. a checkpoint.save fault in the
        listener — must take the restore path or the batch would apply
        twice)."""
        net = self.net
        start_iter = net._iteration

        def retryable(e):
            return is_transient(e) and net._iteration == start_iter

        try:
            self.retry.call(lambda: self._fit_one(ds), op="train.step",
                            retry_on=retryable)
        except Exception as e:
            if is_transient(e) and net._iteration != start_iter:
                # the update landed and only the post-step tail (e.g. a
                # checkpoint.save fault in the listener) failed
                # transiently: keep the applied update — the next
                # iteration's cadence save checkpoints a newer state, and
                # a crash before then restores + replays exactly
                log.warning("post-update transient failure (%s); update "
                            "kept, not re-applied", type(e).__name__)
                return
            _tm_for(net).step_failures.inc()
            raise

    # ------------------------------------------------------------- recovery
    def _elastic_pool_size(self) -> int:
        """How many of THIS trainer's configured devices the global
        capacity view currently allows: the global loss count is charged
        against the pool, floored at one device — a subset trainer never
        grows past its configured devices, and never shrinks to zero."""
        cap = _elastic.global_capacity()
        pool = len(self._elastic_devices)
        lost_global = cap.total() - cap.available()
        return max(1, pool - min(lost_global, pool - 1))

    def _elastic_heartbeat(self):
        """After each healthy step in elastic mode: feed the capacity
        tracker and, when capacity came back, re-expand the mesh (warm
        re-place on the next batch — params/opt-state are live, so no
        restore is needed on the way UP). Re-expansion is capped at the
        trainer's CONFIGURED device pool."""
        if not self._elastic_live:
            return
        _elastic.global_capacity().note_step()
        avail = self._elastic_pool_size()
        cur = self.target.mesh.size
        if avail > cur:
            log.warning("capacity returned (%d -> %d devices); "
                        "re-expanding the mesh", cur, avail)
            self._resize_mesh(avail, "expand")

    def _resize_mesh(self, n_devices: int, direction: str):
        self.target.resize_mesh(self._elastic_devices[:n_devices])
        _elastic.count_reshape(direction)
        _elastic.set_mesh_size(self.target.mesh.size)

    def _recover(self, error: BaseException, it: SkippingIterator,
                 iter0: int, target: int, blame_batch: bool = True,
                 host_lost: bool = False) -> int:
        """Count the restart, mark the failing batch, restore the newest
        checkpoint; returns the batch position to fast-forward to."""
        self.restarts += 1
        log.warning("training step failed (%s: %s); restart %d/%d",
                    type(error).__name__, error, self.restarts,
                    self.max_restarts)
        if self.restarts > self.max_restarts:
            raise RestartBudgetExhausted(
                f"training failed {self.restarts} times; restart budget "
                f"({self.max_restarts}) exhausted") from error
        # counted only for restarts actually PERFORMED — the exhausting
        # attempt above restores nothing and must not inflate the metric
        _restarts_counter(self.net).inc()
        _faults.record_event("restart", restarts=self.restarts,
                             error=type(error).__name__,
                             detail=str(error)[:200])
        if host_lost and self._elastic_live:
            # SHRINK before restoring: the restore must land on the mesh
            # that will actually run (buffers on the lost devices are
            # gone; replaying onto the full mesh would touch them)
            avail = self._elastic_pool_size()
            if avail < self.target.mesh.size:
                log.warning("shrinking the mesh to the %d surviving "
                            "device(s) before restore", avail)
                self._resize_mesh(avail, "shrink")
        elif host_lost:
            # non-elastic posture: the zip restore below re-runs on the
            # SAME mesh and nothing will ever feed note_step, so leaving
            # the process-wide capacity view degraded would poison a
            # later elastic fit in this process with a phantom loss
            _elastic.global_capacity().restore_capacity()
        # only the batch actually being APPLIED can be at fault —
        # positions below ``target`` are already inside the params (a
        # flaky re-pull during fast-forward must not quarantine them:
        # _position_for assumes quarantined positions were never applied),
        # and a failure AFTER the update landed (blame_batch=False) came
        # from the post-update tail, not the batch
        if blame_batch and it.position() >= target:
            it.note_failure(it.position())
        restored_iter = self._restore_latest(min_iteration=iter0)
        if restored_iter < iter0:
            # should not happen (a boundary checkpoint is written at every
            # epoch start) — but if the directory was tampered with, say
            # so instead of silently losing the prior epoch's tail
            log.warning(
                "restored checkpoint (iteration %d) predates the epoch "
                "boundary (iteration %d); updates between them cannot be "
                "replayed by this epoch's loop", restored_iter, iter0)
        return self._position_for(it, max(0, restored_iter - iter0))

    @staticmethod
    def _position_for(it: SkippingIterator, applied: int) -> int:
        """Map a count of APPLIED batches back to the iterator position to
        resume from: quarantined positions never advanced the iteration
        counter, so they don't count toward ``applied``."""
        pos = seen = 0
        while seen < applied:
            if pos not in it._quarantined:
                seen += 1
            pos += 1
        return pos

    def _restore_latest_elastic(self, min_iteration: int) -> Optional[int]:
        """Restore from the newest COMPLETE sharded manifest onto the
        CURRENT (possibly just-shrunken) mesh. Returns the restored
        iteration, or None to fall through to the zip path (no manifest
        yet, or the manifest store is unreadable)."""
        from deeplearning4j_tpu.parallel import mesh as _mesh
        from deeplearning4j_tpu.parallel.mesh import DATA_AXIS
        n_replicas = _mesh.axis_size(self.target.mesh, DATA_AXIS) \
            if DATA_AXIS in self.target.mesh.axis_names \
            else self.target.mesh.size

        def _do():
            _faults.check("checkpoint.restore")
            return self._elastic_ckpt.restore(
                self.net, min_iteration=min_iteration,
                target_replicas=n_replicas)
        try:
            restored = self.retry.call(_do, op="checkpoint.restore")
        except (TrainingPreempted, KeyboardInterrupt):
            raise
        # graftlint: disable=typed-errors — documented fallback: an
        # unrestorable manifest yields to the zip-checkpoint path
        except Exception as e:
            log.warning("elastic manifest restore failed (%s: %s); "
                        "falling back to zip checkpoints",
                        type(e).__name__, e)
            return None
        if restored is None:
            return None
        if hasattr(self.target, "_placed"):
            # restored state is host arrays — warm re-place onto the
            # (possibly reshaped) mesh before the next step
            self.target._placed = False
        _restores_counter().inc()
        _faults.record_event("restore", path="elastic_manifest",
                             iteration=restored)
        log.warning("restored elastic manifest (iteration %d) onto a "
                    "%d-replica mesh", restored, n_replicas)
        return restored

    def _restore_latest(self, min_iteration: int = 0) -> int:
        if self._elastic_live and self._elastic_ckpt is not None:
            restored = self._restore_latest_elastic(min_iteration)
            if restored is not None:
                return restored
        from deeplearning4j_tpu.utils import strengthen_dtypes
        from deeplearning4j_tpu.utils.serialization import (
            ModelSerializer, checkpoint_candidates)
        paths = checkpoint_candidates(self.checkpoint_dir)
        if not paths:
            raise RestartBudgetExhausted(
                f"no readable checkpoint in {self.checkpoint_dir} to "
                "restore from")
        # newest candidate that does NOT predate the epoch boundary: the
        # mtime ranking can tie the boundary checkpoint with the previous
        # epoch's last cadence file on coarse-mtime filesystems, and the
        # zip's own iteration counter is the authoritative tiebreak
        restored = path = last_err = None
        for cand in paths:
            def _do(c=cand):
                _faults.check("checkpoint.restore")
                return ModelSerializer.restore(c, load_updater=True)
            try:
                r = self.retry.call(_do, op="checkpoint.restore")
            except (TrainingPreempted, KeyboardInterrupt):
                raise
            # graftlint: disable=typed-errors — documented fallback:
            # skip-to-next-newest instead of killing fit()
            except Exception as e:
                # structurally-valid-but-unrestorable zips (stray export,
                # different model class, content corruption) rank like any
                # other candidate — skip to the next-newest, as the
                # candidates docstring promises, instead of killing fit()
                last_err = e
                log.warning("checkpoint %s failed to restore (%s: %s); "
                            "trying next-newest", cand, type(e).__name__, e)
                continue
            if restored is None:
                restored, path = r, cand       # newest = the fallback
            if r._iteration >= min_iteration:
                restored, path = r, cand
                break
        if restored is None:
            raise RestartBudgetExhausted(
                f"no restorable checkpoint in {self.checkpoint_dir}"
            ) from last_err
        net = self.net
        net.set_param_tree(restored._params)
        net._states = strengthen_dtypes(restored._states)
        net._opt_state = restored._opt_state
        # compressed-exchange error-feedback state rides the checkpoint:
        # without it a restore-resume run replays with a zero residual and
        # diverges from the uninterrupted one
        net._grad_compression_state = getattr(
            restored, "_grad_compression_state", None)
        net._iteration = restored._iteration
        # epoch bookkeeping stays ours (the checkpoint's epoch counter may
        # lag the restart loop); pending device-side fetches are stale
        net._pending_score = None
        net._pending_health = []
        if self.target is not net and hasattr(self.target, "_placed"):
            # ShardedTrainer: restored params are host arrays — re-place
            # them on the mesh before the next step (warm start preserves
            # the restored optimizer moments)
            self.target._placed = False
        _restores_counter().inc()
        _faults.record_event("restore", path=os.path.basename(path),
                             iteration=net._iteration)
        log.warning("restored checkpoint %s (iteration %d)", path,
                    net._iteration)
        return net._iteration

    def _save_boundary_checkpoint(self):
        """Snapshot the epoch-boundary state (one rotating file, atomic
        overwrite). Doubles as the initial checkpoint: batch 0 failing
        with an empty directory is recoverable too."""
        from deeplearning4j_tpu.utils.serialization import save_model_atomic
        net = self.net
        if self._elastic_live and self._elastic_ckpt is not None:
            # elastic boundary saves are SYNCHRONOUS: the epoch must not
            # start until its restore anchor is durable (the cadence
            # saves inside the epoch stay async/off the critical path)
            def _do_elastic():
                _faults.check("checkpoint.save")
                self._elastic_ckpt.save(net._iteration, net,
                                        mesh=self.target.mesh, sync=True)

            self.retry.call(_do_elastic, op="checkpoint.save")
            return
        path = os.path.join(self.checkpoint_dir,
                            f"resilient_boundary_{type(net).__name__}.zip")

        def _do():
            _faults.check("checkpoint.save")
            save_model_atomic(net, path)

        self.retry.call(_do, op="checkpoint.save")

    def _save_boundary_with_budget(self):
        """Boundary saves get the same bounded-restart treatment as step
        failures: a non-transient (or retry-exhausting) save error must
        consume the restart budget and be re-attempted, not abort fit()
        on the spot — the identical failure one step later, inside
        CheckpointListener, is absorbed by _fit_epoch's recovery path.
        (Nothing to restore: the params are intact; only the save
        failed.)"""
        while True:
            try:
                self._save_boundary_checkpoint()
                return
            except (TrainingPreempted, KeyboardInterrupt,
                    RestartBudgetExhausted):
                raise
            except Exception as e:
                self.restarts += 1
                log.warning("boundary checkpoint save failed (%s: %s); "
                            "restart %d/%d", type(e).__name__, e,
                            self.restarts, self.max_restarts)
                if self.restarts > self.max_restarts:
                    raise RestartBudgetExhausted(
                        f"boundary checkpoint save failed; restart budget "
                        f"({self.max_restarts}) exhausted") from e
                _faults.record_event("restart", restarts=self.restarts,
                                     error=type(e).__name__,
                                     detail=str(e)[:200])


# ------------------------------------------------------------ metric handles
# handles live in faults' shared cache (one reset hook for the layer)
def _quarantined_counter():
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_data_quarantined_total",
            "batches quarantined by SkippingIterator after repeated "
            "failures")
    return _faults.cached_metric_handle(("quarantine",), make)


def _restores_counter():
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_checkpoint_restores_total",
            "checkpoint restores performed by ResilientTrainer")
    return _faults.cached_metric_handle(("restores",), make)


def _restarts_counter(net):
    kind = type(net).__name__

    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_resilience_restarts_total",
            "restore-resume restarts performed by ResilientTrainer",
            label_names=("model",)).labels(model=kind)
    return _faults.cached_metric_handle(("restarts", kind), make)


def _tm_for(net):
    from deeplearning4j_tpu.observability import train_metrics as _tm
    return _tm.for_model(net)
