"""Multi-tenant QoS: tenant identity, quotas, weighted-fair queueing,
priority tiers, and per-tenant accounting.

Heavy production traffic is not one FIFO queue: a single flooding caller
can fill the front door's in-flight gate, the bounded serving queues,
and every generation slot, starving everyone else — the PR-5 admission
control degrades *gracefully* but not *fairly*. This module adds the
tenant dimension the whole serving path threads through
(ROADMAP item 5; the production-serving posture of large-scale ML
systems, Abadi et al. arXiv:1605.08695 §9 — DL4J's ParallelInference
serving layer grown into a fair multi-tenant one):

- :class:`TenantPolicy` / :class:`TenantRegistry` — per-tenant weight,
  optional priority tier, and request-rate / token-rate quotas enforced
  by token buckets (the PR-5 ``RetryBudget`` pattern generalized to a
  continuous-refill bucket). Env/JSON-configurable via
  ``DL4J_TPU_TENANT_CONFIG`` (inline JSON or a file path); traffic with
  no tenant label rides the **default tenant** and behaves exactly as
  before.
- :class:`QuotaExceeded` — typed admission outcome (a
  :class:`~deeplearning4j_tpu.resilience.policy.ShedError` subclass, so
  every existing error-accounting surface treats it as a lifecycle
  result, and the HTTP front door maps it to 429). It carries
  ``retry_after_s`` — the bucket's refill time — which the front door
  turns into a ``Retry-After`` header.
- :class:`FairQueue` — the drop-in replacement for the single-FIFO
  serving queues: deficit-weighted round-robin over per-tenant FIFOs
  (DRR: each visit grants ``quantum x weight`` deficit; a request pops
  when its cost fits), grouped by priority tier (a higher tier always
  pops first), with tenant-aware full-queue shedding
  (:meth:`FairQueue.pick_victim`: shed the most over-share tenant's
  newest request, never an under-share one).
- :class:`PreemptedError` — a typed shed outcome for step-boundary slot
  preemption in ``GenerationPipeline``: a higher-tier request may claim
  the slot of the most over-share tenant's longest-running lower-tier
  request; the preempted caller resolves typed, never hangs.
- Per-tenant accounting — ``dl4j_tenant_{requests,tokens,shed,
  cost_flops}_total{tenant}`` and a per-tenant latency histogram, all
  label-bounded through :func:`tenant_label` (configured tenants plus
  the first ``DL4J_TPU_TENANT_TOP_N`` unconfigured ones get their own
  series; the rest fold into one ``__other__`` overflow bucket, so an
  attacker spraying tenant ids cannot explode the registry).
  Request cost is the PR-6 cost model's FLOPs for the executed bucket
  (or prefill + per-slot decode-step share), attributed per tenant.

Kill switch ``DL4J_TPU_QOS=0`` (read live): the serving paths construct
their original FIFO queues, no tenant series are created, and the front
door skips quota admission — byte-identical pre-QoS behavior, asserted
in tests like the resilience/rollout switches. Pipeline-level QoS also
requires the resilience layer (``DL4J_TPU_RESILIENCE=1``): fair
scheduling sheds typed outcomes, which is resilience machinery.
"""
from __future__ import annotations

import json
import os
import queue as _queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.policy import ShedError

#: the tenant every unlabeled request rides — its default policy is
#: unlimited, so pre-QoS callers see identical behavior
DEFAULT_TENANT = "default"

#: the bounded-cardinality overflow label for tenants beyond the top-N
OVERFLOW_TENANT = "__other__"


def qos_enabled() -> bool:
    """``DL4J_TPU_QOS`` kill switch (read live, like the resilience and
    rollout switches — flipping it affects new pipelines/requests
    without a restart)."""
    return os.environ.get("DL4J_TPU_QOS", "1") != "0"


def tenant_top_n() -> int:
    """``DL4J_TPU_TENANT_TOP_N``: how many *unconfigured* tenants get
    their own metric label before folding into ``__other__``."""
    try:
        return max(0, int(os.environ.get("DL4J_TPU_TENANT_TOP_N", 16)))
    except (TypeError, ValueError):
        return 16


class QuotaExceeded(ShedError):
    """The tenant is over its request-rate or token-rate quota — a typed
    admission outcome (HTTP 429 at the front door). ``retry_after_s`` is
    the quota bucket's refill time for one unit of work — the
    ``Retry-After`` header the front door derives."""

    def __init__(self, message: str, tenant: str = DEFAULT_TENANT,
                 quota: str = "request", retry_after_s: float = 1.0):
        super().__init__(message)
        self.tenant = tenant
        self.quota = quota
        self.retry_after_s = float(retry_after_s)


class PreemptedError(ShedError):
    """This request's generation slot was claimed by a higher-priority
    tenant at a decode step boundary — a typed lifecycle outcome; the
    caller may re-submit (its tokens so far are lost)."""


# ------------------------------------------------------------ token bucket
class TokenBucket:
    """Continuous-refill token bucket (the RetryBudget pattern with a
    rate): ``rate`` tokens/second refill up to ``burst``. Two admission
    styles: :meth:`try_acquire` (classic — spend-or-refuse, for
    request-rate quotas where the cost of one unit is known) and the
    debt model via :meth:`charge` + :meth:`in_debt` (for token quotas
    where a generation's cost is only known after it ran: admission
    requires a non-negative balance, usage is charged after the fact and
    may push the balance negative — the next admission waits out the
    debt)."""

    def __init__(self, rate: float, burst: Optional[float] = None):
        self.rate = max(1e-9, float(rate))
        self.burst = float(burst) if burst is not None else \
            max(1.0, self.rate)
        self._level = self.burst
        self._at = time.monotonic()
        self._lock = threading.Lock()

    def _refill_locked(self, now: float):
        self._level = min(self.burst,
                          self._level + (now - self._at) * self.rate)
        self._at = now

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill_locked(time.monotonic())
            if self._level >= n:
                self._level -= n
                return True
            return False

    def charge(self, n: float):
        """Post-hoc usage charge; may drive the level negative (debt)."""
        with self._lock:
            self._refill_locked(time.monotonic())
            self._level -= float(n)

    def in_debt(self) -> bool:
        with self._lock:
            self._refill_locked(time.monotonic())
            return self._level < 0.0

    def level(self) -> float:
        with self._lock:
            self._refill_locked(time.monotonic())
            return self._level

    def time_to(self, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens are available (0 when they already
        are) — the Retry-After derivation."""
        with self._lock:
            self._refill_locked(time.monotonic())
            missing = n - self._level
        return max(0.0, missing / self.rate)


# ------------------------------------------------------------- policies
class TenantPolicy:
    """One tenant's QoS contract. ``None`` rates mean unlimited (the
    default tenant ships unlimited so unlabeled traffic is untouched).
    ``weight`` drives the deficit-weighted round-robin share;
    ``priority`` is the preemption tier (higher preempts lower; equal
    tiers never preempt — the default 0 everywhere disables it)."""

    __slots__ = ("name", "weight", "priority", "request_rate",
                 "request_burst", "token_rate", "token_burst")

    def __init__(self, name: str, weight: float = 1.0, priority: int = 0,
                 request_rate: Optional[float] = None,
                 request_burst: Optional[float] = None,
                 token_rate: Optional[float] = None,
                 token_burst: Optional[float] = None):
        if weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0, "
                             f"got {weight}")
        for label, rate in (("request_rate", request_rate),
                            ("token_rate", token_rate)):
            if rate is not None and rate <= 0:
                # a falsy 0 would silently skip bucket creation and
                # mean UNLIMITED — the opposite of an operator's
                # "block this tenant" intent. Refuse loudly; blocking
                # is a tiny positive rate.
                raise ValueError(
                    f"tenant {name!r}: {label} must be > 0 or None "
                    f"(got {rate}); to effectively block a tenant use "
                    "a tiny rate like 0.001")
        self.name = str(name)
        self.weight = float(weight)
        self.priority = int(priority)
        self.request_rate = (float(request_rate)
                             if request_rate is not None else None)
        self.request_burst = (float(request_burst)
                              if request_burst is not None else None)
        self.token_rate = (float(token_rate)
                           if token_rate is not None else None)
        self.token_burst = (float(token_burst)
                            if token_burst is not None else None)

    @classmethod
    def from_dict(cls, name: str, doc: dict) -> "TenantPolicy":
        known = {"weight", "priority", "request_rate", "request_burst",
                 "token_rate", "token_burst"}
        alien = set(doc) - known
        if alien:
            raise ValueError(
                f"tenant {name!r}: unknown policy keys {sorted(alien)} "
                f"(known: {sorted(known)})")
        return cls(name, **doc)

    def to_dict(self) -> dict:
        return {"weight": self.weight, "priority": self.priority,
                "request_rate": self.request_rate,
                "request_burst": self.request_burst,
                "token_rate": self.token_rate,
                "token_burst": self.token_burst}


class _TenantState:
    """Runtime state per tenant: quota buckets + lifetime counters."""

    __slots__ = ("policy", "req_bucket", "tok_bucket", "requests",
                 "tokens", "shed", "cost_flops", "configured")

    def __init__(self, policy: TenantPolicy, configured: bool):
        self.policy = policy
        self.configured = configured
        self.req_bucket = (TokenBucket(policy.request_rate,
                                       policy.request_burst)
                           if policy.request_rate else None)
        self.tok_bucket = (TokenBucket(policy.token_rate,
                                       policy.token_burst)
                          if policy.token_rate else None)
        self.requests = 0
        self.tokens = 0.0
        self.shed = 0
        self.cost_flops = 0.0


class TenantRegistry:
    """The process-wide tenant policy + accounting store. One instance
    via :func:`global_tenants`; tests may construct their own (FairQueue
    takes the registry explicitly)."""

    def __init__(self, load_env: bool = True):
        self._lock = threading.Lock()
        self._states: Dict[str, _TenantState] = {}
        self._default_policy = TenantPolicy(DEFAULT_TENANT)
        self._labels: Dict[str, str] = {}   # tenant -> bounded label
        self._n_unconfigured = 0
        # bumped on configure(); FairQueue caches policy views against
        # it so the pop hot path pays one registry-lock hit per tenant
        # per config generation, not per pop
        self.version = 0
        if load_env:
            self._load_env()

    @staticmethod
    def _max_tracked() -> int:
        """Distinct UNCONFIGURED tenants that get their own state/label
        entry before folding into the shared overflow state — an
        id-spraying caller must not grow `_states`/`_labels` (and with
        them /debug/tenants and tenants.json) without bound."""
        return max(256, 8 * tenant_top_n())

    # --------------------------------------------------------- config
    def _load_env(self):
        raw = os.environ.get("DL4J_TPU_TENANT_CONFIG")
        if not raw:
            return
        text = raw
        if not raw.lstrip().startswith("{"):
            with open(raw, encoding="utf-8") as f:
                text = f.read()
        doc = json.loads(text)
        if not isinstance(doc, dict):
            raise ValueError("DL4J_TPU_TENANT_CONFIG must be a JSON "
                             "object {default?, tenants?}")
        self.configure(
            {name: TenantPolicy.from_dict(name, spec)
             for name, spec in (doc.get("tenants") or {}).items()},
            default=(TenantPolicy.from_dict(DEFAULT_TENANT, doc["default"])
                     if isinstance(doc.get("default"), dict) else None))

    def configure(self, policies: Dict[str, TenantPolicy],
                  default: Optional[TenantPolicy] = None):
        """(Re)install tenant policies. Existing tenants keep their
        lifetime counters but take fresh quota buckets (a live config
        push resets debt — operators expect a raised quota to admit
        immediately)."""
        with self._lock:
            if default is not None:
                self._default_policy = default
            for name, pol in policies.items():
                prev = self._states.get(name)
                st = _TenantState(pol, configured=True)
                if prev is not None:
                    st.requests, st.tokens = prev.requests, prev.tokens
                    st.shed, st.cost_flops = prev.shed, prev.cost_flops
                if prev is not None and not prev.configured:
                    self._n_unconfigured -= 1
                self._states[name] = st
                # a tenant first seen unconfigured may have folded into
                # the overflow label; configuring it grants its own
                self._labels.pop(name, None)
            self.version += 1

    # ------------------------------------------------------- identity
    @staticmethod
    def resolve(tenant) -> str:
        """Canonical tenant name for a request label (None/empty → the
        default tenant; whitespace trimmed; length-bounded so a header
        cannot smuggle megabytes into queues and snapshots)."""
        if tenant is None:
            return DEFAULT_TENANT
        name = str(tenant).strip()
        return name[:128] if name else DEFAULT_TENANT

    def _state(self, tenant: str) -> _TenantState:
        st = self._states.get(tenant)
        if st is None:
            name = tenant
            if (self._n_unconfigured >= self._max_tracked()
                    and tenant != DEFAULT_TENANT):
                # past the tracking cap every fresh unconfigured name
                # shares ONE overflow state (one bucket set, one row in
                # snapshots) — hostile id-spraying stays O(1)
                name = OVERFLOW_TENANT
                st = self._states.get(name)
                if st is not None:
                    return st
            st = self._states[name] = _TenantState(
                TenantPolicy(name,
                             weight=self._default_policy.weight,
                             priority=self._default_policy.priority,
                             request_rate=self._default_policy.request_rate,
                             request_burst=self._default_policy.request_burst,
                             token_rate=self._default_policy.token_rate,
                             token_burst=self._default_policy.token_burst),
                configured=False)
            self._n_unconfigured += 1
        return st

    def policy(self, tenant) -> TenantPolicy:
        with self._lock:
            return self._state(self.resolve(tenant)).policy

    def weight(self, tenant) -> float:
        return self.policy(tenant).weight

    def priority(self, tenant) -> int:
        return self.policy(tenant).priority

    # ------------------------------------------------------ admission
    def admit(self, tenant) -> str:
        """Quota gate for one arriving request: spends a request-rate
        token and requires the token-rate bucket to be out of debt.
        Raises :class:`QuotaExceeded` (counted per tenant) when either
        quota refuses; returns the resolved tenant name otherwise."""
        name = self.resolve(tenant)
        with self._lock:
            st = self._state(name)
            req_bucket, tok_bucket = st.req_bucket, st.tok_bucket
        # token-debt first: it consumes nothing, so a tenant waiting
        # out its debt doesn't ALSO drain its request-rate bucket on
        # every (correctly paced) retry and stay throttled past what
        # either quota implies
        if tok_bucket is not None and tok_bucket.in_debt():
            retry = tok_bucket.time_to(0.0)
            self.count_shed(name, "quota")
            raise QuotaExceeded(
                f"tenant {name!r} over its token-rate quota "
                f"({st.policy.token_rate} tokens/s); retry in "
                f"{retry:.3f}s", tenant=name, quota="token",
                retry_after_s=retry)
        if req_bucket is not None and not req_bucket.try_acquire():
            retry = req_bucket.time_to(1.0)
            self.count_shed(name, "quota")
            raise QuotaExceeded(
                f"tenant {name!r} over its request-rate quota "
                f"({st.policy.request_rate}/s); retry in {retry:.3f}s",
                tenant=name, quota="request", retry_after_s=retry)
        return name

    def over_quota(self, tenant) -> bool:
        """Is the tenant currently past either quota? (The tenant-aware
        shed-victim tie-breaker: prefer shedding someone already over
        their contract.)"""
        with self._lock:
            st = self._states.get(self.resolve(tenant))
        if st is None:
            return False
        if st.req_bucket is not None and st.req_bucket.level() < 1.0:
            return True
        return st.tok_bucket is not None and st.tok_bucket.in_debt()

    # ----------------------------------------------------- accounting
    def observe_request(self, tenant, latency_s: float,
                        error: Optional[BaseException] = None):
        """One resolved request's per-tenant accounting (success, typed
        shed, and error paths all share it)."""
        name = self.resolve(tenant)
        with self._lock:
            self._state(name).requests += 1
        label = self.tenant_label(name)
        _tenant_requests(label).inc()
        _tenant_latency(label).observe(max(0.0, float(latency_s)))

    def account_tokens(self, tenant, n: float):
        """Charge ``n`` tokens of usage (emitted generation tokens, or
        scored examples on the classify path) against the tenant's token
        bucket (debt model) and the per-tenant counter."""
        if n <= 0:
            return
        name = self.resolve(tenant)
        with self._lock:
            st = self._state(name)
            st.tokens += float(n)
            bucket = st.tok_bucket
        if bucket is not None:
            bucket.charge(n)
        _tenant_tokens(self.tenant_label(name)).inc(float(n))

    def account_cost(self, tenant, flops: float):
        """Attribute ``flops`` of accounted device work (the PR-6 cost
        model's bucket/prefill/decode FLOPs) to the tenant."""
        if not flops or flops <= 0:
            return
        name = self.resolve(tenant)
        with self._lock:
            self._state(name).cost_flops += float(flops)
        _tenant_cost(self.tenant_label(name)).inc(float(flops))

    def count_shed(self, tenant, reason: str):
        name = self.resolve(tenant)
        with self._lock:
            self._state(name).shed += 1
        _tenant_shed(self.tenant_label(name), reason).inc()
        _faults.record_event("tenant_shed", tenant=name, reason=reason)

    # --------------------------------------------------------- labels
    def tenant_label(self, tenant) -> str:
        """THE bounded-cardinality label mapper every ``{tenant}`` metric
        series routes through (lint-enforced by check_metric_names):
        configured tenants always get their own label; the first
        ``DL4J_TPU_TENANT_TOP_N`` *unconfigured* tenants do too; every
        further distinct name folds into ``__other__``."""
        name = self.resolve(tenant)
        with self._lock:
            label = self._labels.get(name)
            if label is not None:
                return label
            st = self._states.get(name)
            if (st is not None and st.configured) or name == DEFAULT_TENANT:
                label = name
            elif len(self._labels) >= self._max_tracked():
                # the label CACHE is bounded too: past the cap the
                # answer is always the overflow bucket — return it
                # without remembering yet another sprayed name
                return OVERFLOW_TENANT
            else:
                distinct = sum(1 for t, lb in self._labels.items()
                               if lb == t and not (
                                   t in self._states
                                   and self._states[t].configured)
                               and t != DEFAULT_TENANT)
                label = name if distinct < tenant_top_n() else \
                    OVERFLOW_TENANT
            self._labels[name] = label
            return label

    # ------------------------------------------------------- queries
    def snapshot(self) -> dict:
        """``/debug/tenants`` + the flight recorder's ``tenants.json``:
        policies, live bucket levels, and lifetime per-tenant counters."""
        with self._lock:
            states = dict(self._states)
            default = self._default_policy
            labels = dict(self._labels)
        tenants = {}
        for name, st in sorted(states.items()):
            tenants[name] = {
                "policy": st.policy.to_dict(),
                "configured": st.configured,
                "label": labels.get(name, name),
                "requests": st.requests,
                "tokens": st.tokens,
                "shed": st.shed,
                "cost_flops": st.cost_flops,
                "request_bucket_level": (st.req_bucket.level()
                                         if st.req_bucket else None),
                "token_bucket_level": (st.tok_bucket.level()
                                       if st.tok_bucket else None),
                "over_quota": (
                    (st.req_bucket is not None
                     and st.req_bucket.level() < 1.0)
                    or (st.tok_bucket is not None
                        and st.tok_bucket.in_debt())),
            }
        return {
            "enabled": qos_enabled(),
            "default_policy": default.to_dict(),
            "top_n": tenant_top_n(),
            "overflow_label": OVERFLOW_TENANT,
            "tenants": tenants,
        }


# ---------------------------------------------------------- fair queue
class FairQueue:
    """Deficit-weighted round-robin queue over per-tenant FIFOs — the
    drop-in replacement for the serving queues' ``queue.Queue`` subset
    (``put_nowait`` / ``get(timeout)`` / ``get_nowait`` / ``qsize`` /
    ``maxsize``, stdlib ``queue.Full``/``queue.Empty`` semantics).

    Pop order: the highest priority *tier* with queued work always pops
    first; within a tier, classic DRR — visiting a tenant grants
    ``quantum x weight`` deficit and its head request pops when its
    ``cost_fn`` fits the deficit (cost = examples for inference, 1 slot
    for generation), so a backlogged heavy tenant cannot starve a light
    one and long-run service converges to the weight ratio.

    :meth:`pick_victim` implements tenant-aware full-queue shedding:
    the victim is the most over-share tenant's NEWEST request (an
    under-share tenant is never chosen; a tenant past its rate quota is
    preferred over one merely over its queue share). ``None`` means the
    *arriving* tenant is itself the most over-share — the caller sheds
    the arrival instead."""

    QUANTUM = 1.0

    def __init__(self, maxsize: int, tenants: "TenantRegistry",
                 cost_fn=None):
        self.maxsize = max(1, int(maxsize))
        self._tenants = tenants
        self._cost = cost_fn or (lambda req: 1.0)
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._queues: Dict[str, deque] = {}
        self._order: List[str] = []        # arrival order of active tenants
        self._deficit: Dict[str, float] = {}
        self._tcost: Dict[str, float] = {}  # running queued-cost totals
        # DRR visit state: the tenant currently being served and whether
        # it already received this visit's quantum (a tenant keeps
        # popping while its deficit lasts — that is where the weight
        # ratio comes from; granting per pop would collapse to 1:1)
        self._cur: Optional[str] = None
        self._cur_granted = False
        self._size = 0
        # (priority, weight) views cached against the registry's config
        # version: the pop hot path would otherwise take the registry
        # lock O(active tenants) times per pop
        self._pv_cache: Dict[str, tuple] = {}
        self._pv_version = -1

    def _pview(self, tenant: str) -> tuple:
        v = self._tenants.version
        if v != self._pv_version:
            self._pv_cache.clear()
            self._pv_version = v
        view = self._pv_cache.get(tenant)
        if view is None:
            pol = self._tenants.policy(tenant)
            view = self._pv_cache[tenant] = (pol.priority, pol.weight)
        return view

    def qsize(self) -> int:
        with self._lock:
            return self._size

    def tenant_sizes(self) -> Dict[str, int]:
        with self._lock:
            return {t: len(q) for t, q in self._queues.items() if q}

    def _tenant_of(self, req) -> str:
        return getattr(req, "tenant", None) or DEFAULT_TENANT

    def _rcost(self, req) -> float:
        return max(1e-9, float(self._cost(req)))

    def _remove_cost(self, t: str, cost: float):
        """Bookkeeping after removing one request of ``t``: running
        cost totals stay consistent, and a tenant whose queue emptied
        is dropped from EVERY per-tenant dict (queues, deficit, order,
        cost, policy view) — an id-spraying caller must not grow the
        queue's internals without bound either."""
        left = self._tcost.get(t, 0.0) - cost
        self._tcost[t] = left
        self._size -= 1
        if not self._queues.get(t):
            self._queues.pop(t, None)
            self._deficit.pop(t, None)
            self._tcost.pop(t, None)
            self._pv_cache.pop(t, None)
            if t in self._order:
                self._order.remove(t)
            if self._cur == t:
                self._cur = None

    def put_nowait(self, req):
        with self._not_empty:
            if self._size >= self.maxsize:
                raise _queue.Full
            t = self._tenant_of(req)
            q = self._queues.get(t)
            if q is None:
                q = self._queues[t] = deque()
            if not q:
                if t not in self._order:
                    self._order.append(t)
                self._deficit.setdefault(t, 0.0)
            q.append(req)
            self._tcost[t] = self._tcost.get(t, 0.0) + self._rcost(req)
            self._size += 1
            self._not_empty.notify()

    # ---------------------------------------------------------- pops
    def _pop_locked(self):
        """One DRR pop (caller holds the lock; queue known non-empty).
        Highest-priority tier first; within it, the visit pointer STAYS
        on a tenant while its deficit covers the next head's cost (one
        quantum x weight granted per visit, not per pop — that is where
        the weight ratio comes from). Moving past every tenant grants
        each another quantum, so a pop happens in bounded cycles."""
        active = [t for t in self._order if self._queues.get(t)]
        if not active:
            return None
        top = max(self._pview(t)[0] for t in active)
        tier = [t for t in active
                if self._pview(t)[0] == top]
        if self._cur not in tier:
            self._cur = None
            self._cur_granted = False
        idx = tier.index(self._cur) if self._cur is not None else 0
        scanned = 0
        while True:
            t = tier[idx % len(tier)]
            if t != self._cur:
                self._cur = t
                self._cur_granted = False
            if not self._cur_granted:
                self._deficit[t] = self._deficit.get(t, 0.0) \
                    + self.QUANTUM * self._pview(t)[1]
                self._cur_granted = True
            q = self._queues[t]
            cost = max(1e-9, float(self._cost(q[0])))
            if self._deficit[t] >= cost:
                req = q.popleft()
                if q:
                    self._deficit[t] -= cost
                else:
                    # DRR: an emptied tenant forfeits its deficit
                    # (saved-up credit must not burst later)
                    self._deficit[t] = 0.0
                self._remove_cost(t, cost)
                return req
            # can't afford the head: this visit is over — ending it
            # matters even when the tenant re-arrives immediately (a
            # single-tenant queue whose head costs more than one
            # quantum x weight must keep accruing on each new visit,
            # or this scan would spin forever)
            self._cur = None
            idx += 1
            scanned += 1
            if scanned >= len(tier):
                # a full wrap popped nothing: bulk-grant the minimum
                # number of further quanta that lets SOME tenant afford
                # its head — O(tenants), instead of spinning one
                # quantum per wrap under the lock when a head's cost is
                # many times quantum x weight (e.g. a 512-example
                # request from a low-weight tenant)
                scanned = 0
                need = None
                for t2 in tier:
                    c2 = max(1e-9, float(self._cost(self._queues[t2][0])))
                    w2 = max(1e-9, self.QUANTUM * self._pview(t2)[1])
                    k2 = (c2 - self._deficit.get(t2, 0.0)) / w2
                    if need is None or k2 < need:
                        need = k2
                grants = max(0, int(need))
                if grants:
                    for t2 in tier:
                        self._deficit[t2] = self._deficit.get(t2, 0.0) \
                            + grants * self.QUANTUM * self._pview(t2)[1]

    def get_nowait(self):
        with self._not_empty:
            if self._size == 0:
                raise _queue.Empty
            return self._pop_locked()

    def get(self, timeout: Optional[float] = None):
        with self._not_empty:
            if timeout is None:
                while self._size == 0:
                    self._not_empty.wait()
            else:
                end = time.monotonic() + max(0.0, timeout)
                while self._size == 0:
                    rem = end - time.monotonic()
                    if rem <= 0:
                        raise _queue.Empty
                    self._not_empty.wait(timeout=rem)
            return self._pop_locked()

    def peek_priority(self) -> Optional[int]:
        """Highest priority tier with queued work (None when empty) —
        the generation pipeline's preemption trigger."""
        with self._lock:
            active = [t for t in self._order if self._queues.get(t)]
            if not active:
                return None
            return max(self._pview(t)[0] for t in active)

    # ------------------------------------------------------- shedding
    def pick_victim(self, arriving_req):
        """Remove and return the queued request to shed when the queue
        is full and ``arriving_req`` wants in (see class doc). The
        arriving request is weighed as if queued, so a flooding arrival
        correctly identifies ITSELF as the victim (→ ``None``)."""
        arr_t = self._tenant_of(arriving_req)
        arr_cost = self._rcost(arriving_req)
        with self._lock:
            ratios = self._ratios_locked(arr_t, arr_cost)
            # ONLY over-share tenants are eligible victims — the quota
            # state is a tie-break AMONG them, never the primary key (a
            # quota-limited but under-share tenant must not mask the
            # actual flooder and get the innocent arrival shed)
            over_share = [t for t in ratios if ratios[t] > 1.0]
            if not over_share:
                return None
            victim_t = max(sorted(over_share), key=lambda t: (
                1 if self._tenants.over_quota(t) else 0, ratios[t]))
            if victim_t == arr_t:
                # the arrival's own tenant is the chosen victim: shed
                # the arrival (the caller's decision how)
                return None
            q = self._queues[victim_t]
            req = q.pop()                  # newest of the over-share flow
            self._remove_cost(victim_t, self._rcost(req))
            return req

    def _ratios_locked(self, arr_t: str,
                       arr_cost: float) -> Dict[str, float]:
        """Per-tenant queued-cost / weight-fair-share ratios, with the
        arrival weighed as if queued — from the RUNNING totals, so a
        full-queue arrival storm pays O(tenants), never O(queued
        requests)."""
        costs = {t: c for t, c in self._tcost.items()
                 if self._queues.get(t)}
        if arr_t:
            costs[arr_t] = costs.get(arr_t, 0.0) + arr_cost
        total = sum(costs.values())
        weights = {t: self._pview(t)[1] for t in costs}
        wsum = sum(weights.values()) or 1.0
        return {t: costs[t] / max(total * weights[t] / wsum, 1e-9)
                for t in costs}

    def pop_oldest_of(self, tenant) -> Optional[object]:
        """Remove and return ``tenant``'s OLDEST queued request (None
        when it has none) — the tenant-scoped generalization of the
        ``reject_oldest`` policy for when the arrival's own tenant is
        the shed victim: its stale head gives way to the fresh arrival
        instead of the arrival bouncing off its own backlog."""
        name = self.resolve_name(tenant)
        with self._lock:
            q = self._queues.get(name)
            if not q:
                return None
            req = q.popleft()
            self._remove_cost(name, self._rcost(req))
            return req

    def pop_global_oldest(self) -> Optional[object]:
        """Remove and return the most-backlogged tenant's oldest
        request (ties broken by the oldest head) — the last-resort
        ``reject_oldest`` fallback when nobody is strictly over-share
        and the arrival has no backlog of its own (e.g. a brand-new
        tenant arriving at a queue where every tenant sits exactly at
        its fair share): pre-QoS reject_oldest always admitted the
        fresh arrival, and the most underserved newcomer must not be
        the one request that bounces."""
        with self._lock:
            if self._size == 0:
                return None
            ratios = self._ratios_locked("", 0.0)

            def age(t):
                head = self._queues[t][0]
                return -float(getattr(head, "t_enqueue_us", 0.0) or 0.0)

            victim_t = max(sorted(ratios),
                           key=lambda t: (ratios[t], age(t)))
            q = self._queues[victim_t]
            req = q.popleft()
            self._remove_cost(victim_t, self._rcost(req))
            return req

    @staticmethod
    def resolve_name(tenant) -> str:
        return tenant if tenant else DEFAULT_TENANT


# ------------------------------------------------------ metric handles
def _tenant_requests(label: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_tenant_requests_total",
            "requests resolved per tenant (success, typed shed, or "
            "error; label bounded via the top-N tenant_label helper)",
            label_names=("tenant",)).labels(tenant=label)
    return _faults.cached_metric_handle(("tenant_req", label), make)


def _tenant_tokens(label: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_tenant_tokens_total",
            "usage tokens charged per tenant (emitted generation tokens "
            "+ scored classify examples)",
            label_names=("tenant",)).labels(tenant=label)
    return _faults.cached_metric_handle(("tenant_tok", label), make)


def _tenant_shed(label: str, reason: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_tenant_shed_total",
            "requests shed per tenant, by reason (quota = admission "
            "refusal, queue_full/deadline/preempted = in-pipeline)",
            label_names=("tenant", "reason")).labels(
                tenant=label, reason=reason)
    return _faults.cached_metric_handle(("tenant_shed", label, reason),
                                        make)


def _tenant_cost(label: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_tenant_cost_flops_total",
            "accounted device work per tenant: the cost model's FLOPs "
            "for each executed bucket / prefill / decode-step share",
            label_names=("tenant",)).labels(tenant=label)
    return _faults.cached_metric_handle(("tenant_cost", label), make)


def _tenant_latency(label: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().histogram(
            "dl4j_tenant_latency_seconds",
            "end-to-end request latency per tenant (the per-tenant SLO "
            "rule's read surface; worst tenant grades /health)",
            label_names=("tenant",)).labels(tenant=label)
    return _faults.cached_metric_handle(("tenant_lat", label), make)


# ------------------------------------------------------ process wiring
_global_tenants: Optional[TenantRegistry] = None
_tenants_lock = threading.Lock()


def global_tenants() -> TenantRegistry:
    """THE process-wide tenant registry (front door, pipelines, and
    /debug/tenants all consult it)."""
    global _global_tenants
    if _global_tenants is None:
        with _tenants_lock:
            if _global_tenants is None:
                _global_tenants = TenantRegistry()
    return _global_tenants


def reset_global_tenants() -> TenantRegistry:
    global _global_tenants
    with _tenants_lock:
        _global_tenants = TenantRegistry()
    return _global_tenants


def snapshot() -> dict:
    """``tenants.json`` / ``/debug/tenants`` payload — never constructs
    the registry structure beyond what traffic already created."""
    return global_tenants().snapshot()
