"""Resilience policies: retries with budgets, deadlines, circuit breaking.

The serving/training hot paths gain the standard production failure
policies (the TF-Serving/gRPC posture; Abadi et al. arXiv:1605.08695 §9):

- :class:`RetryPolicy` — exponential backoff + deterministic jitter, gated
  by a shared token-bucket :class:`RetryBudget` so a failing dependency
  cannot be amplified into a retry storm (each retry spends a token; only
  successes refill them).
- :class:`Deadline` — a monotonic expiry carried by work items. Requests
  into ``ParallelInference`` may carry one: the batcher sheds already-
  expired requests before padding/dispatch, the completer fails expired
  ones with :class:`DeadlineExceeded`, and expired work never occupies an
  in-flight slot.
- :class:`CircuitBreaker` — consecutive device-execution failures open the
  circuit; callers then fail fast with :class:`CircuitOpenError` instead
  of queueing behind a dead device. After ``reset_timeout_seconds`` a
  bounded number of half-open probes may pass; one probe success closes
  it. State is published as ``dl4j_circuit_state{op}`` (0 closed,
  1 half-open, 2 open) and :class:`CircuitOpenRule` folds it into
  ``/health`` + ``/alerts``.

Typed failure taxonomy (all ``RuntimeError`` subclasses so existing
callers that catch broadly keep working):

- :class:`TransientError`   — retryable by contract (``transient=True``)
- :class:`DeadlineExceeded` — the request outlived its deadline
- :class:`ShedError`        — rejected by admission control (queue full)
- :class:`CircuitOpenError` — failed fast on an open circuit
- :class:`ShutdownError`    — the serving instance was shut down (distinct
  from device errors, for callers and error-rate accounting alike)
- :class:`RestartBudgetExhausted` — ResilientTrainer ran out of restarts

Everything here no-ops/fails open under ``DL4J_TPU_RESILIENCE=0``.
"""
from __future__ import annotations

import os
import random
import threading
import time
import weakref
from typing import Callable, Optional

from deeplearning4j_tpu.observability.slo import (DEGRADED, FAILING, OK,
                                                  SLORule)
from deeplearning4j_tpu.resilience import faults as _faults


# ------------------------------------------------------------------- errors
class ResilienceError(RuntimeError):
    """Base of the typed resilience outcomes."""


class TransientError(ResilienceError):
    """Marked retryable; :func:`is_transient` keys off ``transient``."""
    transient = True


class DeadlineExceeded(ResilienceError):
    pass


class ShedError(ResilienceError):
    pass


class CachePagesExhausted(ShedError):
    """The paged KV-cache pool ran out of free pages — a LOAD outcome
    (the pool admits by actual cached tokens, so a burst of long
    generations can outgrow it), shed typed at a decode step boundary
    or at admission. Retryable by the caller once resident pages drain;
    never an error-rate event (``ShedError`` subclass)."""


class CircuitOpenError(ResilienceError):
    pass


class ShutdownError(RuntimeError):
    """ParallelInference was shut down while the request was in flight —
    a lifecycle outcome, not a device error (callers can route it to
    another replica; error-rate SLOs must not page on it)."""


class RestartBudgetExhausted(ResilienceError):
    pass


#: lifecycle/admission outcomes — typed results a caller routes on, not
#: device errors. THE canonical tuple: ParallelInference and the serving
#: router both exclude exactly these from their error counters (and from
#: breaker failure accounting); a new typed outcome added here reaches
#: every accounting site at once.
TYPED_OUTCOMES = (ShedError, DeadlineExceeded, ShutdownError,
                  CircuitOpenError)


def is_transient(exc: BaseException) -> bool:
    """Retry-safe failures: anything carrying ``transient=True`` —
    :class:`TransientError` subclasses and transient
    :class:`~deeplearning4j_tpu.resilience.faults.InjectedFault`."""
    return bool(getattr(exc, "transient", False))


# ----------------------------------------------------------------- deadline
class Deadline:
    """An absolute monotonic expiry a work item carries across queues."""

    __slots__ = ("expires_at",)

    def __init__(self, expires_at: float):
        self.expires_at = float(expires_at)

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(time.monotonic() + max(0.0, float(seconds)))

    @classmethod
    def after_ms(cls, ms: float) -> "Deadline":
        return cls.after(ms / 1e3)

    def remaining(self) -> float:
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def __repr__(self):
        return f"Deadline(remaining={self.remaining():.3f}s)"


def default_deadline_ms() -> float:
    """``DL4J_TPU_DEADLINE_MS``: default serving deadline (0 = none).
    Read per call so tests can flip it."""
    try:
        return max(0.0, float(os.environ.get("DL4J_TPU_DEADLINE_MS", 0)))
    except (TypeError, ValueError):
        return 0.0


# -------------------------------------------------------------------- retry
class RetryBudget:
    """gRPC-style token bucket: a retry costs one token, a first-attempt
    success refills ``refill_per_success``. When the bucket is dry,
    failures surface immediately — a hard floor on retry amplification."""

    def __init__(self, max_tokens: float = 10.0,
                 refill_per_success: float = 0.1):
        self.max_tokens = float(max_tokens)
        self.refill_per_success = float(refill_per_success)
        self._tokens = self.max_tokens
        self._lock = threading.Lock()

    def allow_retry(self) -> bool:
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def on_success(self):
        with self._lock:
            self._tokens = min(self.max_tokens,
                               self._tokens + self.refill_per_success)

    def tokens(self) -> float:
        with self._lock:
            return self._tokens


class RetryPolicy:
    """Exponential backoff with deterministic jitter under a shared
    :class:`RetryBudget`. ``call(fn, op=...)`` runs ``fn``, retrying
    failures that satisfy ``retry_on`` (default: :func:`is_transient` —
    blind retry of non-transient device errors could re-execute work whose
    donated buffers are already gone)."""

    def __init__(self, max_retries: int = 3,
                 base_delay_seconds: float = 0.02,
                 max_delay_seconds: float = 1.0, jitter: float = 0.5,
                 budget: Optional[RetryBudget] = None, seed: int = 0,
                 retry_on: Callable[[BaseException], bool] = is_transient):
        self.max_retries = max(0, int(max_retries))
        self.base_delay_seconds = float(base_delay_seconds)
        self.max_delay_seconds = float(max_delay_seconds)
        self.jitter = float(jitter)
        self.budget = budget if budget is not None else RetryBudget()
        self.retry_on = retry_on
        self._rng = random.Random(seed)

    def call(self, fn: Callable, op: str = "op",
             deadline: Optional[Deadline] = None,
             retry_on: Optional[Callable[[BaseException], bool]] = None):
        pred = retry_on if retry_on is not None else self.retry_on
        attempt = 0
        while True:
            try:
                out = fn()
            except Exception as e:
                if (not _faults.resilience_enabled() or not pred(e)
                        or attempt >= self.max_retries
                        or not self.budget.allow_retry()):
                    raise
                delay = min(self.max_delay_seconds,
                            self.base_delay_seconds * (2 ** attempt))
                delay *= 1.0 + self.jitter * self._rng.random()
                if deadline is not None and delay >= deadline.remaining():
                    raise
                attempt += 1
                _retry_counter(op).inc()
                _faults.record_event("retry", op=op, attempt=attempt,
                                     error=type(e).__name__)
                time.sleep(delay)
                continue
            if attempt == 0:
                self.budget.on_success()
            return out


# ---------------------------------------------------------- circuit breaker
CLOSED, HALF_OPEN, OPEN = 0, 1, 2
_STATE_NAMES = {CLOSED: "closed", HALF_OPEN: "half_open", OPEN: "open"}

#: live breakers by id(breaker) for /debug/resilience + bundle snapshots.
#: WEAK values: a breaker abandoned without retire() (its owner dropped on
#: an error path) must not leak here forever, nor keep pinning the shared
#: {op} gauge at OPEN — a finalizer re-publishes the op when one is GC'd
_breakers: "weakref.WeakValueDictionary[int, CircuitBreaker]" = \
    weakref.WeakValueDictionary()
# RLock: a CircuitBreaker's weakref.finalize callback re-acquires this
# lock, and cyclic GC can fire that callback on a thread ALREADY inside a
# locked region (any allocation under the lock can trigger collection) —
# a plain Lock would self-deadlock there
_breakers_lock = threading.RLock()


def _republish_op(op: str):
    """Recompute one op's worst-of-live-breakers gauge value (runs from
    CircuitBreaker finalizers after a breaker is garbage-collected)."""
    try:
        with _breakers_lock:
            states = [b._state for b in list(_breakers.values())
                      if b.op == op]
        _circuit_gauge(op).set(max(states, default=CLOSED))
    except Exception:  # graftlint: disable=typed-errors — best-effort
        pass           # gauge publish; no request outcome flows here


class CircuitBreaker:
    """Consecutive-failure circuit breaker with timed half-open probes."""

    def __init__(self, op: str, failure_threshold: int = 8,
                 reset_timeout_seconds: float = 5.0,
                 half_open_probes: int = 1):
        self.op = op
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_seconds = float(reset_timeout_seconds)
        self.half_open_probes = max(1, int(half_open_probes))
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probes_left = 0
        self._half_open_since = 0.0
        self._retired = False
        with _breakers_lock:
            _breakers[id(self)] = self
        weakref.finalize(self, _republish_op, op)
        self._publish()

    # state reads/writes under self._lock; the gauge publish happens
    # outside it (registry has its own locking)
    def allow(self) -> bool:
        """May a new unit of work proceed? Also drives the open→half-open
        transition once the reset timeout elapses."""
        if not _faults.resilience_enabled():
            return True
        now = time.monotonic()
        with self._lock:
            if self._retired:
                return True              # inert: the instance is gone
            if self._state == OPEN:
                if now - self._opened_at >= self.reset_timeout_seconds:
                    self._state = HALF_OPEN
                    self._probes_left = self.half_open_probes
                    self._half_open_since = now
                    self._transitioned(OPEN, HALF_OPEN)
                else:
                    return False
            if self._state == HALF_OPEN:
                if (self._probes_left <= 0
                        and now - self._half_open_since
                        >= self.reset_timeout_seconds):
                    # an admitted probe can die a typed death (shed,
                    # deadline) that reports neither success nor failure —
                    # replenish on the reset cadence so the breaker can
                    # never wedge half-open with zero probes forever
                    self._probes_left = self.half_open_probes
                    self._half_open_since = now
                if self._probes_left <= 0:
                    return False
                self._probes_left -= 1
                return True
            return True

    def record_success(self):
        with self._lock:
            if self._retired:
                return
            self._failures = 0
            if self._state != CLOSED:
                prev, self._state = self._state, CLOSED
                self._transitioned(prev, CLOSED)

    def record_failure(self):
        with self._lock:
            if self._retired:
                # a straggling serve thread outliving shutdown's join
                # timeout must not re-open a retired breaker and pin
                # /health failing with no live instance left to clear it
                return
            self._failures += 1
            if self._state == HALF_OPEN or (
                    self._state == CLOSED
                    and self._failures >= self.failure_threshold):
                prev, self._state = self._state, OPEN
                self._opened_at = time.monotonic()
                self._transitioned(prev, OPEN)

    def _transitioned(self, prev: int, new: int):
        # called with the lock held: keep it to bookkeeping + publish
        self._publish()
        _faults.record_event("circuit", op=self.op,
                             from_state=_STATE_NAMES[prev],
                             to_state=_STATE_NAMES[new],
                             consecutive_failures=self._failures)
        try:
            from deeplearning4j_tpu.observability.tracing import (
                current_context, now_us, record_span)
            record_span("circuit_transition", now_us(),
                        ctx=current_context(), op=self.op,
                        to_state=_STATE_NAMES[new])
        except Exception:  # graftlint: disable=typed-errors — tracing is
            pass           # best-effort; no request outcome flows here

    def _publish(self):
        # several instances may protect the same op (one breaker per
        # ParallelInference): the shared {op} series reports the WORST
        # live state, so a fresh/retiring CLOSED breaker can never mask
        # another instance's OPEN circuit on /health
        try:
            with _breakers_lock:
                peers = [b._state for b in list(_breakers.values())
                         if b.op == self.op]
            _circuit_gauge(self.op).set(max(peers, default=self._state))
        except Exception:  # graftlint: disable=typed-errors — best-effort
            pass           # gauge publish; no request outcome flows here

    def state(self) -> int:
        return self._state

    def state_name(self) -> str:
        return _STATE_NAMES[self._state]

    def retire(self):
        """Forget this breaker (instance shutdown): it goes permanently
        inert and the {op} gauge re-publishes the worst LIVE state, so a
        dead instance's open circuit cannot pin ``/health`` failing."""
        with _breakers_lock:
            _breakers.pop(id(self), None)
        with self._lock:
            self._retired = True
            self._failures = 0
            self._state = CLOSED
        self._publish()

    def snapshot(self) -> dict:
        with self._lock:
            return {"op": self.op, "state": _STATE_NAMES[self._state],
                    "consecutive_failures": self._failures,
                    "failure_threshold": self.failure_threshold,
                    "reset_timeout_seconds": self.reset_timeout_seconds}


def circuit_snapshot() -> list:
    with _breakers_lock:
        live = list(_breakers.values())
    return [b.snapshot() for b in live]


class CircuitOpenRule(SLORule):
    """``/health``/``/alerts`` view of the breakers: any OPEN circuit ⇒
    failing (callers are being failed fast — eject the replica), any
    HALF_OPEN ⇒ degraded (recovery probing in progress)."""

    def __init__(self, name: str = "circuit_breaker",
                 metric: str = "dl4j_circuit_state"):
        super().__init__(name, "circuit-breaker state per protected op "
                               "(0 closed / 1 half-open / 2 open)")
        self.metric = metric

    def _evaluate(self, registry) -> dict:
        inst = registry.get(self.metric)
        if inst is None:
            return {"status": OK, "detail": "no data"}
        open_ops, half_open_ops = [], []
        for lvals, child in inst.series():
            if child.value >= OPEN:
                open_ops.append(",".join(lvals))
            elif child.value >= HALF_OPEN:
                half_open_ops.append(",".join(lvals))
        if open_ops:
            return {"status": FAILING, "open": sorted(open_ops),
                    "half_open": sorted(half_open_ops)}
        if half_open_ops:
            return {"status": DEGRADED, "half_open": sorted(half_open_ops)}
        return {"status": OK}


# ------------------------------------------------------------ metric handles
def _retry_counter(op: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_resilience_retries_total",
            "retries performed by RetryPolicy, per protected operation",
            label_names=("op",)).labels(op=op)
    return _faults.cached_metric_handle(("retry", op), make)


def _circuit_gauge(op: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().gauge(
            "dl4j_circuit_state",
            "circuit-breaker state per protected op: 0 closed, "
            "1 half-open, 2 open", label_names=("op",)).labels(op=op)
    return _faults.cached_metric_handle(("circuit", op), make)


def _on_registry_reset():
    # the shared handle cache is cleared by faults' own reset hook; this
    # one re-publishes the live breakers so the fresh registry's
    # dl4j_circuit_state series stays truthful for /health and snapshots
    with _breakers_lock:
        live = list(_breakers.values())
    for b in live:
        b._publish()


try:
    from deeplearning4j_tpu.observability import on_registry_reset
    on_registry_reset(_on_registry_reset)
except Exception:            # pragma: no cover - observability always present
    pass
