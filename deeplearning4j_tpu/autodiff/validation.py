"""Gradient-check harness, analog of
``org.nd4j.autodiff.validation.GradCheckUtil`` / ``OpValidation`` and DL4J's
``org.deeplearning4j.gradientcheck.GradientCheckTests``.

Two modes:
- ``grad_check``  — central finite differences in float64 against
  ``jax.grad`` of a scalar-valued function over a pytree of inputs. This is
  the reference's exact methodology (central FD, double precision).
- ``check_vjp``   — stochastic VJP/JVP consistency via jax.test_util-style
  inner products, cheaper for large inputs.
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

try:
    _enable_x64 = jax.enable_x64
except AttributeError:      # pre-0.5 jax: experimental home, same semantics
    from jax.experimental import enable_x64 as _enable_x64


def grad_check(fn: Callable, params, epsilon: float = 1e-5, max_rel_error: float = 1e-3,
               min_abs_error: float = 1e-8, subset: int = None, seed: int = 0) -> bool:
    """Central finite-difference check of ``jax.grad(fn)`` at ``params``.

    fn: pytree -> scalar. params: pytree of float arrays. Computation runs in
    float64 on CPU (enable_x64 scope) — matching the reference's
    double-precision gradcheck requirement.
    """
    with _enable_x64(True):
        params64 = jax.tree.map(lambda p: jnp.asarray(np.asarray(p), jnp.float64), params)
        analytic = jax.grad(fn)(params64)

        flat_p, treedef = jax.tree.flatten(params64)
        flat_g = jax.tree.leaves(analytic)
        rng = np.random.default_rng(seed)

        for leaf_idx, (p, g) in enumerate(zip(flat_p, flat_g)):
            p_np = np.asarray(p)
            n = p_np.size
            idxs = range(n) if subset is None or n <= subset else rng.choice(n, subset, replace=False)
            for i in idxs:
                orig = p_np.flat[i]

                def eval_at(v):
                    p_mod = p_np.copy()
                    p_mod.flat[i] = v
                    leaves = list(flat_p)
                    leaves[leaf_idx] = jnp.asarray(p_mod)
                    return float(fn(jax.tree.unflatten(treedef, leaves)))

                num = (eval_at(orig + epsilon) - eval_at(orig - epsilon)) / (2 * epsilon)
                ana = float(np.asarray(g).flat[i])
                abs_err = abs(num - ana)
                denom = max(abs(num), abs(ana))
                rel_err = abs_err / denom if denom > 0 else 0.0
                if abs_err > min_abs_error and rel_err > max_rel_error:
                    raise AssertionError(
                        f"Gradient check FAILED at leaf {leaf_idx} flat-index {i}: "
                        f"numerical={num:.8g} analytic={ana:.8g} relErr={rel_err:.3g}")
    return True


def check_vjp(fn: Callable, *primals, atol: float = 1e-4, rtol: float = 1e-4, eps: float = 1e-4) -> bool:
    """Cheap directional check: FD directional derivative vs JVP, plus
    VJP/JVP inner-product consistency <J v, u> == <v, J^T u>."""
    with _enable_x64(True):
        primals64 = jax.tree.map(lambda p: jnp.asarray(np.asarray(p), jnp.float64), primals)
        rng = np.random.default_rng(0)
        tangents = jax.tree.map(lambda p: jnp.asarray(rng.normal(size=p.shape)), primals64)
        y, jvp_out = jax.jvp(fn, primals64, tangents)
        cotangent = jax.tree.map(lambda o: jnp.asarray(rng.normal(size=o.shape)), y)
        _, vjp_fn = jax.vjp(fn, *primals64)
        vjp_out = vjp_fn(cotangent)

        # inner-product identity
        lhs = sum(float(jnp.vdot(a, b)) for a, b in zip(jax.tree.leaves(jvp_out), jax.tree.leaves(cotangent)))
        rhs = sum(float(jnp.vdot(a, b)) for a, b in zip(jax.tree.leaves(vjp_out), jax.tree.leaves(tangents)))
        np.testing.assert_allclose(lhs, rhs, atol=atol, rtol=rtol)

        # FD directional derivative
        def shift(t):
            return jax.tree.map(lambda p, d: p + t * d, list(primals64), list(tangents))
        y_plus = fn(*shift(eps))
        y_minus = fn(*shift(-eps))
        fd = jax.tree.map(lambda a, b: (a - b) / (2 * eps), y_plus, y_minus)
        for f, j in zip(jax.tree.leaves(fd), jax.tree.leaves(jvp_out)):
            np.testing.assert_allclose(np.asarray(f), np.asarray(j), atol=1e-3, rtol=1e-3)
    return True
