"""SameDiff FlatBuffers artifact compatibility (ref: ``SameDiff#asFlatBuffers``
/ ``SameDiff#save`` and ``libnd4j/include/graph/scheme/{graph,node,variable,
array,properties,utils}.fbs`` — SURVEY N6/J7).

The reference persists SameDiff graphs as a FlatBuffers ``FlatGraph`` in the
``org.nd4j.graph`` namespace. This module writes and reads that binary
layout using the flatbuffers runtime directly (no generated classes), with
the table field slots reconstructed from the upstream schema:

- ``FlatGraph``    : id, variables:[FlatVariable], nodes:[FlatNode],
                     outputs:[IntPair], configuration, placeholders:[string],
                     lossVariables:[string], trainingConfig:string,
                     updaterState:[UpdaterState]
- ``FlatVariable`` : id:IntPair, name, dtype, shape:[long],
                     ndarray:FlatArray, device, variabletype,
                     controlDeps/controlDepForOp/controlDepsForVar:[string]
- ``FlatNode``     : id, name, opType, opNum, properties:[FlatProperties],
                     input:[int], inputPaired:[IntPair], output:[int],
                     extraParams:[double], extraInteger:[long],
                     extraBools:[bool], dimensions:[int], device, scope_id,
                     scope_name, outputNames:[string], opName:string,
                     outputTypes:[DType], scalar:FlatArray, controlDeps,
                     varControlDeps, controlDepFor, extraTypes,
                     extraStrings:[string]
- ``FlatArray``    : shape:[long], buffer:[byte], dtype, byteOrder —
                     ``shape`` holds the full nd4j shapeInfo descriptor
                     ``[rank, dims…, strides…, extras, ews, order]``
                     (ref: BaseNDArray#toFlatArray writes
                     shapeInfoDataBuffer); the reader also accepts bare
                     dims for pre-r5 self-written artifacts
- ``UpdaterState``  : paramName, updaterStateKeys:[string],
                     updaterStateValues:[FlatArray] — written by
                     ``save(…, save_updater_state=True)`` so Adam
                     moments survive a ``.fb`` resume
- ``FlatProperties``: name, i:[int], l:[long], d:[double], a:[FlatArray],
                     b:[bool], s:[string], shape:[int]
- ``IntPair``      : first:int, second:int

Ops are written as CUSTOM nodes keyed by ``opName`` with their attributes in
``properties`` (the reference's convention for DynamicCustomOp arguments);
an extra ``__attr_meta__`` property records the exact Python attr types so
a round-trip reconstructs attrs losslessly (a reference reader simply sees
one more named property). The reference's ``trainingConfig`` field is a
Jackson JSON string; ours is our TrainingConfig JSON — same transport.

Caveat (same stance as ``modelimport/dl4j_zip.py``): the schema was
reconstructed from the upstream .fbs layout in a zero-egress build with an
empty reference mount, so slot numbers are documented here and isolated in
the ``_FG``/``_FV``/``_FN``/``_FA``/``_FP`` slot maps for easy adjustment
against a real artifact. Control-flow subgraphs serialize as SCOPED node
regions (``scope_name = …__sub__/<op>/<key>/`` — the reference's
LOGIC-scope shape) with the composite op recording its branch outputs in
a ``__cf_subgraphs__`` property; only lambda ops refuse.
"""
from __future__ import annotations

import json
from typing import Dict, List, Optional

import flatbuffers
import numpy as np
from flatbuffers import number_types as NT

# ---------------------------------------------------------------- enums

# org.nd4j.graph.DType
_DTYPE_TO_NP = {1: np.dtype(np.bool_), 3: np.dtype(np.float16),
                5: np.dtype(np.float32), 6: np.dtype(np.float64),
                7: np.dtype(np.int8), 8: np.dtype(np.int16),
                9: np.dtype(np.int32), 10: np.dtype(np.int64),
                11: np.dtype(np.uint8), 12: np.dtype(np.uint16),
                13: np.dtype(np.uint32), 14: np.dtype(np.uint64)}
_NP_TO_DTYPE = {v: k for k, v in _DTYPE_TO_NP.items()}
try:  # bfloat16 = 17 when ml_dtypes is present (it is, via jax)
    import ml_dtypes

    _DTYPE_TO_NP[17] = np.dtype(ml_dtypes.bfloat16)
    _NP_TO_DTYPE[np.dtype(ml_dtypes.bfloat16)] = 17
except Exception:  # pragma: no cover
    pass

# org.nd4j.graph.VarType
_VARTYPE_TO_OURS = {0: "VARIABLE", 1: "CONSTANT", 2: "ARRAY",
                    3: "PLACEHOLDER"}
_OURS_TO_VARTYPE = {v: k for k, v in _VARTYPE_TO_OURS.items()}

# org.nd4j.graph.OpType: TRANSFORM_FLOAT..RANDOM enumerate 0..20, so
# CUSTOM = 21 (ADVICE r4: 22 would be GRAPH). The reader below keys on
# opName and does not validate this constant.
_OP_TYPE_CUSTOM = 21
_BYTE_ORDER_LE = 0            # org.nd4j.graph.ByteOrder.LE

# field slot numbers (declaration order in the .fbs — voffset = 4 + 2*slot)
_FA = {"shape": 0, "buffer": 1, "dtype": 2, "byteOrder": 3}
_FV = {"id": 0, "name": 1, "dtype": 2, "shape": 3, "ndarray": 4,
       "device": 5, "variabletype": 6}
_FP = {"name": 0, "i": 1, "l": 2, "d": 3, "a": 4, "b": 5, "s": 6,
       "shape": 7}
_FN = {"id": 0, "name": 1, "opType": 2, "opNum": 3, "properties": 4,
       "input": 5, "inputPaired": 6, "output": 7, "extraParams": 8,
       "extraInteger": 9, "extraBools": 10, "dimensions": 11, "device": 12,
       "scope_id": 13, "scope_name": 14, "outputNames": 15, "opName": 16,
       "outputTypes": 17, "scalar": 18}
_FG = {"id": 0, "variables": 1, "nodes": 2, "outputs": 3,
       "configuration": 4, "placeholders": 5, "lossVariables": 6,
       "trainingConfig": 7, "updaterState": 8}
# org.nd4j.graph.UpdaterState: per-parameter named updater moments
# (ref: graph.fbs ``table UpdaterState { paramName; updaterStateKeys;
# updaterStateValues }`` — SameDiff#save persists Adam M/V through it)
_US = {"paramName": 0, "updaterStateKeys": 1, "updaterStateValues": 2}

_ATTR_META = "__attr_meta__"

# Legacy enum-op support (VERDICT r4 Missing #7): reference artifacts can
# carry nodes with opType≠CUSTOM identified by (opType, opNum) enum pairs
# instead of an opName string. The mapping lives in the reference's
# legacy_ops.h enum tables, which cannot be verified in this zero-egress
# build — so the table ships EMPTY and loud, with a registration hook to
# fill verified entries against a real artifact.
_LEGACY_OPS: Dict[tuple, tuple] = {}


def register_legacy_op(op_type: int, op_num: int, name: str,
                       attr_adapter=None):
    """Map a legacy (OpType enum, opNum) pair to a registry op name so
    non-CUSTOM FlatGraph nodes can load. Entries should be verified
    against a real reference artifact before registration.

    ``attr_adapter(payload) -> attrs`` translates the node's legacy
    argument encoding — ``payload`` is ``{"extra_params": [float],
    "extra_integer": [int], "extra_bools": [bool], "dimensions": [int]}``
    — into the registry op's named attrs (e.g. dimensions → axis).
    Without an adapter, a node CARRYING legacy arguments refuses loudly
    rather than silently running the op without them."""
    _LEGACY_OPS[(int(op_type), int(op_num))] = (name, attr_adapter)


# --------------------------------------------------------------- writing

def _write_int_pair(b, first: int, second: int):
    b.StartObject(2)
    b.PrependInt32Slot(0, int(first), 0)
    b.PrependInt32Slot(1, int(second), 0)
    return b.EndObject()


def _shape_info(shape) -> np.ndarray:
    """nd4j shapeInfo descriptor for a C-order dense array: ``[rank,
    dims…, elementStrides…, extras, ews, order]`` (len = 2·rank+4 — ref:
    ``BaseNDArray#toFlatArray`` writes shapeInfoDataBuffer, layout in
    ``libnd4j helpers/shape.h``). extras=0, ews=1, order='c'=99."""
    rank = len(shape)
    strides = []
    acc = 1
    for d in reversed(shape):
        strides.append(acc)
        acc *= int(d)
    strides.reverse()
    return np.asarray([rank, *shape, *strides, 0, 1, ord("c")],
                      dtype=np.int64)


def _write_flat_array(b, arr: np.ndarray):
    arr = np.asarray(arr)
    if arr.dtype not in _NP_TO_DTYPE:
        raise ValueError(f"dtype {arr.dtype} has no FlatBuffers DType code")
    buf_off = b.CreateByteVector(arr.tobytes(order="C"))
    shape_off = b.CreateNumpyVector(_shape_info(arr.shape))
    b.StartObject(4)
    b.PrependUOffsetTRelativeSlot(_FA["shape"], shape_off, 0)
    b.PrependUOffsetTRelativeSlot(_FA["buffer"], buf_off, 0)
    b.PrependInt8Slot(_FA["dtype"], _NP_TO_DTYPE[arr.dtype], 0)
    b.PrependInt8Slot(_FA["byteOrder"], _BYTE_ORDER_LE, 0)
    return b.EndObject()


def _offset_vector(b, offsets: List[int]) -> int:
    b.StartVector(4, len(offsets), 4)
    for off in reversed(offsets):
        b.PrependUOffsetTRelative(off)
    return b.EndVector()


def _string_vector(b, strings: List[str]) -> int:
    return _offset_vector(b, [b.CreateString(s) for s in strings])


def _attr_to_property(b, name: str, value) -> (int, dict):
    """One attr → (FlatProperties offset, meta entry for reconstruction)."""
    sname = b.CreateString(name)
    slots = {}
    meta: dict = {}
    v = value
    if isinstance(v, (bool, np.bool_)):
        meta["k"] = "bool"
        slots["b"] = ("bool", [bool(v)])
    elif isinstance(v, (int, np.integer)):
        meta["k"] = "int"
        slots["l"] = ("long", [int(v)])
    elif isinstance(v, (float, np.floating)):
        meta["k"] = "float"
        slots["d"] = ("double", [float(v)])
    elif isinstance(v, str):
        meta["k"] = "str"
        slots["s"] = ("string", [v])
    elif isinstance(v, np.ndarray) or type(v).__module__.startswith("jax"):
        meta["k"] = "ndarray"
        slots["a"] = ("array", [np.asarray(v)])
    elif isinstance(v, (list, tuple)):
        flat, dims = _flatten_nested(v)
        meta["k"] = "seq"
        meta["tuple"] = isinstance(v, tuple)
        meta["dims"] = dims
        if all(isinstance(e, (bool, np.bool_)) for e in flat) and flat:
            meta["et"] = "bool"
            slots["b"] = ("bool", [bool(e) for e in flat])
        elif all(isinstance(e, (int, np.integer)) for e in flat):
            meta["et"] = "int"
            slots["l"] = ("long", [int(e) for e in flat])
        elif all(isinstance(e, (int, float, np.integer, np.floating))
                 for e in flat):
            meta["et"] = "float"
            slots["d"] = ("double", [float(e) for e in flat])
        elif all(isinstance(e, str) for e in flat):
            meta["et"] = "str"
            slots["s"] = ("string", list(flat))
        else:
            meta = {"k": "json", "v": json.dumps(_jsonable(v))}
    else:
        # None, np.dtype, and other config-ish values ride the meta json
        meta = {"k": "json", "v": json.dumps(_jsonable(v))}

    offs = {}
    if "s" in slots:
        offs["s"] = _string_vector(b, slots["s"][1])
    if "a" in slots:
        offs["a"] = _offset_vector(
            b, [_write_flat_array(b, a) for a in slots["a"][1]])
    if "l" in slots:
        offs["l"] = b.CreateNumpyVector(
            np.asarray(slots["l"][1], dtype=np.int64))
    if "d" in slots:
        offs["d"] = b.CreateNumpyVector(
            np.asarray(slots["d"][1], dtype=np.float64))
    if "b" in slots:
        b.StartVector(1, len(slots["b"][1]), 1)
        for e in reversed(slots["b"][1]):
            b.PrependBool(bool(e))
        offs["b"] = b.EndVector()
    dims_off = None
    if meta.get("dims") and len(meta["dims"]) > 1:
        dims_off = b.CreateNumpyVector(
            np.asarray(meta["dims"], dtype=np.int32))

    b.StartObject(8)
    b.PrependUOffsetTRelativeSlot(_FP["name"], sname, 0)
    for key in ("l", "d", "a", "b", "s"):
        if key in offs:
            b.PrependUOffsetTRelativeSlot(_FP[key], offs[key], 0)
    if dims_off is not None:
        b.PrependUOffsetTRelativeSlot(_FP["shape"], dims_off, 0)
    return b.EndObject(), meta


def _flatten_nested(v):
    """Nested lists/tuples of scalars → (flat list, dims). Ragged nesting
    falls back to dims=[len] with json handling upstream."""
    if not isinstance(v, (list, tuple)):
        return [v], []
    if all(isinstance(e, (list, tuple)) for e in v) and v \
            and len({len(e) for e in v}) == 1:
        flat = [x for e in v for x in e]
        if not any(isinstance(x, (list, tuple)) for x in flat):
            return flat, [len(v), len(v[0])]
    return list(v), [len(v)]


def _jsonable(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.dtype):
        return {"__dtype__": v.name}
    if isinstance(v, type) and issubclass(v, np.generic):
        return {"__dtype__": np.dtype(v).name}
    if isinstance(v, np.ndarray) or type(v).__module__.startswith("jax"):
        # arrays nested inside lists/dicts take the json path (the
        # top-level ndarray path uses FlatArray) — ADVICE r4 #4
        a = np.asarray(v)
        return {"__nd__": a.tolist(), "__nd_dtype__": a.dtype.name}
    if isinstance(v, (list, tuple)):
        return [_jsonable(e) for e in v]
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    return v


def _unjsonable(v):
    if isinstance(v, dict) and "__dtype__" in v:
        return np.dtype(v["__dtype__"])
    if isinstance(v, dict) and "__nd__" in v:
        return np.asarray(v["__nd__"],
                          dtype=np.dtype(v.get("__nd_dtype__", "f4")))
    if isinstance(v, dict):
        return {k: _unjsonable(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_unjsonable(e) for e in v]
    return v


_SUB = "__sub__/"
_CF_KEY = "__cf_subgraphs__"


def _collect_graph(sd, prefix: str, vars_out: list, nodes_out: list):
    """Recursive flattening of a SameDiff (+ its control-flow subgraphs)
    into prefixed variable/node records. Subgraph entities live under
    ``<prefix>__sub__/<op>/<key>/`` and carry that path as the FlatNode
    scope_name — the reference's scoped-LOGIC-region shape; a composite
    op records its branch outputs in a ``__cf_subgraphs__`` attr so the
    reader can reattach them."""
    for name, v in sd._vars.items():
        if _SUB in name:
            raise ValueError(
                f"variable name {name!r} contains the reserved scope "
                f"marker {_SUB!r} and cannot be FlatGraph-serialized")
        vars_out.append((prefix + name, v, sd._values.get(name)))
    for op in sd._ops:
        if op.fn is not None:
            raise ValueError(f"lambda op {op.name!r} is not serializable")
        if _SUB in op.name:
            raise ValueError(
                f"op name {op.name!r} contains the reserved scope marker "
                f"{_SUB!r} and cannot be FlatGraph-serialized")
        attrs = dict(op.attrs)
        if op.subgraphs:
            cf = {}
            for k, sub in op.subgraphs.items():
                sub_prefix = f"{prefix}{_SUB}{op.name}/{k}/"
                _collect_graph(sub, sub_prefix, vars_out, nodes_out)
                cf[k] = {"outputs": list(sub._branch_outputs)}
            attrs[_CF_KEY] = json.dumps(cf)
        nodes_out.append((prefix + op.name, op.op_name,
                          [prefix + i for i in op.inputs],
                          [prefix + o for o in op.outputs],
                          attrs, prefix))


def to_flat_buffers(sd, include_updater_state: bool = False) -> bytes:
    """Serialize a SameDiff graph to the FlatGraph binary (ref:
    ``SameDiff#asFlatBuffers``). Control-flow subgraphs serialize as
    scoped node regions (see ``_collect_graph``); with
    ``include_updater_state`` the per-parameter optimizer moments ride
    the ``updaterState:[UpdaterState]`` vector (ref: ``SameDiff#save``)."""
    from deeplearning4j_tpu.autodiff.samediff import VariableType

    all_vars: list = []
    all_nodes: list = []
    _collect_graph(sd, "", all_vars, all_nodes)

    b = flatbuffers.Builder(1024 * 1024)

    # ---- id assignment: ops get 1..N; leaf vars continue after
    op_ids = {name: i + 1 for i, (name, *_r) in enumerate(all_nodes)}
    pair_of: Dict[str, tuple] = {}
    for name, _opn, _ins, outs, _attrs, _scope in all_nodes:
        for j, out in enumerate(outs):
            pair_of[out] = (op_ids[name], j)
    next_id = len(all_nodes) + 1
    for name, v, _val in all_vars:
        if name not in pair_of:
            pair_of[name] = (next_id, 0)
            next_id += 1

    # ---- variables
    var_offs = []
    for name, v, val in all_vars:
        name_off = b.CreateString(name)
        nd_off = None
        if v.var_type in (VariableType.VARIABLE, VariableType.CONSTANT) \
                and val is not None:
            nd_off = _write_flat_array(b, np.asarray(val))
        shape_off = None
        if v.shape is not None and all(s is not None for s in v.shape):
            shape_off = b.CreateNumpyVector(
                np.asarray(v.shape, dtype=np.int64))
        id_off = _write_int_pair(b, *pair_of[name])
        b.StartObject(10)
        b.PrependUOffsetTRelativeSlot(_FV["id"], id_off, 0)
        b.PrependUOffsetTRelativeSlot(_FV["name"], name_off, 0)
        dt = np.dtype(v.dtype) if v.dtype is not None else np.dtype("f4")
        b.PrependInt8Slot(_FV["dtype"], _NP_TO_DTYPE.get(dt, 5), 0)
        if shape_off is not None:
            b.PrependUOffsetTRelativeSlot(_FV["shape"], shape_off, 0)
        if nd_off is not None:
            b.PrependUOffsetTRelativeSlot(_FV["ndarray"], nd_off, 0)
        b.PrependInt8Slot(_FV["variabletype"],
                          _OURS_TO_VARTYPE[v.var_type.value], 0)
        var_offs.append(b.EndObject())
    variables_off = _offset_vector(b, var_offs)

    # ---- nodes
    var_by_name = {name: v for name, v, _val in all_vars}
    node_offs = []
    for name, op_name, inputs, outputs, attrs, scope in all_nodes:
        name_off = b.CreateString(name)
        opname_off = b.CreateString(op_name)
        scope_off = b.CreateString(scope) if scope else None
        prop_offs, metas = [], {}
        for an, av in attrs.items():
            off, meta = _attr_to_property(b, an, av)
            prop_offs.append(off)
            metas[an] = meta
        moff, _ = _attr_to_property(b, _ATTR_META, json.dumps(metas))
        prop_offs.append(moff)
        props_off = _offset_vector(b, prop_offs)
        pairs = [_write_int_pair(b, *pair_of[i]) for i in inputs]
        in_paired_off = _offset_vector(b, pairs)
        out_names_off = _string_vector(b, outputs)
        out_types = []
        for o in outputs:
            ov = var_by_name.get(o)
            dt = np.dtype(ov.dtype) if ov is not None and ov.dtype \
                is not None else np.dtype("f4")
            out_types.append(_NP_TO_DTYPE.get(dt, 5))
        b.StartVector(1, len(out_types), 1)
        for t in reversed(out_types):
            b.PrependInt8(t)
        out_types_off = b.EndVector()

        b.StartObject(19)
        b.PrependInt32Slot(_FN["id"], op_ids[name], 0)
        b.PrependUOffsetTRelativeSlot(_FN["name"], name_off, 0)
        b.PrependInt8Slot(_FN["opType"], _OP_TYPE_CUSTOM, 0)
        b.PrependUOffsetTRelativeSlot(_FN["properties"], props_off, 0)
        b.PrependUOffsetTRelativeSlot(_FN["inputPaired"], in_paired_off, 0)
        if scope_off is not None:
            b.PrependUOffsetTRelativeSlot(_FN["scope_name"], scope_off, 0)
        b.PrependUOffsetTRelativeSlot(_FN["outputNames"], out_names_off, 0)
        b.PrependUOffsetTRelativeSlot(_FN["opName"], opname_off, 0)
        b.PrependUOffsetTRelativeSlot(_FN["outputTypes"], out_types_off, 0)
        node_offs.append(b.EndObject())
    nodes_off = _offset_vector(b, node_offs)

    placeholders_off = _string_vector(
        b, [n for n, v in sd._vars.items()
            if v.var_type == VariableType.PLACEHOLDER])
    loss_off = _string_vector(b, list(sd._loss_variables))
    tc_off = None
    if sd.training_config is not None:
        tc_off = b.CreateString(json.dumps(
            _jsonable(sd.training_config.to_dict())))

    us_off = None
    if include_updater_state:
        state = sd._updater_state_by_param()
        if state:
            us_offs = []
            for pname in sorted(state):
                entries = state[pname]
                pn_off = b.CreateString(pname)
                keys = sorted(entries)
                keys_off = _string_vector(b, keys)
                vals_off = _offset_vector(
                    b, [_write_flat_array(b, entries[k]) for k in keys])
                b.StartObject(3)
                b.PrependUOffsetTRelativeSlot(_US["paramName"], pn_off, 0)
                b.PrependUOffsetTRelativeSlot(
                    _US["updaterStateKeys"], keys_off, 0)
                b.PrependUOffsetTRelativeSlot(
                    _US["updaterStateValues"], vals_off, 0)
                us_offs.append(b.EndObject())
            us_off = _offset_vector(b, us_offs)

    b.StartObject(9)
    b.PrependUOffsetTRelativeSlot(_FG["variables"], variables_off, 0)
    b.PrependUOffsetTRelativeSlot(_FG["nodes"], nodes_off, 0)
    b.PrependUOffsetTRelativeSlot(_FG["placeholders"], placeholders_off, 0)
    b.PrependUOffsetTRelativeSlot(_FG["lossVariables"], loss_off, 0)
    if tc_off is not None:
        b.PrependUOffsetTRelativeSlot(_FG["trainingConfig"], tc_off, 0)
    if us_off is not None:
        b.PrependUOffsetTRelativeSlot(_FG["updaterState"], us_off, 0)
    root = b.EndObject()
    b.Finish(root)
    return bytes(b.Output())


# --------------------------------------------------------------- reading

class _Tab:
    """Minimal table reader over the flatbuffers runtime."""

    def __init__(self, buf, pos):
        import flatbuffers.table

        self.t = flatbuffers.table.Table(buf, pos)

    def _o(self, slot):
        return self.t.Offset(4 + 2 * slot)

    def i8(self, slot, default=0):
        o = self._o(slot)
        return self.t.Get(NT.Int8Flags, o + self.t.Pos) if o else default

    def i32(self, slot, default=0):
        o = self._o(slot)
        return self.t.Get(NT.Int32Flags, o + self.t.Pos) if o else default

    def i64(self, slot, default=0):
        o = self._o(slot)
        return self.t.Get(NT.Int64Flags, o + self.t.Pos) if o else default

    def string(self, slot) -> Optional[str]:
        o = self._o(slot)
        return self.t.String(o + self.t.Pos).decode("utf-8") if o else None

    def table(self, slot) -> Optional["_Tab"]:
        o = self._o(slot)
        if not o:
            return None
        return _Tab(self.t.Bytes, self.t.Indirect(o + self.t.Pos))

    def has(self, slot) -> bool:
        """Field PRESENCE via the vtable — a present-but-empty vector (a
        scalar's shape) is distinct from an absent field."""
        return bool(self._o(slot))

    def vec_len(self, slot) -> int:
        o = self._o(slot)
        return self.t.VectorLen(o) if o else 0

    def scalar_vec(self, slot, np_dtype) -> np.ndarray:
        o = self._o(slot)
        if not o:
            return np.zeros((0,), np_dtype)
        n = self.t.VectorLen(o)
        start = self.t.Vector(o)
        itemsize = np.dtype(np_dtype).itemsize
        data = bytes(self.t.Bytes[start:start + n * itemsize])
        return np.frombuffer(data, dtype=np_dtype)

    def table_vec(self, slot) -> List["_Tab"]:
        o = self._o(slot)
        if not o:
            return []
        n = self.t.VectorLen(o)
        start = self.t.Vector(o)
        return [_Tab(self.t.Bytes, self.t.Indirect(start + j * 4))
                for j in range(n)]

    def string_vec(self, slot) -> List[str]:
        o = self._o(slot)
        if not o:
            return []
        n = self.t.VectorLen(o)
        start = self.t.Vector(o)
        return [self.t.String(start + j * 4).decode("utf-8")
                for j in range(n)]


def _decode_shape(vec: np.ndarray, n_elems: int) -> tuple:
    """FlatArray.shape → (dims, order). Reference artifacts store the
    full nd4j shapeInfo descriptor (``[rank, dims…, strides…, extras,
    ews, order]``, len = 2·rank+4); our pre-r5 artifacts stored bare
    dims (always C order). Detect by layout, disambiguating rare
    collisions via the buffer element count. The order char matters: an
    f-order reference array's buffer is laid out column-major."""
    vals = [int(x) for x in vec]
    n = len(vals)
    if n >= 4 and vals[0] >= 0 and n == 2 * vals[0] + 4:
        dims = tuple(vals[1:1 + vals[0]])
        si_elems = int(np.prod(dims)) if dims else 1
        bare_elems = int(np.prod(vals)) if vals else 1
        # both layouts possible only when n == 2*vals[0]+4 AND the bare
        # product matches the buffer — prefer whichever is consistent
        if si_elems == n_elems or bare_elems != n_elems:
            order = "F" if vals[-1] == ord("f") else "C"
            return dims, order
    return tuple(vals), "C"


def _read_flat_array(tab: _Tab) -> np.ndarray:
    shape = tab.scalar_vec(_FA["shape"], np.int64)
    code = tab.i8(_FA["dtype"])
    dt = _DTYPE_TO_NP.get(int(code))
    if dt is None:
        raise ValueError(f"FlatArray dtype code {code} unsupported")
    raw = tab.scalar_vec(_FA["buffer"], np.uint8)
    arr = np.frombuffer(bytes(raw.tobytes()), dtype=dt)
    dims, order = _decode_shape(shape, arr.size)
    return np.reshape(arr, dims, order=order)


def _property_value(tab: _Tab, meta: dict):
    kind = meta.get("k") if meta else None
    bools = tab.scalar_vec(_FP["b"], np.int8)
    longs = tab.scalar_vec(_FP["l"], np.int64)
    dbls = tab.scalar_vec(_FP["d"], np.float64)
    strs = tab.string_vec(_FP["s"])
    arrs = tab.table_vec(_FP["a"])
    if kind == "bool":
        return bool(bools[0])
    if kind == "int":
        return int(longs[0])
    if kind == "float":
        return float(dbls[0])
    if kind == "str":
        return strs[0]
    if kind == "ndarray":
        return _read_flat_array(arrs[0])
    if kind == "json":
        return _unjsonable(json.loads(meta["v"]))
    if kind == "seq":
        et = meta.get("et")
        if et == "bool":
            flat = [bool(x) for x in bools]
        elif et == "int":
            flat = [int(x) for x in longs]
        elif et == "float":
            flat = [float(x) for x in dbls]
        else:
            flat = list(strs)
        dims = meta.get("dims") or [len(flat)]
        if len(dims) == 2:
            flat = [flat[r * dims[1]:(r + 1) * dims[1]]
                    for r in range(dims[0])]
            if meta.get("tuple"):
                flat = tuple(tuple(r) for r in flat)
            return flat
        return tuple(flat) if meta.get("tuple") else flat
    # no meta (foreign artifact): best-effort by which vector is populated
    ints32 = tab.scalar_vec(_FP["i"], np.int32)
    for seq, conv in ((bools, lambda x: bool(x)), (longs, int),
                      (ints32, int), (dbls, float)):
        if len(seq):
            vals = [conv(x) for x in seq]
            return vals[0] if len(vals) == 1 else vals
    if strs:
        return strs[0] if len(strs) == 1 else strs
    if arrs:
        vals = [_read_flat_array(a) for a in arrs]
        return vals[0] if len(vals) == 1 else vals
    return None


def from_flat_buffers(data: bytes):
    """Parse a FlatGraph binary into a SameDiff (ref: ``SameDiff#fromFlatBuffers``)."""
    from deeplearning4j_tpu.autodiff.samediff import (OpNode, SameDiff,
                                                      SDVariable,
                                                      TrainingConfig,
                                                      VariableType)
    import jax.numpy as jnp

    buf = bytearray(data)
    root_pos = flatbuffers.encode.Get(NT.UOffsetTFlags.packer_type, buf, 0)
    g = _Tab(buf, root_pos)

    pair_to_name: Dict[tuple, str] = {}
    var_recs = []                      # (full_name, vtype, shape, dt, value)
    for vt in g.table_vec(_FG["variables"]):
        name = vt.string(_FV["name"])
        code = vt.i8(_FV["dtype"])
        dt = _DTYPE_TO_NP.get(int(code), np.dtype("f4"))
        shape = tuple(int(s) for s in vt.scalar_vec(_FV["shape"], np.int64)) \
            if vt.has(_FV["shape"]) else None   # () scalar != absent
        vtype = VariableType(_VARTYPE_TO_OURS.get(
            int(vt.i8(_FV["variabletype"])), "ARRAY"))
        nd = vt.table(_FV["ndarray"])
        val = _read_flat_array(nd) if nd is not None else None
        var_recs.append((name, vtype, shape, dt, val))
        idp = vt.table(_FV["id"])
        if idp is not None:
            pair_to_name[(idp.i32(0), idp.i32(1))] = name

    nodes = g.table_vec(_FG["nodes"])
    for nt in nodes:
        nid = nt.i32(_FN["id"])
        for j, out in enumerate(nt.string_vec(_FN["outputNames"])):
            pair_to_name.setdefault((nid, j), out)

    node_recs = []   # (full_name, op_name, inputs, outputs, codes, attrs,
                     #  scope)
    for nt in sorted(nodes, key=lambda t: t.i32(_FN["id"])):
        name = nt.string(_FN["name"]) or f"node_{nt.i32(_FN['id'])}"
        op_name = nt.string(_FN["opName"])
        legacy_attrs = None
        if not op_name:
            # legacy enum-op node: resolve via the (opType, opNum) table
            key = (int(nt.i8(_FN["opType"])), int(nt.i64(_FN["opNum"])))
            entry = _LEGACY_OPS.get(key)
            if not entry:
                raise ValueError(
                    f"FlatNode {name!r} has no opName and legacy enum pair "
                    f"(opType={key[0]}, opNum={key[1]}) is not registered — "
                    f"verify the mapping against the reference's "
                    f"legacy_ops.h and add it via "
                    f"flatgraph.register_legacy_op({key[0]}, {key[1]}, "
                    f"'<registry-op>')")
            op_name, adapter = entry
            payload = {
                "extra_params": [float(v) for v in
                                 nt.scalar_vec(_FN["extraParams"],
                                               np.float64)],
                "extra_integer": [int(v) for v in
                                  nt.scalar_vec(_FN["extraInteger"],
                                                np.int64)],
                "extra_bools": [bool(v) for v in
                                nt.scalar_vec(_FN["extraBools"], np.int8)],
                "dimensions": [int(v) for v in
                               nt.scalar_vec(_FN["dimensions"], np.int32)],
            }
            if any(payload.values()):
                if adapter is None:
                    raise ValueError(
                        f"legacy node {name!r} ({op_name}) carries "
                        f"arguments {payload} but its registration has no "
                        f"attr_adapter — running without them would be "
                        f"silently wrong; register with "
                        f"register_legacy_op(..., attr_adapter=fn)")
                legacy_attrs = dict(adapter(payload))
        props = nt.table_vec(_FN["properties"])
        raw = {p.string(_FP["name"]): p for p in props}
        metas = {}
        if _ATTR_META in raw:
            meta_meta = {"k": "str"}
            metas = json.loads(_property_value(raw.pop(_ATTR_META),
                                               meta_meta))
        attrs = {an: _property_value(p, metas.get(an))
                 for an, p in raw.items()}
        if legacy_attrs:
            attrs.update(legacy_attrs)
        inputs = []
        for pt in nt.table_vec(_FN["inputPaired"]):
            key = (pt.i32(0), pt.i32(1))
            if key not in pair_to_name:
                raise ValueError(f"node {name!r} references unknown "
                                 f"producer {key}")
            inputs.append(pair_to_name[key])
        scope = nt.string(_FN["scope_name"]) or ""
        if scope and not scope.endswith("/"):
            # a foreign artifact's free-form scope label (not our
            # __sub__/<op>/<key>/ convention): treat as top-level — the
            # old reader ignored scope_name entirely
            scope = ""
        node_recs.append((name, op_name, inputs,
                          nt.string_vec(_FN["outputNames"]),
                          nt.scalar_vec(_FN["outputTypes"], np.int8),
                          attrs, scope))

    # ---- group by scope path (one pass) and build bottom-up (deepest
    # first), so a composite op's subgraphs exist when its scope is built
    def _var_scope(name):
        i = name.rfind(_SUB)
        if i < 0:
            return ""
        rest = name[i + len(_SUB):]          # "<op>/<key>/<local>"
        parts = rest.split("/", 2)
        if len(parts) < 3:
            return ""                        # not our convention
        return name[:i] + _SUB + parts[0] + "/" + parts[1] + "/"

    vars_by_scope: Dict[str, list] = {}
    for rec in var_recs:
        vars_by_scope.setdefault(_var_scope(rec[0]), []).append(rec)
    nodes_by_scope: Dict[str, list] = {}
    for rec in node_recs:
        nodes_by_scope.setdefault(rec[-1], []).append(rec)
    scopes = sorted(set(vars_by_scope) | set(nodes_by_scope) | {""},
                    key=len, reverse=True)
    built: Dict[str, "SameDiff"] = {}
    for scope in scopes:
        sd = SameDiff()
        for name, vtype, shape, dt, val in vars_by_scope.get(scope, []):
            local = name[len(scope):]
            v = SDVariable(sd, local, vtype, shape, dt)
            sd._vars[local] = v
            if val is not None:
                sd._values[local] = jnp.asarray(val)
                if v.shape is None:
                    v.shape = val.shape
        for name, op_name, inputs, outputs, out_codes, attrs, _nscope \
                in nodes_by_scope.get(scope, []):
            local = name[len(scope):]
            subgraphs = None
            if _CF_KEY in attrs:
                cf = json.loads(attrs.pop(_CF_KEY))
                subgraphs = {}
                for k, meta in cf.items():
                    sub_path = f"{scope}{_SUB}{local}/{k}/"
                    sub = built.get(sub_path)
                    if sub is None:
                        raise ValueError(
                            f"composite op {name!r} references missing "
                            f"subgraph scope {sub_path!r}")
                    sub._branch_outputs = list(meta.get("outputs", []))
                    subgraphs[k] = sub
            l_inputs = [i[len(scope):] for i in inputs]
            l_outputs = [o[len(scope):] for o in outputs]
            node = OpNode(local, op_name, l_inputs, l_outputs, attrs,
                          subgraphs=subgraphs)
            sd._ops.append(node)
            for j, out in enumerate(l_outputs):
                if out not in sd._vars:
                    dt = _DTYPE_TO_NP.get(int(out_codes[j]),
                                          np.dtype("f4")) \
                        if j < len(out_codes) else np.dtype("f4")
                    sd._vars[out] = SDVariable(sd, out, VariableType.ARRAY,
                                               None, dt)
                sd._producer[out] = node
        sd._reseed_name_counters()
        built[scope] = sd

    sd = built[""]
    sd._loss_variables = g.string_vec(_FG["lossVariables"])
    tc = g.string(_FG["trainingConfig"])
    if tc:
        sd.training_config = TrainingConfig.from_dict(
            _unjsonable(json.loads(tc)))
    us_tabs = g.table_vec(_FG["updaterState"])
    if us_tabs:
        named: Dict[str, dict] = {}
        for ut in us_tabs:
            pname = ut.string(_US["paramName"]) or ""
            keys = ut.string_vec(_US["updaterStateKeys"])
            vals = [_read_flat_array(a)
                    for a in ut.table_vec(_US["updaterStateValues"])]
            named[pname] = dict(zip(keys, vals))
        sd._pending_opt_named = named
        # identity of the updater that produced the state: the artifact's
        # trainingConfig updater (guards the rehydrate against a
        # key-compatible but different updater)
        upd = getattr(sd.training_config, "updater", None)
        if upd is not None:
            sd._pending_opt_updater = type(upd).__name__
    return sd


def save_flatbuffers(sd, path: str):
    with open(path, "wb") as f:
        f.write(to_flat_buffers(sd))


def load_flatbuffers(path: str):
    with open(path, "rb") as f:
        return from_flat_buffers(f.read())
