"""Graph engine + validation (ref: org.nd4j.autodiff)."""
from deeplearning4j_tpu.autodiff import validation  # noqa: F401
from deeplearning4j_tpu.autodiff.samediff import (  # noqa: F401
    SameDiff, SDVariable, TrainingConfig, VariableType)
