"""Graph engine + validation (ref: org.nd4j.autodiff)."""
from deeplearning4j_tpu.autodiff import validation
