"""Emission-time graph peepholes (the GraphOptimizer analog).

Reference analog: ``org.nd4j.autodiff.samediff.optimize.GraphOptimizer``
with its ``Optimizer`` pass list (SURVEY J6) — the reference rewrites the
op DAG before execution (identity removal, constant folding, shape-op
dedup). TPU-first reinterpretation: XLA already does classical scalar
optimizations, so the passes here target what XLA **cannot** recover —
patterns whose *algorithm* blocks fusion. They run on a shallow copy of
the op list at ``SameDiff._emit`` time; the stored graph (``sd._ops``)
is never mutated, so save/load round-trips the artifact exactly as built.

The flagship pass rewrites the two-pass variance motif that every frozen
TF graph carries for LayerNorm/moments (``tf.nn.moments``):

    m  = Mean(x, axes, keepdims)
    sd = SquaredDifference(x, StopGradient(m))   # StopGradient -> Identity
    v  = Mean(sd, axes, keepdims)

The second Mean depends on the first, forcing two HBM passes over the
activation. The one-pass form ``E[x^2] - E[x]^2`` reads ``x`` twice
*independently*, so XLA fuses both reductions into one multi-output pass
(measured on the ResNet-50 layer twin of this motif: 12.80 -> 11.92
ms/step, benchmarks/resnet_profile.py).

Gradient equivalence is exact, not approximate: with ``c = sg(E[x])``,
``d/dx E[(x-c)^2] = 2(x-c)/N``, and ``d/dx (E[x^2] - (E[x])^2)
= 2x/N - 2*E[x]/N = 2(x-E[x])/N`` — identical (TF inserts the
StopGradient precisely because the mean's gradient term cancels
mathematically). The clamp to >= 0 restores the two-pass form's
non-negativity under f32 cancellation (ops/moments rationale).
"""
from __future__ import annotations

import os
from typing import List, Tuple

import jax.numpy as jnp

from deeplearning4j_tpu.ops import registry as op_registry
from deeplearning4j_tpu.ops.registry import register


@register("one_pass_variance")
def one_pass_variance(x, mean, axis=None, keepdims=False, keep_dims=None):
    """Variance given the already-computed mean over the same reduction.
    Emitted only by the peephole pass — the importer/builder surfaces never
    produce it directly. Accepts the ``keep_dims`` attr spelling because
    the rewritten Mean node's attrs are copied verbatim and ``reduce_mean``
    accepts both. Formula + clamp live in ops/moments (single home)."""
    from deeplearning4j_tpu.ops.moments import (
        one_pass_variance as _opv)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    kd = keepdims if keep_dims is None else keep_dims
    # cast back: the Mean node this replaces produced x.dtype (f32 inside
    # still defeats bf16 cancellation)
    return _opv(x, mean, ax, bool(kd)).astype(x.dtype)


def _canon(name: str) -> str:
    return op_registry.get(name).name if op_registry.has(name) else name


def _norm_axis(a):
    if isinstance(a, (list, tuple)):
        return tuple(int(x) for x in a)
    return a if a is None else (int(a),)


def _keepdims(attrs: dict) -> bool:
    # reduce_mean accepts both spellings; honor whichever is present
    return bool(attrs.get("keepdims", attrs.get("keep_dims", False)))


def _same_reduction(a1: dict, a2: dict) -> bool:
    return (_norm_axis(a1.get("axis")) == _norm_axis(a2.get("axis"))
            and _keepdims(a1) == _keepdims(a2))


def fuse_two_pass_moments(ops: List) -> Tuple[List, int]:
    """Return ``(new_ops, n_rewritten)``: every matched variance-Mean node
    replaced (as a copy — input list untouched) by a ``one_pass_variance``
    node reading the raw activation and the LIVE mean. The orphaned
    SquaredDifference (and StopGradient identity) are left in place;
    ``SameDiff._needed_ops`` prunes them when nothing else consumes them.
    """
    from deeplearning4j_tpu.autodiff.samediff import OpNode

    prod = {}
    for op in ops:
        for o in op.outputs:
            prod[o] = op

    def resolve(name: str, through_sg: bool = False) -> str:
        # ``through_sg`` unwraps a native stop_gradient — gradient-safe
        # ONLY on the mean side (the proven-equivalent transform keeps the
        # mean live); on the activation side it would change gradients.
        # Plain identity is gradient-transparent and safe everywhere
        # (tfimport maps StopGradient to Identity globally, a pre-existing
        # frozen-graph semantic).
        ok = ("identity", "stop_gradient") if through_sg else ("identity",)
        seen = set()
        while name in prod and name not in seen:
            seen.add(name)
            p = prod[name]
            if _canon(p.op_name) in ok and len(p.inputs) == 1:
                name = p.inputs[0]
                continue
            break
        return name

    out, n = [], 0
    for op in ops:
        new_op = op
        if (_canon(op.op_name) == "reduce_mean" and len(op.inputs) == 1
                and len(op.outputs) == 1):
            sq = prod.get(op.inputs[0])
            if (sq is not None and _canon(sq.op_name) == "squaredsubtract"
                    and len(sq.inputs) == 2):
                raw = list(sq.inputs)
                for xi, mi in ((0, 1), (1, 0)):
                    x_name = resolve(raw[xi])
                    m_name = resolve(raw[mi], through_sg=True)
                    m_op = prod.get(m_name)
                    if (m_op is not None
                            and _canon(m_op.op_name) == "reduce_mean"
                            and len(m_op.inputs) == 1
                            and len(m_op.outputs) == 1
                            and resolve(m_op.inputs[0]) == x_name
                            and _same_reduction(m_op.attrs, op.attrs)):
                        new_op = OpNode(op.name, "one_pass_variance",
                                        [x_name, m_op.outputs[0]],
                                        list(op.outputs), dict(op.attrs))
                        n += 1
                        break
        out.append(new_op)
    return out, n


def graph_opt_enabled() -> bool:
    """Live value of the ``DL4J_TPU_GRAPH_OPT`` kill switch. Callers that
    cache emitted/jitted functions MUST fold this into their cache key —
    otherwise flipping the flag mid-session silently serves programs built
    under the previous setting."""
    return os.environ.get("DL4J_TPU_GRAPH_OPT", "1") != "0"


def optimize_for_emission(ops: List) -> List:
    """All enabled peepholes, in order. Disable with
    ``DL4J_TPU_GRAPH_OPT=0`` (config/flags surface, SURVEY §5.6)."""
    if not graph_opt_enabled():
        return ops
    ops, _ = fuse_two_pass_moments(ops)
    return ops
