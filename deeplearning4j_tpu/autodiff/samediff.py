"""SameDiff-equivalent define-then-run graph engine, TPU-first.

Reference surface: ``org.nd4j.autodiff.samediff.SameDiff`` (~6k lines),
``SDVariable``, namespaced op factories (``SDBaseOps``, ``SDNN``, ``SDCNN``,
``SDRNN``, ``SDLoss``, ``SDMath``), ``TrainingConfig``, ``SameDiff#fit``,
``SameDiff#output``, ``SameDiff#save/load`` (SURVEY.md J6/J7, call stack
§3.3).

TPU-first redesign (the load-bearing difference): the reference executes its
graph **op-at-a-time in Java**, each op crossing JNI into libnd4j
(``AbstractSession#output`` → ``InferenceSession#doExec`` →
``NativeOpExecutioner``). Here the topological walk *emits* a single
jax-traceable function over the whole graph, which XLA compiles and fuses
once per (output-set, placeholder-shapes) signature — the graph interpreter
becomes an HLO emitter, per SURVEY §3.3's "north star". Backward graphs are
not hand-assembled from per-op ``doDiff`` rules; ``jax.grad`` of the emitted
program plays that role (``SameDiff#createGradFunction`` analog).

Serialization: the reference persists FlatBuffers (``SameDiff#asFlatBuffers``,
schema shared with libnd4j's C++ graph runtime). We persist the same logical
content — op graph + variable kinds + values + training config — as a zip of
``graph.json`` + ``values.npz`` (documented divergence: no C++ graph
executor exists to share a schema with; XLA is the executor).
"""
from __future__ import annotations

import enum
import io
import json
import zipfile
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.ops import registry as op_registry
import deeplearning4j_tpu.ops  # noqa: F401  (trigger op registrations)


class History(list):
    """Training history (ref: ``org.nd4j.autodiff.listeners.records.History``
    + ``LossCurve``). Subclasses list of per-iteration losses so existing
    ``losses[-1]`` style code keeps working."""

    def loss_curve(self):
        return list(self)

    lossCurve = loss_curve

    def final_loss(self) -> float:
        return float(self[-1]) if self else float("nan")

    finalTrainingLoss = final_loss


class VariableType(enum.Enum):
    """Mirror of ``org.nd4j.autodiff.samediff.VariableType``."""

    VARIABLE = "VARIABLE"        # trainable, persisted
    CONSTANT = "CONSTANT"        # non-trainable, persisted
    PLACEHOLDER = "PLACEHOLDER"  # fed at exec time
    ARRAY = "ARRAY"              # op output, computed


class SDVariable:
    """Symbolic graph variable (ref: ``org.nd4j.autodiff.samediff.SDVariable``).

    Holds no data for ARRAY type; VARIABLE/CONSTANT values live in the owning
    ``SameDiff``'s value store. Arithmetic operators create graph ops.
    """

    def __init__(self, sd: "SameDiff", name: str, var_type: VariableType,
                 shape: Optional[Tuple[int, ...]], dtype):
        self.sd = sd
        self.name = name
        self.var_type = var_type
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype

    # ---- graph-building arithmetic ------------------------------------
    def _bin(self, op: str, other, reverse=False):
        other = self.sd._lift(other)
        a, b = (other, self) if reverse else (self, other)
        return self.sd._op(op, a, b)

    def __add__(self, o): return self._bin("add", o)
    def __radd__(self, o): return self._bin("add", o, reverse=True)
    def __sub__(self, o): return self._bin("sub", o)
    def __rsub__(self, o): return self._bin("sub", o, reverse=True)
    def __mul__(self, o): return self._bin("mul", o)
    def __rmul__(self, o): return self._bin("mul", o, reverse=True)
    def __truediv__(self, o): return self._bin("div", o)
    def __rtruediv__(self, o): return self._bin("div", o, reverse=True)
    def __pow__(self, o): return self._bin("pow", o)
    def __neg__(self): return self.sd._op("neg", self)
    def __matmul__(self, o): return self.mmul(o)

    # comparison → boolean arrays (as in SDVariable#gt etc.)
    def gt(self, o): return self._bin("greater", o)
    def gte(self, o): return self._bin("greater_equal", o)
    def lt(self, o): return self._bin("less", o)
    def lte(self, o): return self._bin("less_equal", o)
    def eq(self, o): return self._bin("equals", o)
    def neq(self, o): return self._bin("not_equals", o)
    __gt__ = gt
    __ge__ = gte
    __lt__ = lt
    __le__ = lte
    # (__eq__ stays identity — variables live in dict keys)

    # common method-style ops (SDVariable convenience methods)
    def add(self, o): return self.__add__(o)
    def sub(self, o): return self.__sub__(o)
    def mul(self, o): return self.__mul__(o)
    def div(self, o): return self.__truediv__(o)
    def rdiv(self, o): return self.__rtruediv__(o)
    def mmul(self, o): return self.sd._op("matmul", self, self.sd._lift(o))
    def dot(self, o): return self.sd._op("tensordot", self, self.sd._lift(o), axes=1)
    def transpose(self, *perm):
        return self.sd._op("transpose", self, axes=list(perm) or None)
    def permute(self, *perm): return self.transpose(*perm)
    def reshape(self, *shape):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        return self.sd._op("reshape", self, shape=list(shape))
    def sum(self, *axis, keepdims=False):
        return self.sd._op("reduce_sum", self, axis=list(axis) or None, keepdims=keepdims)
    def mean(self, *axis, keepdims=False):
        return self.sd._op("reduce_mean", self, axis=list(axis) or None, keepdims=keepdims)
    def max(self, *axis, keepdims=False):
        return self.sd._op("reduce_max", self, axis=list(axis) or None, keepdims=keepdims)
    def min(self, *axis, keepdims=False):
        return self.sd._op("reduce_min", self, axis=list(axis) or None, keepdims=keepdims)
    def prod(self, *axis, keepdims=False):
        return self.sd._op("reduce_prod", self, axis=list(axis) or None, keepdims=keepdims)
    def std(self, *axis, keepdims=False):
        return self.sd._op("reduce_stdev", self, axis=list(axis) or None, keepdims=keepdims)
    def norm2(self, *axis, keepdims=False):
        return self.sd._op("reduce_norm2", self, axis=list(axis) or None, keepdims=keepdims)
    def argmax(self, axis=-1): return self.sd._op("argmax", self, axis=axis)
    def argmin(self, axis=-1): return self.sd._op("argmin", self, axis=axis)
    def squeeze(self, axis=None): return self.sd._op("squeeze", self, axis=axis)
    def cast(self, dtype): return self.sd._op("cast", self, dtype=np.dtype(dtype).name)
    def rank(self): return len(self.shape) if self.shape is not None else None
    def get(self, *slices): return self.__getitem__(slices if len(slices) > 1 else slices[0])

    def __getitem__(self, idx):
        if not isinstance(idx, tuple):
            idx = (idx,)
        begins, ends, strides, squeeze_axes = [], [], [], []
        for ax, s in enumerate(idx):
            if isinstance(s, int):
                if s == -1:
                    # end=0 would make an empty slice; 2**31-1 = "to the end"
                    begins.append(s); ends.append(2**31 - 1)
                else:
                    begins.append(s); ends.append(s + 1)
                strides.append(1)
                squeeze_axes.append(ax)
            elif isinstance(s, slice):
                dim = self.shape[ax] if self.shape is not None else None
                begins.append(s.start if s.start is not None else 0)
                ends.append(s.stop if s.stop is not None else (dim if dim is not None else 2**31 - 1))
                strides.append(s.step if s.step is not None else 1)
            else:
                raise TypeError(f"Unsupported index {s!r}")
        out = self.sd._op("strided_slice", self, begin=begins, end=ends,
                          strides=strides)
        if squeeze_axes:
            out = self.sd._op("squeeze", out, axis=squeeze_axes)
        return out

    # ---- graph metadata ------------------------------------------------
    def rename(self, new_name: str) -> "SDVariable":
        self.sd._rename(self.name, new_name)
        return self

    def convert_to_constant(self):
        self.var_type = VariableType.CONSTANT
        return self

    def convert_to_variable(self):
        self.var_type = VariableType.VARIABLE
        return self

    def eval(self, placeholders: Optional[Dict[str, Any]] = None):
        """Compute this variable's value (``SDVariable#eval``)."""
        return self.sd.output(placeholders or {}, [self.name])[self.name]

    def get_arr(self):
        if self.var_type in (VariableType.VARIABLE, VariableType.CONSTANT):
            return self.sd._values[self.name]
        return self.eval()

    def set_arr(self, value):
        value = jnp.asarray(value)
        if self.var_type not in (VariableType.VARIABLE, VariableType.CONSTANT):
            raise ValueError(f"{self.name} is {self.var_type}, has no stored array")
        self.sd._values[self.name] = value
        self.shape = tuple(value.shape)
        self.sd._invalidate_cache()
        return self

    def __repr__(self):
        return (f"SDVariable(name={self.name!r}, type={self.var_type.value}, "
                f"shape={self.shape})")


def _counted_trip(c_sd, b_sd, loop_vars):
    """Detect the counted-while pattern and return its static trip count,
    or None. Pattern (what TF emits for ``i < T`` loops):

    - cond output = Cmp(arg_k, K) (or Cmp(K, arg_k)) through Identity/
      Squeeze wrappers, K a scalar constant in the cond graph;
    - body output k = arg_k ± step, step a scalar constant;
    - the k-th loop var's INITIAL value is a scalar constant.
    """
    def _resolve(sd, name, depth=8):
        """Follow Identity/Squeeze chains to the producing op or leaf."""
        for _ in range(depth):
            prod = sd._producer.get(name)
            if prod is None:
                return name, None
            if prod.op_name in ("Identity", "identity", "Squeeze", "squeeze"):
                name = prod.inputs[0]
                continue
            return name, prod
        return name, None

    def _scalar_const(sd, name):
        name, prod = _resolve(sd, name)
        v = sd._values.get(name)
        if v is not None and np.asarray(v).size == 1:
            return float(np.asarray(v).reshape(()))
        return None

    def _arg_index(sd, name):
        name, prod = _resolve(sd, name)
        if prod is None and name.startswith("arg"):
            try:
                return int(name[3:].split(":")[0])
            except ValueError:
                return None
        return None

    try:
        _, cmp_op = _resolve(c_sd, c_sd._branch_outputs[0])
        if cmp_op is None:
            return None
        cmps = {"Less": "<", "less": "<", "LessEqual": "<=",
                "less_equal": "<=", "Greater": ">", "greater": ">",
                "GreaterEqual": ">=", "greater_equal": ">="}
        sym = cmps.get(cmp_op.op_name)
        if sym is None or len(cmp_op.inputs) != 2:
            return None
        a_idx = _arg_index(c_sd, cmp_op.inputs[0])
        b_idx = _arg_index(c_sd, cmp_op.inputs[1])
        if a_idx is not None and b_idx is None:
            k, bound = a_idx, _scalar_const(c_sd, cmp_op.inputs[1])
            flipped = False
        elif b_idx is not None and a_idx is None:
            k, bound = b_idx, _scalar_const(c_sd, cmp_op.inputs[0])
            flipped = True
        else:
            return None
        if bound is None:
            return None
        # body update of the counter: arg_k ± const step
        _, upd = _resolve(b_sd, b_sd._branch_outputs[k])
        if upd is None or upd.op_name not in ("Add", "add", "AddV2",
                                              "Sub", "sub"):
            return None
        u_args = [_arg_index(b_sd, i) for i in upd.inputs]
        if u_args[0] == k:
            step = _scalar_const(b_sd, upd.inputs[1])
        elif len(u_args) > 1 and u_args[1] == k \
                and upd.op_name in ("Add", "add", "AddV2"):
            step = _scalar_const(b_sd, upd.inputs[0])
        else:
            return None
        if step is None or step == 0:
            return None
        if upd.op_name in ("Sub", "sub"):
            step = -step
        init_v = loop_vars[k]
        raw = init_v.sd._values.get(init_v.name)
        if init_v.var_type != VariableType.CONSTANT or raw is None \
                or np.asarray(raw).size != 1:
            return None
        init = float(np.asarray(raw).reshape(()))
        # normalize to "counter strictly approaches bound"
        if flipped:                      # Cmp(K, arg_k) — mirror it
            sym = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}[sym]
        if sym in ("<", "<=") and step > 0:
            span = bound - init + (1 if sym == "<=" else 0)
            trip = int(np.ceil(span / step))
        elif sym in (">", ">=") and step < 0:
            span = init - bound + (1 if sym == ">=" else 0)
            trip = int(np.ceil(span / -step))
        else:
            return None                  # diverging loop — leave dynamic
        return max(0, trip)
    except Exception:                    # detection must never break import
        return None


class OpNode:
    """One node of the op graph (ref: ``samediff.internal.SameDiffOp``)."""

    __slots__ = ("name", "op_name", "inputs", "outputs", "attrs", "fn",
                 "subgraphs")

    def __init__(self, name, op_name, inputs, outputs, attrs, fn=None,
                 subgraphs=None):
        self.name = name
        self.op_name = op_name
        self.inputs = list(inputs)
        self.outputs = list(outputs)
        self.attrs = dict(attrs)
        self.fn = fn  # only for non-serializable lambda ops
        # control-flow bodies: {"true"/"false"} for __cond__,
        # {"cond"/"body"} for __while__ — nested SameDiff graphs
        self.subgraphs = subgraphs

    def to_dict(self):
        if self.fn is not None:
            raise ValueError(
                f"op {self.name!r} wraps a Python lambda and cannot be "
                f"serialized; rebuild it from registered ops")
        d = {"name": self.name, "op": self.op_name, "inputs": self.inputs,
             "outputs": self.outputs, "attrs": self.attrs}
        if self.subgraphs:
            # subgraph VALUES ride the enclosing graph's npz (binary), keyed
            # "__sub__/<op>/<branch>/<var>" — only structure goes in the json
            d["subgraphs"] = {
                k: {"graph": sg.to_dict(), "outputs": sg._branch_outputs}
                for k, sg in self.subgraphs.items()}
        return d


class TrainingConfig:
    """Ref: ``org.nd4j.autodiff.samediff.TrainingConfig``.

    ``updater`` is an optax GradientTransformation or one of our
    ``optim.updaters`` config objects (which expose ``.to_optax()``).
    """

    def __init__(self, updater=None, l1=0.0, l2=0.0,
                 data_set_feature_mapping: Sequence[str] = (),
                 data_set_label_mapping: Sequence[str] = (),
                 loss_variables: Sequence[str] = ()):
        self.updater = updater
        self.l1 = float(l1)
        self.l2 = float(l2)
        self.data_set_feature_mapping = list(data_set_feature_mapping)
        self.data_set_label_mapping = list(data_set_label_mapping)
        self.loss_variables = list(loss_variables)

    def to_optax(self):
        import optax
        u = self.updater
        if u is None:
            return optax.sgd(1e-3)
        if hasattr(u, "to_optax"):
            return u.to_optax()
        return u

    def to_dict(self):
        u = self.updater
        return {"l1": self.l1, "l2": self.l2,
                "featureMapping": self.data_set_feature_mapping,
                "labelMapping": self.data_set_label_mapping,
                "lossVariables": self.loss_variables,
                "updater": getattr(u, "to_dict", lambda: None)()}

    @staticmethod
    def from_dict(d: dict) -> "TrainingConfig":
        from deeplearning4j_tpu.optim.updaters import Updater
        upd = Updater.from_dict(d["updater"]) if d.get("updater") else None
        return TrainingConfig(
            updater=upd, l1=d.get("l1", 0.0), l2=d.get("l2", 0.0),
            data_set_feature_mapping=d.get("featureMapping", ()),
            data_set_label_mapping=d.get("labelMapping", ()),
            loss_variables=d.get("lossVariables", ()))


class _Namespace:
    def __init__(self, sd: "SameDiff"):
        self.sd = sd

    def _op(self, name, *args, **attrs):
        args = [self.sd._lift(a) for a in args]
        return self.sd._op(name, *args, **attrs)

    def __getattr__(self, item):
        # generic fall-through on EVERY namespace: any registered op by
        # name (the reference generates its ~200-method namespace classes
        # with codegen, SURVEY E8; here the registry IS the codegen source)
        if op_registry.has(item):
            def call(*args, **attrs):
                return self._op(item, *args, **attrs)
            return call
        raise AttributeError(item)


class SDMath(_Namespace):
    """Ref: ``SDMath`` / ``SDBaseOps`` transform ops."""

    def square(self, x): return self._op("square", x)
    def abs(self, x): return self._op("abs", x)
    def exp(self, x): return self._op("exp", x)
    def log(self, x): return self._op("log", x)
    def sqrt(self, x): return self._op("sqrt", x)
    def tanh(self, x): return self._op("tanh", x)
    def cos(self, x): return self._op("cos", x)
    def sin(self, x): return self._op("sin", x)
    def pow(self, x, p): return self._op("pow", x, p)
    def neg(self, x): return self._op("neg", x)
    def max(self, a, b): return self._op("maximum", a, b)
    def min(self, a, b): return self._op("minimum", a, b)
    def isnan(self, x): return self._op("isnan", x)
    def confusion_matrix(self, labels, pred, num_classes):
        return self._op("confusion_matrix", labels, pred, num_classes=num_classes)


class SDNN(_Namespace):
    """Ref: ``SDNN`` (org.nd4j.autodiff.samediff.ops.SDNN)."""

    def relu(self, x): return self._op("relu", x)
    def relu6(self, x): return self._op("relu6", x)
    def gelu(self, x): return self._op("gelu", x)
    def elu(self, x): return self._op("elu", x)
    def selu(self, x): return self._op("selu", x)
    def sigmoid(self, x): return self._op("sigmoid", x)
    def tanh(self, x): return self._op("tanh", x)
    def softmax(self, x, axis=-1): return self._op("softmax", x, axis=axis)
    def log_softmax(self, x, axis=-1): return self._op("log_softmax", x, axis=axis)
    def softplus(self, x): return self._op("softplus", x)
    def swish(self, x): return self._op("swish", x)
    def leakyrelu(self, x, alpha=0.01): return self._op("leakyrelu", x, alpha=alpha)
    def linear(self, x, w, b=None):
        out = self._op("matmul", x, w)
        return out + b if b is not None else out
    def layer_norm(self, x, gamma=None, beta=None, axis=-1, epsilon=1e-5):
        args = [x] + [a for a in (gamma, beta) if a is not None]
        return self._op("layer_norm", *args, axis=axis, epsilon=epsilon)
    def batch_norm(self, x, mean, var, gamma, beta, epsilon=1e-5, axis=-1):
        return self._op("batchnorm", x, mean, var, gamma, beta,
                        epsilon=epsilon, axis=axis)
    def dropout(self, x, p, seed=0):
        return self.sd._random_op("dropout_inverted", x, p=p, seed=seed)
    def multi_head_dot_product_attention(self, q, k, v, mask=None, scaled=True):
        args = [q, k, v] + ([mask] if mask is not None else [])
        return self._op("dot_product_attention", *args, scaled=scaled)
    def pad(self, x, paddings, value=0.0):
        return self._op("pad", x, paddings=paddings, value=value)


class SDCNN(_Namespace):
    """Ref: ``SDCNN``."""

    def conv2d(self, x, w, b=None, strides=(1, 1), padding="SAME", dilation=(1, 1)):
        args = [x, w] + ([b] if b is not None else [])
        return self._op("conv2d", *args, strides=list(strides), padding=padding,
                        dilation=list(dilation))
    def deconv2d(self, x, w, b=None, strides=(1, 1), padding="SAME"):
        args = [x, w] + ([b] if b is not None else [])
        return self._op("deconv2d", *args, strides=list(strides), padding=padding)
    def depthwise_conv2d(self, x, w, b=None, strides=(1, 1), padding="SAME"):
        args = [x, w] + ([b] if b is not None else [])
        return self._op("depthwise_conv2d", *args, strides=list(strides), padding=padding)
    def max_pooling2d(self, x, kernel=(2, 2), strides=None, padding="VALID"):
        return self._op("maxpool2d", x, kernel=list(kernel),
                        strides=list(strides) if strides else None, padding=padding)
    def avg_pooling2d(self, x, kernel=(2, 2), strides=None, padding="VALID"):
        return self._op("avgpool2d", x, kernel=list(kernel),
                        strides=list(strides) if strides else None, padding=padding)
    def upsampling2d(self, x, size=2): return self._op("upsampling2d", x, size=size)
    def im2col(self, x, kernel, strides=(1, 1), padding="VALID"):
        return self._op("im2col", x, kernel=list(kernel), strides=list(strides),
                        padding=padding)
    def space_to_depth(self, x, block): return self._op("space_to_depth", x, block_size=block)
    def depth_to_space(self, x, block): return self._op("depth_to_space", x, block_size=block)


class SDRNN(_Namespace):
    """Ref: ``SDRNN`` — cell-level ops; full sequences via lax.scan in layers."""

    def lstm_cell(self, x, h, c, w, b, forget_bias=1.0):
        return self._op("lstm_cell", x, h, c, w, b, forget_bias=forget_bias,
                        n_out=2)
    def gru_cell(self, x, h, w_rz, w_h, b_rz, b_h):
        return self._op("gru_cell", x, h, w_rz, w_h, b_rz, b_h)
    def sru_cell(self, x, c, w, b):
        return self._op("sru_cell", x, c, w, b, n_out=2)


class SDLoss(_Namespace):
    """Ref: ``SDLoss``. Each returns a scalar mean loss by default."""

    def mse(self, labels, predictions):
        return ((predictions - labels) * (predictions - labels)).mean()
    def mean_squared_error(self, labels, predictions):
        return self.mse(labels, predictions)
    def l2_loss(self, x):
        return (x * x).sum() * 0.5
    def absolute_difference(self, labels, predictions):
        return self._op("abs", predictions - labels).mean()
    def softmax_cross_entropy(self, labels, logits, axis=-1):
        return self._op("softmax_cross_entropy", logits, labels, axis=axis).mean()
    def sparse_softmax_cross_entropy(self, labels, logits):
        return self._op("sparse_softmax_cross_entropy", logits, labels).mean()
    def sigmoid_cross_entropy(self, labels, logits):
        return self._op("sigmoid_cross_entropy", logits, labels).mean()
    def log_loss(self, labels, predictions, epsilon=1e-7):
        p = self._op("clipbyvalue", predictions, clip_value_min=epsilon,
                     clip_value_max=1.0 - epsilon)
        term = labels * self._op("log", p) + (1.0 - labels) * self._op("log", 1.0 - p)
        return -term.mean()
    def cosine_distance(self, labels, predictions, axis=-1):
        a = self._op("l2_normalize", labels, axis=axis)
        b = self._op("l2_normalize", predictions, axis=axis)
        return (1.0 - (a * b).sum(axis)).mean()
    def huber_loss(self, labels, predictions, delta=1.0):
        err = predictions - labels
        abs_err = self._op("abs", err)
        quad = self._op("minimum", abs_err, delta)
        return (0.5 * quad * quad + delta * (abs_err - quad)).mean()
    def hinge_loss(self, labels, predictions):
        # labels in {0,1} → {-1,1}
        sign = labels * 2.0 - 1.0
        return self._op("relu", 1.0 - sign * predictions).mean()


class SDLinalg(_Namespace):
    def cholesky(self, x): return self._op("cholesky", x)
    def svd(self, x): return self._op("svd", x, n_out=3)
    def qr(self, x): return self._op("qr", x, n_out=2)
    def solve(self, a, b): return self._op("solve", a, b)
    def inverse(self, x): return self._op("matrix_inverse", x)
    def det(self, x): return self._op("matrix_determinant", x)
    def matmul(self, a, b, transpose_a=False, transpose_b=False):
        return self._op("matmul", a, b, transpose_a=transpose_a,
                        transpose_b=transpose_b)


class SDRandom(_Namespace):
    """Ref: ``SDRandom``. Random ops fold a per-node counter into the base
    RNG key supplied at execution time (exec arg ``rng_seed``), so graphs stay
    deterministic per seed without a stateful RNG in the graph."""

    def normal(self, mean, stddev, shape, seed=0):
        return self.sd._random_op("random_normal", shape=list(shape), mean=mean,
                                  stddev=stddev, seed=seed)
    def uniform(self, low, high, shape, seed=0):
        return self.sd._random_op("random_uniform", shape=list(shape),
                                  minval=low, maxval=high, seed=seed)
    def bernoulli(self, p, shape, seed=0):
        return self.sd._random_op("random_bernoulli", shape=list(shape), p=p,
                                  seed=seed)


class SDBitwise(_Namespace):
    """Ref: ``SDBitwise`` (nd4j bitwise op namespace)."""

    def and_(self, a, b): return self._op("bitwise_and", a, b)
    def or_(self, a, b): return self._op("bitwise_or", a, b)
    def xor(self, a, b): return self._op("bitwise_xor", a, b)
    def left_shift(self, x, n): return self._op("shift_bits", x, n)
    def right_shift(self, x, n): return self._op("rshift_bits", x, n)
    def left_shift_cyclic(self, x, n):
        return self._op("cyclic_shift_bits", x, n)
    def bits_hamming_distance(self, a, b):
        return self._op("bits_hamming_distance", a, b)
    bitwiseAnd, bitwiseOr, bitwiseXor = and_, or_, xor
    leftShift, rightShift = left_shift, right_shift


class SDImage(_Namespace):
    """Ref: ``SDImage`` (nd4j image op namespace)."""

    def resize_bilinear(self, x, h, w):
        # size is a static attr (shapes must be concrete under jit)
        return self._op("resize_bilinear", x, size=(h, w))
    def resize_nearest(self, x, h, w):
        return self._op("resize_nearest_neighbor", x, size=(h, w))
    def resize_bicubic(self, x, h, w):
        return self._op("resize_bicubic", x, size=(h, w))
    def crop_and_resize(self, image, boxes, box_indices, crop_h, crop_w):
        return self._op("crop_and_resize", image, boxes, box_indices,
                        crop_size=(crop_h, crop_w))
    def extract_image_patches(self, x, kh, kw, sh, sw, rh=1, rw=1,
                              same_mode=False):
        return self._op("extract_image_patches", x, ksizes=(kh, kw),
                        strides=(sh, sw), rates=(rh, rw),
                        padding="SAME" if same_mode else "VALID")
    def rgb_to_hsv(self, x): return self._op("rgb_to_hsv", x)
    def hsv_to_rgb(self, x): return self._op("hsv_to_rgb", x)
    def rgb_to_yuv(self, x): return self._op("rgb_to_yuv", x)
    def yuv_to_rgb(self, x): return self._op("yuv_to_rgb", x)
    def adjust_contrast(self, x, factor):
        return self._op("adjust_contrast", x, factor)
    def adjust_saturation(self, x, factor):
        return self._op("adjust_saturation", x, factor)
    def adjust_hue(self, x, delta): return self._op("adjust_hue", x, delta)
    def non_max_suppression(self, boxes, scores, max_out, iou_threshold=0.5,
                            score_threshold=float("-inf")):
        return self._op("non_max_suppression", boxes, scores,
                        max_output_size=max_out, iou_threshold=iou_threshold,
                        score_threshold=score_threshold)


_RANDOM_OPS = {"random_normal", "random_uniform", "random_bernoulli",
               "dropout", "dropout_inverted"}


class SameDiff:
    """The graph builder + session owner (ref: ``SameDiff`` class).

    Create with ``SameDiff.create()``; build variables and ops; execute with
    ``output``/``exec``; train with ``fit`` after ``set_training_config``.
    """

    def __init__(self):
        self._vars: Dict[str, SDVariable] = {}
        self._values: Dict[str, jnp.ndarray] = {}
        self._ops: List[OpNode] = []
        self._producer: Dict[str, OpNode] = {}   # var name -> producing op
        self._name_counter: Dict[str, int] = {}
        self._loss_variables: List[str] = []
        self._branch_outputs: List[str] = []   # set when used as a CF body
        self.training_config: Optional[TrainingConfig] = None
        self._compiled_cache: Dict[Any, Callable] = {}
        self._train_step = None
        self._train_sig = None
        self._opt_state = None
        self._pending_opt_leaves = None
        self._pending_opt_named = None   # {paramName: {key: array}} from a
                                         # FlatGraph UpdaterState table
        self._pending_opt_updater = None  # class name of the updater that
                                          # produced the pending state
        self._seed = 12345
        self.listeners: List[Any] = []
        self.epoch_count = 0
        self.iteration_count = 0
        # namespaces
        self.math = SDMath(self)
        self.nn = SDNN(self)
        self.cnn = SDCNN(self)
        self.rnn = SDRNN(self)
        self.loss = SDLoss(self)
        self.linalg = SDLinalg(self)
        self.random = SDRandom(self)
        self.bitwise = SDBitwise(self)
        self.image = SDImage(self)
        self.mesh = None               # set_mesh: data-parallel training

    # ---- creation -----------------------------------------------------
    @staticmethod
    def create() -> "SameDiff":
        return SameDiff()

    # ---- multi-device -------------------------------------------------
    def set_mesh(self, mesh) -> "SameDiff":
        """Train data-parallel over a ``jax.sharding.Mesh`` with a 'data'
        axis: ``fit`` shards each feed batch over the axis and replicates
        variables; GSPMD inserts the gradient allreduce. The analog of
        wrapping a net in ShardedTrainer (SURVEY P3/P9) for the SameDiff
        surface — one compiled program, no per-replica copies."""
        from deeplearning4j_tpu.parallel.mesh import DATA_AXIS

        if mesh is not None and DATA_AXIS not in mesh.axis_names:
            raise ValueError(f"mesh has no {DATA_AXIS!r} axis: "
                             f"{mesh.axis_names}")
        self.mesh = mesh
        self._train_step = None        # re-placement on next fit
        return self

    def _shard_feed(self, ph: Dict[str, Any]) -> Dict[str, Any]:
        from jax.sharding import NamedSharding, PartitionSpec as P
        from deeplearning4j_tpu.parallel.mesh import DATA_AXIS

        dp = self.mesh.shape[DATA_AXIS]
        out = {}
        for k, v in ph.items():
            if v.ndim >= 1 and v.shape[0] % dp == 0:
                out[k] = jax.device_put(
                    v, NamedSharding(self.mesh, P(DATA_AXIS)))
            else:   # indivisible or scalar: replicate
                out[k] = jax.device_put(v, NamedSharding(self.mesh, P()))
        return out

    def _replicate_values(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self.mesh, P())
        self._values = {k: jax.device_put(v, rep)
                        for k, v in self._values.items()}

    def _unique(self, base: str) -> str:
        if base not in self._vars and base not in self._name_counter:
            self._name_counter[base] = 0
            return base
        n = self._name_counter.get(base, 0) + 1
        self._name_counter[base] = n
        return f"{base}:{n}"

    def _register(self, v: SDVariable) -> SDVariable:
        self._vars[v.name] = v
        return v

    def var(self, name: str, shape=None, dtype=jnp.float32, init=None,
            weight_init=None) -> SDVariable:
        """Trainable variable. ``init`` may be a concrete array or a
        weight-init name from ``nn.weights`` (e.g. 'xavier', 'relu')."""
        name = self._unique(name)
        if init is not None and not isinstance(init, str):
            arr = jnp.asarray(init, dtype)
            shape = arr.shape
        else:
            if shape is None:
                raise ValueError("var() needs a shape or a concrete init array")
            scheme = init if isinstance(init, str) else (weight_init or "xavier")
            from deeplearning4j_tpu.nn import weights as _w
            shape = tuple(shape)
            fan_in = shape[0] if len(shape) >= 2 else max(1, int(np.prod(shape)))
            fan_out = shape[-1] if len(shape) >= 2 else fan_in
            # stable per-name seed (Python's hash() is salted per process)
            name_seed = zlib.crc32(name.encode("utf-8"))
            arr = _w.init(scheme, jax.random.fold_in(
                              jax.random.key(self._seed), name_seed),
                          shape, fan_in, fan_out, dtype)
        v = SDVariable(self, name, VariableType.VARIABLE, tuple(arr.shape), arr.dtype)
        self._values[name] = arr
        self._invalidate_cache()
        return self._register(v)

    def constant(self, value, name: str = "const") -> SDVariable:
        arr = jnp.asarray(value)
        name = self._unique(name)
        v = SDVariable(self, name, VariableType.CONSTANT, tuple(arr.shape), arr.dtype)
        self._values[name] = arr
        self._invalidate_cache()
        return self._register(v)

    def placeholder(self, name: str, shape=None, dtype=jnp.float32) -> SDVariable:
        name = self._unique(name)
        v = SDVariable(self, name, VariableType.PLACEHOLDER, shape, dtype)
        return self._register(v)

    # DL4J-style aliases
    def variable(self, *a, **k): return self.var(*a, **k)
    def one(self, name, shape): return self.constant(jnp.ones(shape), name)
    def zero(self, name, shape): return self.constant(jnp.zeros(shape), name)

    def _lift(self, x) -> SDVariable:
        if isinstance(x, SDVariable):
            if x.sd is not self:
                raise ValueError("variable belongs to a different SameDiff")
            return x
        return self.constant(x)

    # ---- op creation ---------------------------------------------------
    def _op(self, op_name: str, *inputs: SDVariable, n_out: int = 1,
            name: str = None, **attrs):
        opdef = op_registry.get(op_name)
        node_name = self._unique(name or op_name)
        n_out = max(n_out, opdef.num_outputs)
        out_names = ([node_name] if n_out == 1
                     else [f"{node_name}#{i}" for i in range(n_out)])
        node = OpNode(node_name, op_name, [v.name for v in inputs], out_names,
                      attrs)
        self._ops.append(node)
        # shape inference via eval_shape over abstract inputs
        dtype_only = False
        try:
            in_avals = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in inputs]
            out_aval = jax.eval_shape(lambda *xs: opdef.fn(*xs, **attrs), *in_avals)
        except Exception:
            out_aval = None
            # dtype-only retry: dims unknown (None) block eval_shape, but
            # the output DTYPE is still inferable by substituting a dummy
            # extent — without this, any op downstream of a dynamic-dim
            # placeholder silently defaulted to float32 (e.g. a bool loop
            # condition became f32 and failed while_loop's type check)
            if inputs and all(v.shape is not None for v in inputs):
                try:
                    in_avals = [jax.ShapeDtypeStruct(
                        tuple(2 if d is None else int(d) for d in v.shape),
                        v.dtype) for v in inputs]
                    out_aval = jax.eval_shape(
                        lambda *xs: opdef.fn(*xs, **attrs), *in_avals)
                    dtype_only = True
                except Exception:
                    out_aval = None
        outs = []
        for i, on in enumerate(out_names):
            if out_aval is None:
                shape, dtype = None, jnp.float32
            elif dtype_only:
                # extents from the dummy pass are NOT trustworthy, but the
                # RANK is — keep (None,)*rank so the next consumer's retry
                # gate (`shape is not None`) still fires and dtype keeps
                # flowing through chained ops
                aval = out_aval if n_out == 1 else out_aval[i]
                shape = (None,) * len(aval.shape)
                dtype = aval.dtype
            elif n_out == 1:
                shape, dtype = out_aval.shape, out_aval.dtype
            else:
                shape, dtype = out_aval[i].shape, out_aval[i].dtype
            ov = SDVariable(self, on, VariableType.ARRAY, shape, dtype)
            self._register(ov)
            self._producer[on] = node
            outs.append(ov)
        self._invalidate_cache()
        return outs[0] if n_out == 1 else tuple(outs)

    def _random_op(self, op_name: str, *inputs, **attrs):
        """Random ops get a deterministic per-node key derived from the
        execution-time base seed (see SDRandom docstring)."""
        attrs["__random_index__"] = len(self._ops)
        return self._op(op_name, *inputs, **attrs)

    def lambda_op(self, fn: Callable, *inputs: SDVariable, n_out: int = 1,
                  name: str = "lambda"):
        """Embed an arbitrary jax-traceable function as a graph node.

        Non-serializable (``save`` will refuse); the escape hatch the
        reference provides via ``SameDiffLambdaLayer``/custom ops.
        """
        node_name = self._unique(name)
        out_names = ([node_name] if n_out == 1
                     else [f"{node_name}#{i}" for i in range(n_out)])
        node = OpNode(node_name, "__lambda__", [v.name for v in inputs],
                      out_names, {}, fn=fn)
        self._ops.append(node)
        outs = []
        try:
            in_avals = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in inputs]
            out_aval = jax.eval_shape(fn, *in_avals)
        except Exception:
            out_aval = None
        for i, on in enumerate(out_names):
            if out_aval is None:
                shape, dtype = None, jnp.float32
            elif n_out == 1:
                shape, dtype = out_aval.shape, out_aval.dtype
            else:
                shape, dtype = out_aval[i].shape, out_aval[i].dtype
            ov = SDVariable(self, on, VariableType.ARRAY, shape, dtype)
            self._register(ov)
            self._producer[on] = node
            outs.append(ov)
        self._invalidate_cache()
        return outs[0] if n_out == 1 else tuple(outs)

    # ---- control flow ---------------------------------------------------
    @staticmethod
    def _build_body(builder: Callable, operands: Sequence[SDVariable]):
        """Trace ``builder(sub_sd, *arg_phs)`` into a nested SameDiff whose
        placeholders arg0..argN mirror the operands."""
        sub = SameDiff.create()
        phs = [sub.placeholder(f"arg{i}", v.shape, v.dtype)
               for i, v in enumerate(operands)]
        out = builder(sub, *phs)
        outs = list(out) if isinstance(out, (list, tuple)) else [out]
        sub._branch_outputs = [o.name for o in outs]
        return sub, outs

    def _cf_node(self, op_name, name, inputs, subgraphs, out_templates,
                 attrs=None):
        """Register a control-flow OpNode whose output shapes/dtypes come
        from the branch's traced outputs."""
        node_name = self._unique(name or op_name.strip("_"))
        n_out = len(out_templates)
        out_names = ([node_name] if n_out == 1
                     else [f"{node_name}#{i}" for i in range(n_out)])
        node = OpNode(node_name, op_name, [v.name for v in inputs],
                      out_names, attrs or {}, subgraphs=subgraphs)
        self._ops.append(node)
        outs = []
        for on, tmpl in zip(out_names, out_templates):
            ov = SDVariable(self, on, VariableType.ARRAY, tmpl.shape,
                            tmpl.dtype)
            self._register(ov)
            self._producer[on] = node
            outs.append(ov)
        self._invalidate_cache()
        return outs[0] if n_out == 1 else tuple(outs)

    def if_cond(self, pred: SDVariable, true_body: Callable,
                false_body: Callable, *operands: SDVariable,
                name: str = None):
        """Conditional (ref: ``SameDiff#ifCond``; TF If/StatelessIf).

        ``true_body``/``false_body`` are ``fn(sub_sd, *args) -> var(s)``
        builders traced into nested graphs; lowers to ``lax.cond`` (both
        branches compiled, predicate selects on device — XLA-friendly,
        differentiable). Branches must return matching shapes/dtypes.
        """
        pred = self._lift(pred)
        operands = [self._lift(o) for o in operands]
        t_sd, t_outs = self._build_body(true_body, operands)
        f_sd, f_outs = self._build_body(false_body, operands)
        if [(o.shape, np.dtype(o.dtype)) for o in t_outs] != \
                [(o.shape, np.dtype(o.dtype)) for o in f_outs]:
            raise ValueError("if_cond branches must return matching "
                             "shapes/dtypes")
        return self._cf_node("__cond__", name, [pred] + operands,
                             {"true": t_sd, "false": f_sd}, t_outs)

    ifCond = if_cond

    def while_loop(self, cond_body: Callable, loop_body: Callable,
                   *loop_vars: SDVariable, name: str = None):
        """While loop (ref: ``SameDiff#whileLoop``; TF While/StatelessWhile).

        ``cond_body(sub_sd, *state) -> scalar bool``;
        ``loop_body(sub_sd, *state) -> new state`` (same shapes/dtypes).
        Counted loops (``i < K; i += step`` with constant init/bound/step —
        what TF emits for static-length sequence loops) are DETECTED and
        lowered to ``lax.scan``, which is reverse-differentiable: imported
        control flow in the training hot path gets gradients. Genuinely
        data-dependent loops lower to ``lax.while_loop`` and stay
        forward-only (XLA while has no reverse mode — the reference's
        TF-imported while graphs share the restriction).
        """
        loop_vars = [self._lift(v) for v in loop_vars]
        c_sd, c_outs = self._build_body(cond_body, loop_vars)
        if len(c_outs) != 1:
            raise ValueError("while_loop cond must return one scalar")
        b_sd, b_outs = self._build_body(loop_body, loop_vars)
        if len(b_outs) != len(loop_vars):
            raise ValueError("while_loop body must return one var per "
                             "loop var")
        def compatible(a, b):
            # None = unknown rank, None dim = unknown extent — either is
            # compatible with anything (the dtype-only inference pass emits
            # (None,)*rank shapes; only CONCRETE disagreements are errors)
            if a is None or b is None:
                return True
            if len(a) != len(b):
                return False
            return all(da is None or db is None or da == db
                       for da, db in zip(a, b))

        mismatched = [
            (v.name, v.shape, np.dtype(v.dtype), o.shape, np.dtype(o.dtype))
            for v, o in zip(loop_vars, b_outs)
            if np.dtype(v.dtype) != np.dtype(o.dtype)
            or not compatible(v.shape, o.shape)]
        if mismatched:
            raise ValueError(
                f"while_loop body must preserve loop-var shapes/dtypes; "
                f"mismatches (var, init shape/dtype, body shape/dtype): "
                f"{mismatched}")
        # counted-loop detection: `for i in range(k, C)` shapes (the form
        # every TF while_loop over a static sequence length takes). When the
        # trip count is provably static, the executor lowers to lax.scan —
        # which IS reverse-differentiable — so imported control flow in the
        # training hot path gets gradients (lax.while_loop cannot)
        trip = _counted_trip(c_sd, b_sd, loop_vars)
        return self._cf_node("__while__", name, loop_vars,
                             {"cond": c_sd, "body": b_sd}, b_outs,
                             attrs=({"trip_count": int(trip)}
                                    if trip is not None else None))

    whileLoop = while_loop

    # ---- introspection -------------------------------------------------
    def get_variable(self, name: str) -> SDVariable:
        return self._vars[name]

    def has_variable(self, name: str) -> bool:
        return name in self._vars

    def variables(self) -> List[SDVariable]:
        return list(self._vars.values())

    def variable_names(self) -> List[str]:
        return list(self._vars.keys())

    def trainable_names(self) -> List[str]:
        return [n for n, v in self._vars.items()
                if v.var_type == VariableType.VARIABLE]

    def placeholders(self) -> List[str]:
        return [n for n, v in self._vars.items()
                if v.var_type == VariableType.PLACEHOLDER]

    # ---- updater-state naming (FlatGraph UpdaterState table) -----------
    @staticmethod
    def _opt_leaf_key(path, trainable: set):
        """A tree-path of the optax state → (paramName, stateKey). Leaves
        whose path crosses a trainable param's dict key group under that
        param (e.g. Adam's mu['w'] → ('w', '0/mu')); global leaves (step
        count) group under paramName '' — the reference's per-parameter
        ``UpdaterState{paramName, updaterStateKeys, updaterStateValues}``
        shape."""
        pname, parts = "", []
        for entry in path:
            k = getattr(entry, "key", None)
            if k is None:
                k = getattr(entry, "name", None)
            if k is None:
                k = getattr(entry, "idx", None)
            if isinstance(k, str) and not pname and k in trainable:
                pname = k
            else:
                parts.append(str(k))
        return pname, "/".join(parts) or "_"

    def _updater_state_by_param(self):
        """Current optimizer state grouped per parameter (None when no
        state exists) — the FlatGraph ``updaterState`` payload. A graph
        loaded from a checkpoint but not yet re-fit still holds its state
        as ``_pending_opt_named`` — re-saving must not drop it."""
        if self._opt_state is None:
            if self._pending_opt_named is not None:
                return {p: dict(kv)
                        for p, kv in self._pending_opt_named.items()}
            return None
        from jax.tree_util import tree_flatten_with_path

        trainable = set(self.trainable_names())
        flat, _ = tree_flatten_with_path(self._opt_state)
        out: Dict[str, Dict[str, np.ndarray]] = {}
        for path, leaf in flat:
            pname, key = self._opt_leaf_key(path, trainable)
            out.setdefault(pname, {})[key] = np.asarray(leaf)
        return out

    def ops(self) -> List[OpNode]:
        return list(self._ops)

    def summary(self) -> str:
        lines = [f"SameDiff: {len(self._vars)} variables, {len(self._ops)} ops"]
        for v in self._vars.values():
            if v.var_type != VariableType.ARRAY:
                lines.append(f"  {v.var_type.value:<12} {v.name:<24} {v.shape}")
        for op in self._ops:
            lines.append(f"  op {op.op_name:<20} {op.inputs} -> {op.outputs}")
        return "\n".join(lines)

    def _rename(self, old: str, new: str):
        if new in self._vars:
            raise ValueError(f"variable {new!r} already exists")
        v = self._vars.pop(old)
        v.name = new
        self._vars[new] = v
        if old in self._values:
            self._values[new] = self._values.pop(old)
        if old in self._producer:
            self._producer[new] = self._producer.pop(old)
        for op in self._ops:
            op.inputs = [new if i == old else i for i in op.inputs]
            op.outputs = [new if o == old else o for o in op.outputs]
        self._loss_variables = [new if n == old else n for n in self._loss_variables]
        self._invalidate_cache()

    def set_loss_variables(self, *names):
        self._loss_variables = [n.name if isinstance(n, SDVariable) else n
                                for n in names]
        self._invalidate_cache()

    def set_training_config(self, config: TrainingConfig):
        self.training_config = config
        if config.loss_variables and not self._loss_variables:
            self._loss_variables = list(config.loss_variables)
        self._train_step = None
        self._opt_state = None

    def add_listener(self, listener):
        self.listeners.append(listener)

    def _invalidate_cache(self):
        self._compiled_cache.clear()
        self._train_step = None

    # ---- emission (the AbstractSession topo-walk → HLO emitter) --------
    def _needed_ops(self, outputs: Sequence[str],
                    ops: Optional[List[OpNode]] = None) -> List[OpNode]:
        """Ops needed to compute `outputs`, in graph order."""
        needed_vars = set(outputs)
        needed_ops: List[OpNode] = []
        for op in reversed(self._ops if ops is None else ops):
            if any(o in needed_vars for o in op.outputs):
                needed_ops.append(op)
                needed_vars.update(op.inputs)
        return list(reversed(needed_ops))

    def _emit(self, outputs: Sequence[str]) -> Callable:
        """Build fn(values: dict, placeholders: dict, rng_seed) -> tuple.

        One pass over the (pruned) op list in insertion order — insertion
        order is topological by construction in a define-then-run builder.
        """
        # emission-time peepholes (autodiff/passes): rewrites run on a
        # copy — the stored graph/serialization is untouched. Pruning
        # happens AFTER the rewrite so orphaned motif remnants drop out.
        from deeplearning4j_tpu.autodiff.passes import optimize_for_emission
        ops = self._needed_ops(outputs, optimize_for_emission(self._ops))

        def fn(values: Dict[str, jnp.ndarray],
               placeholders: Dict[str, jnp.ndarray],
               rng_seed=0):
            env: Dict[str, jnp.ndarray] = {}
            env.update(values)
            env.update(placeholders)
            base_key = jax.random.key(rng_seed) if not isinstance(
                rng_seed, jax.Array) or jnp.issubdtype(
                jnp.asarray(rng_seed).dtype, jnp.integer) else rng_seed
            for op_idx, op in enumerate(ops):
                args = [env[i] for i in op.inputs]
                if op.op_name == "__cond__":
                    t_fn = op.subgraphs["true"]._branch_fn()
                    f_fn = op.subgraphs["false"]._branch_fn()
                    pred = jnp.squeeze(args[0]).astype(bool)
                    # thread a per-node key so random ops inside branches
                    # follow the execution-time seed
                    key = jax.random.fold_in(base_key, 1 + op_idx)
                    res = jax.lax.cond(
                        pred,
                        lambda a: t_fn(*a[:-1], rng_seed=a[-1]),
                        lambda a: f_fn(*a[:-1], rng_seed=a[-1]),
                        (*args[1:], key))
                    if len(op.outputs) == 1 and isinstance(res, tuple):
                        res = res[0]
                elif op.op_name == "__while__":
                    b_fn = op.subgraphs["body"]._branch_fn()
                    key = jax.random.fold_in(base_key, 1 + op_idx)

                    def _body(st, _b=b_fn, _k=key):
                        r = _b(*st, rng_seed=_k)
                        r = r if isinstance(r, tuple) else (r,)
                        # carry must keep the init structure/dtypes exactly
                        return tuple(jnp.asarray(x).astype(s.dtype)
                                     for x, s in zip(r, st))

                    trip = op.attrs.get("trip_count")
                    if trip is not None:
                        # counted loop: lax.scan is reverse-differentiable,
                        # so TF-imported control flow in the hot path TRAINS
                        def _scan_body(st, _x, _b=_body):
                            return _b(st), None
                        res, _ = jax.lax.scan(_scan_body, tuple(args),
                                              None, length=trip)
                    else:
                        c_fn = op.subgraphs["cond"]._branch_fn()
                        res = jax.lax.while_loop(
                            lambda st: jnp.squeeze(c_fn(*st)).astype(bool),
                            _body, tuple(args))
                    if len(op.outputs) == 1:
                        res = res[0]
                elif op.fn is not None:
                    res = op.fn(*args)
                else:
                    attrs = dict(op.attrs)
                    ridx = attrs.pop("__random_index__", None)
                    opdef = op_registry.get(op.op_name)
                    if ridx is not None:
                        key = jax.random.fold_in(base_key, ridx)
                        node_seed = attrs.pop("seed", 0)
                        if node_seed:
                            key = jax.random.fold_in(key, node_seed)
                        if op.op_name in ("dropout", "dropout_inverted"):
                            res = opdef(args[0], key, **attrs)
                        else:
                            res = opdef(key, **attrs)
                    else:
                        res = opdef(*args, **attrs)
                if len(op.outputs) == 1:
                    env[op.outputs[0]] = res
                else:
                    for on, r in zip(op.outputs, res):
                        env[on] = r
            return tuple(env[o] for o in outputs)

        return fn

    def _branch_fn(self) -> Callable:
        """Executor for a control-flow body: g(*args) over placeholders
        arg0..argN, closing over this subgraph's constant values."""
        outs = self._branch_outputs
        emit = self._emit(outs)

        def g(*xs, rng_seed=0):
            ph = {f"arg{i}": x for i, x in enumerate(xs)}
            res = emit(self._values, ph, rng_seed)
            return res if len(outs) > 1 else res[0]

        return g

    # ---- execution ----------------------------------------------------
    def output(self, placeholders: Dict[str, Any],
               outputs: Union[str, Sequence[str], None] = None,
               rng_seed: int = 0) -> Dict[str, jnp.ndarray]:
        """Whole-graph jitted inference (ref: ``SameDiff#output``).

        Compiled once per (outputs, placeholder shape/dtype) signature and
        cached — repeated calls hit the XLA executable directly.
        """
        if outputs is None:
            produced = {o for op in self._ops for o in op.outputs}
            consumed = {i for op in self._ops for i in op.inputs}
            outputs = sorted(produced - consumed)
        if isinstance(outputs, str):
            outputs = [outputs]
        outputs = [o.name if isinstance(o, SDVariable) else o for o in outputs]
        ph = {k: jnp.asarray(v) for k, v in (placeholders or {}).items()}
        needed_inputs = {i for op in self._needed_ops(outputs)
                         for i in op.inputs}
        missing = [p for p in self.placeholders()
                   if p not in ph and p in needed_inputs]
        if missing:
            raise ValueError(f"missing placeholders: {missing}")
        # the graph-opt flag is part of the key: toggling it mid-session
        # must re-emit, not silently reuse programs built under the other
        # setting (the peepholes run at emission time)
        from deeplearning4j_tpu.autodiff.passes import graph_opt_enabled
        key = (tuple(outputs), graph_opt_enabled(),
               tuple(sorted((k, v.shape, str(v.dtype)) for k, v in ph.items())))
        if key not in self._compiled_cache:
            emitted = self._emit(outputs)
            self._compiled_cache[key] = jax.jit(emitted)
        res = self._compiled_cache[key](self._values, ph, rng_seed)
        return dict(zip(outputs, res))

    def exec(self, placeholders=None, *outputs):
        return self.output(placeholders or {}, list(outputs) or None)

    def batch_output(self, placeholders, outputs):
        return self.output(placeholders, outputs)

    # ---- gradients ----------------------------------------------------
    def calculate_gradients(self, placeholders: Dict[str, Any],
                            wrt: Sequence[str] = None,
                            rng_seed: int = 0) -> Dict[str, jnp.ndarray]:
        """Ref: ``SameDiff#calculateGradients``. Backward graph = jax.grad of
        the emitted forward program (replaces createGradFunction/doDiff)."""
        if not self._loss_variables:
            raise ValueError("no loss variables set (set_loss_variables)")
        wrt = list(wrt) if wrt else self.trainable_names()
        emitted = self._emit(self._loss_variables)
        ph = {k: jnp.asarray(v) for k, v in (placeholders or {}).items()}

        def loss_fn(train_vals, fixed_vals):
            outs = emitted({**fixed_vals, **train_vals}, ph, rng_seed)
            return sum(jnp.sum(o) for o in outs)

        train_vals = {n: self._values[n] for n in wrt}
        fixed_vals = {n: v for n, v in self._values.items() if n not in train_vals}
        grads = jax.jit(jax.grad(loss_fn))(train_vals, fixed_vals)
        return grads

    grad = calculate_gradients

    # ---- training -----------------------------------------------------
    def _build_train_step(self, ph_sig):
        import optax
        tc = self.training_config
        opt = tc.to_optax()
        loss_names = list(self._loss_variables)
        emitted = self._emit(loss_names)
        trainable = self.trainable_names()
        l1, l2 = tc.l1, tc.l2

        def step(train_vals, fixed_vals, opt_state, ph, rng_seed):
            def loss_fn(tv):
                outs = emitted({**fixed_vals, **tv}, ph, rng_seed)
                loss = sum(jnp.sum(o) for o in outs)
                if l2:
                    loss = loss + l2 * sum(jnp.sum(p * p) for p in tv.values())
                if l1:
                    loss = loss + l1 * sum(jnp.sum(jnp.abs(p)) for p in tv.values())
                return loss
            loss, grads = jax.value_and_grad(loss_fn)(train_vals)
            updates, opt_state = opt.update(grads, opt_state, train_vals)
            train_vals = optax.apply_updates(train_vals, updates)
            return train_vals, opt_state, loss

        jitted = jax.jit(step, donate_argnums=(0, 2))
        init_state = opt.init({n: self._values[n] for n in trainable})
        if self._pending_opt_leaves is not None:
            # updater state loaded from a checkpoint: rehydrate into the
            # freshly-built optax tree structure (ref: SameDiff#load restoring
            # updater moments so Adam state survives resume)
            same_upd = (self._pending_opt_updater is None
                        or tc.updater is None
                        or self._pending_opt_updater
                        == type(tc.updater).__name__)
            treedef = jax.tree.structure(init_state)
            leaves = [jnp.asarray(l) for l in self._pending_opt_leaves]
            if same_upd and len(leaves) == treedef.num_leaves:
                init_state = jax.tree.unflatten(treedef, leaves)
            self._pending_opt_leaves = None
            self._pending_opt_updater = None
        elif self._pending_opt_named is not None:
            # per-parameter state from a FlatGraph UpdaterState table:
            # match each fresh leaf by its (paramName, stateKey) path —
            # robust to leaf ORDER, unlike the flat-leaves zip path
            from jax.tree_util import tree_flatten_with_path

            ok = True
            # identity of the updater that PRODUCED the state = the
            # artifact's trainingConfig updater (recorded at load); a
            # key-compatible but different updater (RMSProp's nu ⊂
            # Adam's state) must not silently adopt the wrong moments
            if self._pending_opt_updater is not None \
                    and tc.updater is not None \
                    and self._pending_opt_updater != type(tc.updater).__name__:
                ok = False
            tset = set(trainable)
            flat, _ = tree_flatten_with_path(init_state)
            new_leaves = []
            for path, leaf in (flat if ok else []):
                pname, key = self._opt_leaf_key(path, tset)
                arr = self._pending_opt_named.get(pname, {}).get(key)
                if arr is None or tuple(np.shape(arr)) != tuple(leaf.shape):
                    ok = False
                    break
                new_leaves.append(jnp.asarray(arr, dtype=leaf.dtype))
            if ok:
                init_state = jax.tree.unflatten(
                    jax.tree.structure(init_state), new_leaves)
            else:
                import warnings

                warnings.warn(
                    "saved updaterState does not match the updater's "
                    "state tree (different updater config?) — starting "
                    "from fresh optimizer state", stacklevel=2)
            self._pending_opt_named = None
            self._pending_opt_updater = None
        return jitted, init_state

    def evaluate(self, iterator, output_name: str, evaluation=None,
                 label_index: int = 0):
        """Evaluate a graph output against iterator labels (ref:
        ``SameDiff#evaluate(DataSetIterator, String, IEvaluation...)``).
        Placeholder binding follows TrainingConfig's dataSetFeatureMapping,
        as in the reference; ``evaluation`` defaults to classification
        ``Evaluation``."""
        from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
        from deeplearning4j_tpu.eval.classification import Evaluation

        if self.training_config is None:
            raise ValueError("call set_training_config first (the feature "
                             "mapping binds iterator columns to placeholders)")
        ev = evaluation if evaluation is not None else Evaluation()
        tc = self.training_config
        if hasattr(iterator, "reset"):
            iterator.reset()
        data = iterator
        if isinstance(data, (DataSet, MultiDataSet)):
            data = [data]
        for ds in data:
            feats = ds.features if isinstance(ds.features, (list, tuple)) \
                else [ds.features]
            labs = ds.labels if isinstance(ds.labels, (list, tuple)) \
                else [ds.labels]
            ph = {name: jnp.asarray(arr) for name, arr in
                  zip(tc.data_set_feature_mapping, feats)}
            out = self.output(ph, [output_name])[output_name]
            ev.eval(labs[label_index], np.asarray(out))
        return ev

    def fit(self, data=None, epochs: int = 1, batch_size: int = None,
            rng_seed: int = 0):
        """Train (ref: ``SameDiff#fit``). ``data`` is a DataSet/
        MultiDataSet, an iterator of them, or a dict of placeholder arrays.

        Placeholder binding follows TrainingConfig's
        dataSetFeatureMapping/dataSetLabelMapping, as in the reference.
        """
        if self.training_config is None:
            raise ValueError("call set_training_config first")
        tc = self.training_config
        losses = []

        def batches():
            from deeplearning4j_tpu.data.dataset import DataSet, MultiDataSet
            if isinstance(data, dict):
                yield {k: jnp.asarray(v) for k, v in data.items()}
                return
            it = data
            if isinstance(it, (DataSet, MultiDataSet)):
                it = [it]
            for ds in it:
                feats = ds.features if isinstance(ds.features, (list, tuple)) \
                    else [ds.features]
                labs = ds.labels if isinstance(ds.labels, (list, tuple)) \
                    else [ds.labels]
                ph = {}
                for name, arr in zip(tc.data_set_feature_mapping, feats):
                    ph[name] = jnp.asarray(arr)
                for name, arr in zip(tc.data_set_label_mapping, labs):
                    ph[name] = jnp.asarray(arr)
                yield ph

        # a one-shot iterator would silently yield nothing on epochs 2..N —
        # materialize it once (reference iterators have reset(); support both)
        import collections.abc as _abc
        if (epochs > 1 and isinstance(data, _abc.Iterator)
                and not hasattr(data, "reset")):
            data = list(data)

        trainable = self.trainable_names()
        # rebuild when the graph (trainable set / loss set) or the training
        # config changes; batch-shape changes hit jax.jit's own signature
        # cache and must NOT reset optimizer state. The signature is hashed
        # once per fit() call, not per batch — the graph cannot change
        # mid-loop, and json.dumps of the config per step is measurable
        # host overhead on large imported graphs (BERT-base: ~600 values)
        # the graph-opt flag rides in the signature for the same reason it
        # rides in the output cache key: the train step is emitted through
        # the same peephole pass
        from deeplearning4j_tpu.autodiff.passes import graph_opt_enabled
        sig = (tuple(trainable), tuple(self._loss_variables),
               graph_opt_enabled(),
               json.dumps(tc.to_dict(), sort_keys=True, default=str))
        if self._train_step is None or self._train_sig != sig:
            # a placement-only rebuild (set_mesh with unchanged graph sig)
            # must NOT reset accumulated optimizer moments — only re-home
            # them onto the mesh alongside the values
            keep_state = (self._train_sig == sig
                          and self._opt_state is not None
                          and self._pending_opt_leaves is None
                          and self._pending_opt_named is None)
            if self.mesh is not None:
                self._replicate_values()
            self._train_step, fresh_state = self._build_train_step(sig)
            if keep_state:
                if self.mesh is not None:
                    from jax.sharding import NamedSharding, PartitionSpec
                    self._opt_state = jax.device_put(
                        self._opt_state,
                        NamedSharding(self.mesh, PartitionSpec()))
            else:
                self._opt_state = fresh_state
            self._train_sig = sig
        train_set = set(trainable)
        fixed_vals = {n: v for n, v in self._values.items()
                      if n not in train_set}
        for epoch in range(epochs):
            if epoch > 0 and hasattr(data, "reset"):
                data.reset()
            for ph in batches():
                if self.mesh is not None:
                    ph = self._shard_feed(ph)
                train_vals = {n: self._values[n] for n in trainable}
                train_vals, self._opt_state, loss = self._train_step(
                    train_vals, fixed_vals, self._opt_state, ph,
                    rng_seed + self.iteration_count)
                self._values.update(train_vals)
                loss = float(loss)
                losses.append(loss)
                self.iteration_count += 1
                for lst in self.listeners:
                    if hasattr(lst, "iteration_done"):
                        lst.iteration_done(self, self.iteration_count, loss)
            self.epoch_count += 1
            for lst in self.listeners:
                if hasattr(lst, "on_epoch_end"):
                    lst.on_epoch_end(self, self.epoch_count)
        # output()'s cache holds stale self._values copies only by reference —
        # values dict is passed per call, so no invalidation needed here.
        return History(losses)

    # ---- serialization -------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "format": "deeplearning4j_tpu.samediff/1",
            "variables": [
                {"name": v.name, "type": v.var_type.value,
                 "shape": list(v.shape) if v.shape is not None else None,
                 "dtype": np.dtype(v.dtype).name}
                for v in self._vars.values()],
            "ops": [op.to_dict() for op in self._ops],
            "lossVariables": self._loss_variables,
            "trainingConfig": (self.training_config.to_dict()
                               if self.training_config else None),
        }

    def _gather_values(self, prefix: str = "") -> Dict[str, np.ndarray]:
        """This graph's values plus all control-flow subgraph values,
        flattened under npz-safe prefixed keys."""
        out = {prefix + n: np.asarray(v) for n, v in self._values.items()}
        for op in self._ops:
            if op.subgraphs:
                for k, sg in op.subgraphs.items():
                    out.update(sg._gather_values(
                        f"{prefix}__sub__/{op.name}/{k}/"))
        return out

    def as_flat_buffers(self, include_updater_state: bool = False) -> bytes:
        """The graph as a reference-schema FlatGraph binary (ref:
        ``SameDiff#asFlatBuffers`` — org.nd4j.graph FlatBuffers schema).
        ``include_updater_state`` writes the per-parameter UpdaterState
        table so Adam moments survive a ``.fb`` round-trip."""
        from deeplearning4j_tpu.autodiff import flatgraph

        return flatgraph.to_flat_buffers(
            self, include_updater_state=include_updater_state)

    asFlatBuffers = as_flat_buffers

    @staticmethod
    def from_flat_buffers(data: bytes) -> "SameDiff":
        """Parse a FlatGraph binary (ref: ``SameDiff#fromFlatBuffers``)."""
        from deeplearning4j_tpu.autodiff import flatgraph

        return flatgraph.from_flat_buffers(data)

    fromFlatBuffers = from_flat_buffers

    def save(self, path: str, save_updater_state: bool = False):
        """Persist graph + values. A ``.fb``/``.fbs``/``.sdfb`` path writes
        the reference's FlatGraph binary (ref: ``SameDiff#save`` writes
        FlatBuffers; control-flow subgraphs ride as scoped node regions);
        anything else uses the native zip container. With
        ``save_updater_state=True`` BOTH formats persist the optimizer
        moments — the fb path through the UpdaterState table, the zip
        through ``updater.npz`` — so a resumed fine-tune continues
        exactly (ref: SameDiff#save includes updater state)."""
        if str(path).endswith((".fb", ".fbs", ".sdfb")):
            with open(path, "wb") as f:
                f.write(self.as_flat_buffers(
                    include_updater_state=save_updater_state))
            return
        d = self.to_dict()
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr("graph.json", json.dumps(d, indent=1))
            buf = io.BytesIO()
            np.savez(buf, **self._gather_values())
            zf.writestr("values.npz", buf.getvalue())
            if save_updater_state:
                if self._opt_state is not None:
                    leaves = [np.asarray(l)
                              for l in jax.tree.leaves(self._opt_state)]
                elif self._pending_opt_leaves is not None:
                    # loaded-but-not-refit checkpoint: re-saving must not
                    # drop the state it still carries
                    leaves = [np.asarray(l)
                              for l in self._pending_opt_leaves]
                elif self._pending_opt_named is not None:
                    # named fb-style state has no defined flat order for
                    # the zip container — write the named form instead
                    buf = io.BytesIO()
                    np.savez(buf, **{
                        f"{p}||{k}": np.asarray(v)
                        for p, kv in self._pending_opt_named.items()
                        for k, v in kv.items()})
                    zf.writestr("updater_named.npz", buf.getvalue())
                    leaves = None
                else:
                    leaves = None
                if leaves is not None:
                    buf = io.BytesIO()
                    np.savez(buf, **{f"leaf{i}": l
                                     for i, l in enumerate(leaves)})
                    zf.writestr("updater.npz", buf.getvalue())

    @staticmethod
    def load(path: str) -> "SameDiff":
        if str(path).endswith((".fb", ".fbs", ".sdfb")):
            from deeplearning4j_tpu.autodiff import flatgraph

            return flatgraph.load_flatbuffers(path)
        if not zipfile.is_zipfile(path):
            # unrecognized extension + not a zip: attempt the FlatGraph
            # binary, but convert parser noise into a diagnosable error
            # (a truncated native zip must not surface as a struct error)
            from deeplearning4j_tpu.autodiff import flatgraph

            try:
                return flatgraph.load_flatbuffers(path)
            except Exception as e:
                raise ValueError(
                    f"{path!r} is neither a SameDiff zip (corrupt or "
                    f"truncated?) nor a readable FlatGraph binary: "
                    f"{e!r}") from e
        opt_leaves = None
        opt_named = None
        with zipfile.ZipFile(path) as zf:
            d = json.loads(zf.read("graph.json"))
            with zf.open("values.npz") as f:
                values = dict(np.load(io.BytesIO(f.read())))
            if "updater.npz" in zf.namelist():
                with zf.open("updater.npz") as f:
                    raw = dict(np.load(io.BytesIO(f.read())))
                opt_leaves = [raw[f"leaf{i}"] for i in range(len(raw))]
            elif "updater_named.npz" in zf.namelist():
                with zf.open("updater_named.npz") as f:
                    raw = dict(np.load(io.BytesIO(f.read())))
                opt_named = {}
                for key, arr in raw.items():
                    pname, _, skey = key.partition("||")
                    opt_named.setdefault(pname, {})[skey] = arr
        sd = SameDiff._restore(d, values)
        sd._pending_opt_leaves = opt_leaves
        sd._pending_opt_named = opt_named
        if (opt_leaves is not None or opt_named is not None):
            upd = getattr(sd.training_config, "updater", None)
            if upd is not None:
                sd._pending_opt_updater = type(upd).__name__
        return sd

    @staticmethod
    def _restore(d: dict, values: Dict[str, np.ndarray]) -> "SameDiff":
        """Rebuild a SameDiff (or a control-flow subgraph) from its dict."""
        sd = SameDiff()
        for vd in d["variables"]:
            v = SDVariable(sd, vd["name"], VariableType(vd["type"]),
                           tuple(vd["shape"]) if vd["shape"] is not None else None,
                           np.dtype(vd["dtype"]))
            sd._vars[v.name] = v
            if v.name in values and v.var_type in (VariableType.VARIABLE,
                                                   VariableType.CONSTANT):
                sd._values[v.name] = jnp.asarray(values[v.name])
        for od in d["ops"]:
            subgraphs = None
            if od.get("subgraphs"):
                subgraphs = {}
                for k, sub_d in od["subgraphs"].items():
                    p = f"__sub__/{od['name']}/{k}/"
                    sub_vals = {n[len(p):]: v for n, v in values.items()
                                if n.startswith(p)}
                    sub = SameDiff._restore(sub_d["graph"], sub_vals)
                    sub._branch_outputs = list(sub_d["outputs"])
                    subgraphs[k] = sub
            node = OpNode(od["name"], od["op"], od["inputs"], od["outputs"],
                          od["attrs"], subgraphs=subgraphs)
            sd._ops.append(node)
            for o in node.outputs:
                sd._producer[o] = node
        sd._loss_variables = d.get("lossVariables", [])
        if d.get("trainingConfig"):
            sd.training_config = TrainingConfig.from_dict(d["trainingConfig"])
        sd._reseed_name_counters()
        return sd

    def _reseed_name_counters(self):
        """Make future ``_unique`` names skip past every loaded name —
        shared by the zip and FlatBuffers load paths."""
        for n in self._vars:
            base = n.split(":")[0].split("#")[0]
            cur = self._name_counter.get(base, 0)
            try:
                suffix = int(n.split(":")[1]) if ":" in n else 0
            except ValueError:
                suffix = 0
            self._name_counter[base] = max(cur, suffix)
