"""Updaters, schedules, listeners (ref: org.nd4j.linalg.learning, org.deeplearning4j.optimize)."""
from deeplearning4j_tpu.optim.updaters import (
    Adam, AdamW, AdaDelta, AdaGrad, AdaMax, AMSGrad, Nadam, Nesterovs, NoOp,
    RmsProp, Sgd, Updater)
from deeplearning4j_tpu.optim import schedules, listeners
from deeplearning4j_tpu.optim.solvers import (  # noqa: E402
    ConjugateGradient, LBFGS, LineGradientDescent, Solver,
    StochasticGradientDescent)
