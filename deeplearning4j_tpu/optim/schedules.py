"""Learning-rate schedules, analog of ``org.nd4j.linalg.schedule.ISchedule``
impls (MapSchedule, ExponentialSchedule, InverseSchedule, PolySchedule,
SigmoidSchedule, StepSchedule, CycleSchedule). ScheduleType ITERATION is the
native unit (a jitted step == one iteration); EPOCH schedules take
iterations_per_epoch at build time.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

import jax.numpy as jnp

_SCHEDULES = {}


def _register(cls):
    _SCHEDULES[cls.__name__.lower()] = cls
    return cls


@dataclasses.dataclass
class Schedule:
    def value_at(self, iteration):
        raise NotImplementedError

    def __call__(self, step):
        return self.value_at(step)

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["@schedule"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        cls = _SCHEDULES[d.pop("@schedule").lower()]
        return cls(**d)


@_register
@dataclasses.dataclass
class FixedSchedule(Schedule):
    value: float = 1e-3

    def value_at(self, it):
        return self.value


@_register
@dataclasses.dataclass
class MapSchedule(Schedule):
    """values[i] applies from iteration i onward (ref: MapSchedule)."""
    values: Dict[int, float] = dataclasses.field(default_factory=dict)

    def value_at(self, it):
        keys = sorted(int(k) for k in self.values)
        out = jnp.asarray(float(self.values[keys[0]] if not isinstance(next(iter(self.values)), str) else self.values[str(keys[0])]))
        vals = {int(k): float(v) for k, v in self.values.items()}
        for k in keys:
            out = jnp.where(it >= k, vals[k], out)
        return out


@_register
@dataclasses.dataclass
class ExponentialSchedule(Schedule):
    initial_value: float = 1e-3
    gamma: float = 0.99

    def value_at(self, it):
        return self.initial_value * jnp.power(self.gamma, it)


@_register
@dataclasses.dataclass
class InverseSchedule(Schedule):
    initial_value: float = 1e-3
    gamma: float = 0.01
    power: float = 1.0

    def value_at(self, it):
        return self.initial_value / jnp.power(1.0 + self.gamma * it, self.power)


@_register
@dataclasses.dataclass
class PolySchedule(Schedule):
    initial_value: float = 1e-3
    power: float = 1.0
    max_iter: int = 10000

    def value_at(self, it):
        frac = jnp.clip(it / self.max_iter, 0.0, 1.0)
        return self.initial_value * jnp.power(1.0 - frac, self.power)


@_register
@dataclasses.dataclass
class SigmoidSchedule(Schedule):
    initial_value: float = 1e-3
    gamma: float = 0.01
    step_size: int = 100

    def value_at(self, it):
        return self.initial_value / (1.0 + jnp.exp(self.gamma * (it - self.step_size)))


@_register
@dataclasses.dataclass
class StepSchedule(Schedule):
    initial_value: float = 1e-3
    decay_rate: float = 0.1
    step: int = 1000

    def value_at(self, it):
        return self.initial_value * jnp.power(self.decay_rate, jnp.floor(it / self.step))


@_register
@dataclasses.dataclass
class CosineSchedule(Schedule):
    """Warmup-free cosine decay (TPU-era addition; no reference analog)."""
    initial_value: float = 1e-3
    max_iter: int = 10000
    final_value: float = 0.0

    def value_at(self, it):
        frac = jnp.clip(it / self.max_iter, 0.0, 1.0)
        return self.final_value + 0.5 * (self.initial_value - self.final_value) * (1 + jnp.cos(math.pi * frac))


@_register
@dataclasses.dataclass
class WarmupSchedule(Schedule):
    """Linear warmup wrapping another schedule (transformer fine-tune staple)."""
    warmup_iters: int = 100
    then_value: float = 1e-3

    def value_at(self, it):
        warm = self.then_value * (it + 1) / max(1, self.warmup_iters)
        return jnp.where(it < self.warmup_iters, warm, self.then_value)


def resolve(lr) -> Schedule:
    if isinstance(lr, Schedule):
        return lr
    if isinstance(lr, dict) and "@schedule" in lr:
        return Schedule.from_dict(lr)
    return FixedSchedule(float(lr))
