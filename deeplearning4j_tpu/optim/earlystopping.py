"""Early stopping (ref: org.deeplearning4j.earlystopping.*, SURVEY D14).

``EarlyStoppingConfiguration`` + ``EarlyStoppingTrainer`` with score
calculators, epoch/iteration termination conditions, and model savers —
the same decomposition as the reference.
"""
from __future__ import annotations

import os
import time
from typing import Callable, List, Optional


# ------------------------------------------------------------ score calcs
class ScoreCalculator:
    """ref: earlystopping.scorecalc.ScoreCalculator — lower is better by
    default (minimize_score)."""

    minimize_score = True

    def calculate_score(self, network) -> float:
        raise NotImplementedError


class DataSetLossCalculator(ScoreCalculator):
    """Average loss over an iterator (ref: scorecalc.DataSetLossCalculator)."""

    def __init__(self, iterator, average: bool = True):
        self.iterator = iterator
        self.average = average

    def calculate_score(self, network) -> float:
        total, n = 0.0, 0
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        for ds in self.iterator:
            total += network.score(ds)
            n += 1
        if n == 0:
            raise ValueError("empty scoring iterator")
        return total / n if self.average else total


class ClassificationScoreCalculator(ScoreCalculator):
    """Maximize accuracy/f1 (ref: scorecalc.ClassificationScoreCalculator)."""

    minimize_score = False

    def __init__(self, iterator, metric: str = "accuracy"):
        self.iterator = iterator
        self.metric = metric

    def calculate_score(self, network) -> float:
        if hasattr(self.iterator, "reset"):
            self.iterator.reset()
        ev = network.evaluate(self.iterator)
        return float(getattr(ev, self.metric)())


# --------------------------------------------------- termination conditions
class EpochTerminationCondition:
    def terminate(self, epoch: int, score: float, minimize: bool) -> bool:
        raise NotImplementedError


class MaxEpochsTerminationCondition(EpochTerminationCondition):
    def __init__(self, max_epochs: int):
        self.max_epochs = max_epochs

    def terminate(self, epoch, score, minimize):
        return epoch >= self.max_epochs - 1


class ScoreImprovementEpochTerminationCondition(EpochTerminationCondition):
    """Stop after N epochs without (sufficient) improvement
    (ref: termination.ScoreImprovementEpochTerminationCondition)."""

    def __init__(self, max_epochs_without_improvement: int,
                 min_improvement: float = 0.0):
        self.patience = max_epochs_without_improvement
        self.min_improvement = min_improvement
        self._best: Optional[float] = None
        self._stale = 0

    def terminate(self, epoch, score, minimize):
        if self._best is None:
            self._best = score
            return False
        improved = ((self._best - score) if minimize else (score - self._best)) \
            > self.min_improvement
        if improved:
            self._best = score
            self._stale = 0
        else:
            self._stale += 1
        return self._stale >= self.patience


class BestScoreEpochTerminationCondition(EpochTerminationCondition):
    """Stop once the score is at least this good (ref: same name)."""

    def __init__(self, best_expected: float):
        self.best_expected = best_expected

    def terminate(self, epoch, score, minimize):
        return score <= self.best_expected if minimize \
            else score >= self.best_expected


class IterationTerminationCondition:
    def terminate(self, score: float) -> bool:
        raise NotImplementedError


class MaxTimeIterationTerminationCondition(IterationTerminationCondition):
    def __init__(self, max_seconds: float):
        self.max_seconds = max_seconds
        self._start = None

    def initialize(self):
        self._start = time.time()

    def terminate(self, score):
        if self._start is None:
            self.initialize()
        return time.time() - self._start > self.max_seconds


class MaxScoreIterationTerminationCondition(IterationTerminationCondition):
    """Abort when the score explodes (ref: same name)."""

    def __init__(self, max_score: float):
        self.max_score = max_score

    def terminate(self, score):
        return score > self.max_score or score != score  # NaN guard


# ------------------------------------------------------------------ savers
class InMemoryModelSaver:
    def __init__(self):
        self._best = None
        self._latest = None

    def save_best_model(self, net, score):
        self._best = net.clone()

    def save_latest_model(self, net, score):
        self._latest = net.clone()

    def get_best_model(self):
        return self._best

    def get_latest_model(self):
        return self._latest


class LocalFileModelSaver:
    """ref: earlystopping.saver.LocalFileModelSaver — bestModel.bin /
    latestModel.bin in a directory."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def _path(self, kind):
        return os.path.join(self.directory, f"{kind}Model.bin")

    def save_best_model(self, net, score):
        net.save(self._path("best"))

    def save_latest_model(self, net, score):
        net.save(self._path("latest"))

    def get_best_model(self):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer
        return ModelSerializer.restore(self._path("best"))

    def get_latest_model(self):
        from deeplearning4j_tpu.utils.serialization import ModelSerializer
        return ModelSerializer.restore(self._path("latest"))


# ------------------------------------------------------------------- config
class EarlyStoppingConfiguration:
    """ref: earlystopping.EarlyStoppingConfiguration (+ .Builder)."""

    def __init__(self, score_calculator: ScoreCalculator,
                 epoch_termination_conditions: List[EpochTerminationCondition] = (),
                 iteration_termination_conditions: List[IterationTerminationCondition] = (),
                 model_saver=None, evaluate_every_n_epochs: int = 1,
                 save_last_model: bool = False):
        self.score_calculator = score_calculator
        self.epoch_conditions = list(epoch_termination_conditions)
        self.iteration_conditions = list(iteration_termination_conditions)
        self.model_saver = model_saver or InMemoryModelSaver()
        self.evaluate_every_n_epochs = evaluate_every_n_epochs
        self.save_last_model = save_last_model

    class Builder:
        def __init__(self):
            self._kw = {"epoch_termination_conditions": [],
                        "iteration_termination_conditions": []}

        def score_calculator(self, sc):
            self._kw["score_calculator"] = sc
            return self

        scoreCalculator = score_calculator

        def epoch_termination_conditions(self, *conds):
            self._kw["epoch_termination_conditions"].extend(conds)
            return self

        epochTerminationConditions = epoch_termination_conditions

        def iteration_termination_conditions(self, *conds):
            self._kw["iteration_termination_conditions"].extend(conds)
            return self

        iterationTerminationConditions = iteration_termination_conditions

        def model_saver(self, saver):
            self._kw["model_saver"] = saver
            return self

        modelSaver = model_saver

        def evaluate_every_n_epochs(self, n):
            self._kw["evaluate_every_n_epochs"] = n
            return self

        evaluateEveryNEpochs = evaluate_every_n_epochs

        def save_last_model(self, b=True):
            self._kw["save_last_model"] = b
            return self

        def build(self):
            return EarlyStoppingConfiguration(**self._kw)


class EarlyStoppingResult:
    """ref: earlystopping.EarlyStoppingResult."""

    def __init__(self, termination_reason, termination_details, score_vs_epoch,
                 best_model_epoch, best_model_score, total_epochs, best_model):
        self.termination_reason = termination_reason
        self.termination_details = termination_details
        self.score_vs_epoch = score_vs_epoch
        self.best_model_epoch = best_model_epoch
        self.best_model_score = best_model_score
        self.total_epochs = total_epochs
        self.best_model = best_model

    def get_best_model(self):
        return self.best_model

    getBestModel = get_best_model


class EarlyStoppingTrainer:
    """Train epoch-by-epoch, score on the validation calculator, stop per
    the configured conditions (ref: trainer.EarlyStoppingTrainer)."""

    def __init__(self, config: EarlyStoppingConfiguration, network, train_data):
        self.config = config
        self.network = network
        self.train_data = train_data

    def fit(self) -> EarlyStoppingResult:
        cfg = self.config
        minimize = cfg.score_calculator.minimize_score
        best_score, best_epoch = None, -1
        scores = {}
        reason, details = "MaxEpochs", "loop exhausted"
        epoch = -1
        for cond in cfg.iteration_conditions:
            if hasattr(cond, "initialize"):
                cond.initialize()
        max_epochs = max((c.max_epochs for c in cfg.epoch_conditions
                          if isinstance(c, MaxEpochsTerminationCondition)),
                         default=10_000)
        stop = False
        for epoch in range(max_epochs):
            if hasattr(self.train_data, "reset"):
                self.train_data.reset()
            self.network.fit(self.train_data, epochs=1)
            # iteration-level conditions checked against the training score
            # (score() is a sync point: it materializes a loss the async
            # fit loop may have left on device)
            tscore = (self.network.score()
                      if callable(getattr(self.network, "score", None))
                      else getattr(self.network, "_score", float("nan")))
            for cond in cfg.iteration_conditions:
                if cond.terminate(tscore):
                    reason = "IterationTerminationCondition"
                    details = type(cond).__name__
                    stop = True
            if stop:
                break
            if (epoch + 1) % cfg.evaluate_every_n_epochs == 0:
                score = cfg.score_calculator.calculate_score(self.network)
                scores[epoch] = score
                better = (best_score is None
                          or (score < best_score if minimize
                              else score > best_score))
                if better:
                    best_score, best_epoch = score, epoch
                    cfg.model_saver.save_best_model(self.network, score)
                if cfg.save_last_model:
                    cfg.model_saver.save_latest_model(self.network, score)
                for cond in cfg.epoch_conditions:
                    if cond.terminate(epoch, score, minimize):
                        reason = ("MaxEpochs"
                                  if isinstance(cond, MaxEpochsTerminationCondition)
                                  else "EpochTerminationCondition")
                        details = type(cond).__name__
                        stop = True
                if stop:
                    break
        best = cfg.model_saver.get_best_model() or self.network
        return EarlyStoppingResult(
            termination_reason=reason, termination_details=details,
            score_vs_epoch=scores, best_model_epoch=best_epoch,
            best_model_score=best_score, total_epochs=epoch + 1,
            best_model=best)


# alias matching the reference's graph trainer
EarlyStoppingGraphTrainer = EarlyStoppingTrainer
