"""Training listener bus, analog of
``org.deeplearning4j.optimize.api.TrainingListener`` and impls
(ScoreIterationListener, PerformanceListener, EvaluativeListener,
CheckpointListener, TimeIterationListener — SURVEY §5.5).

Listeners fire at iteration granularity on the host, outside the jitted
step — the XLA-era equivalent of the reference's listener callbacks around
``Solver#optimize``.
"""
from __future__ import annotations

import logging
import time

log = logging.getLogger("deeplearning4j_tpu")


class TrainingListener:
    def iteration_done(self, model, iteration: int, epoch: int, score: float):
        pass

    def on_epoch_start(self, model, epoch: int):
        pass

    def on_epoch_end(self, model, epoch: int):
        pass

    def on_forward_pass(self, model, activations):
        pass

    def on_backward_pass(self, model):
        pass

    def on_gradient_calculation(self, model):
        pass


class ScoreIterationListener(TrainingListener):
    """Log score every N iterations (ref: ScoreIterationListener)."""

    def __init__(self, print_iterations: int = 10):
        self.print_iterations = max(1, print_iterations)

    def iteration_done(self, model, iteration, epoch, score):
        if iteration % self.print_iterations == 0:
            log.info("Score at iteration %d is %.6f", iteration, score)


class PerformanceListener(TrainingListener):
    """Examples/sec + iterations/sec (ref: PerformanceListener)."""

    def __init__(self, frequency: int = 10, report_batch: bool = True):
        self.frequency = max(1, frequency)
        self.report_batch = report_batch
        self._last_time = None
        self._last_iter = None
        self._examples = 0

    def iteration_done(self, model, iteration, epoch, score):
        now = time.perf_counter()
        batch = getattr(model, "_last_batch_size", 0)
        self._examples += batch
        if iteration % self.frequency == 0:
            if self._last_time is not None:
                dt = now - self._last_time
                iters = iteration - self._last_iter
                if dt > 0:
                    log.info("iteration %d: %.1f iters/sec, %.1f examples/sec, score=%.6f",
                             iteration, iters / dt, self._examples / dt, score)
            self._last_time = now
            self._last_iter = iteration
            self._examples = 0


class TimeIterationListener(TrainingListener):
    """ETA logging (ref: TimeIterationListener)."""

    def __init__(self, total_iterations: int):
        self.total = total_iterations
        self.start = time.perf_counter()

    def iteration_done(self, model, iteration, epoch, score):
        elapsed = time.perf_counter() - self.start
        if iteration > 0:
            remaining = elapsed / iteration * (self.total - iteration)
            log.info("iteration %d/%d, ETA %.0fs", iteration, self.total, remaining)


class EvaluativeListener(TrainingListener):
    """Periodic evaluation on a held-out iterator (ref: EvaluativeListener)."""

    def __init__(self, iterator, frequency: int = 100):
        self.iterator = iterator
        self.frequency = max(1, frequency)
        self.last_evaluation = None

    def iteration_done(self, model, iteration, epoch, score):
        if iteration > 0 and iteration % self.frequency == 0:
            self.iterator.reset()
            self.last_evaluation = model.evaluate(self.iterator)
            log.info("Evaluation at iteration %d:\n%s", iteration, self.last_evaluation.stats())


class CollectScoresListener(TrainingListener):
    """Score history in memory (ref: CollectScoresIterationListener)."""

    def __init__(self):
        self.scores = []
        self.iterations = []

    def iteration_done(self, model, iteration, epoch, score):
        self.iterations.append(iteration)
        self.scores.append(float(score))


class MetricsReportingListener(TrainingListener):
    """Bridge the TrainingListener bus into the observability registry.

    The built-in fit loops already publish the step-time decomposition;
    this listener covers everything that drives models through the
    *listener* contract instead — external training loops (arbiter
    hyperparameter search, RL), imported-graph trainers, custom solvers —
    so their iterations/scores land in the same ``/metrics`` series. An
    optional ``prefix`` namespaces a run (e.g. per arbiter candidate).
    """

    def __init__(self, prefix: str = "dl4j_listener"):
        from deeplearning4j_tpu.observability import global_registry
        reg = global_registry()
        self._iters = reg.counter(
            f"{prefix}_iterations_total",
            "iterations observed on the TrainingListener bus",
            label_names=("model",))
        self._score = reg.gauge(
            f"{prefix}_score", "last score seen on the listener bus",
            label_names=("model",))
        self._epochs = reg.counter(
            f"{prefix}_epochs_total",
            "epochs completed on the TrainingListener bus",
            label_names=("model",))
        # per model KIND, matching the label: one listener attached to
        # several models (arbiter candidates, RL actors) must not record
        # cross-model gaps as either model's iteration time
        self._last_t: dict = {}
        self._iter_seconds = reg.histogram(
            f"{prefix}_iteration_seconds",
            "wall time between consecutive iteration_done callbacks",
            label_names=("model",))
        # divergence visibility for EXTERNAL loops: the built-in fit loops
        # detect non-finite loss/grads in-graph (observability/numerics),
        # but a custom solver driving the bus only hands us its score —
        # count the non-finite ones so those runs page too
        self._nonfinite = reg.counter(
            f"{prefix}_nonfinite_scores_total",
            "non-finite scores observed on the TrainingListener bus",
            label_names=("model",))

    def iteration_done(self, model, iteration, epoch, score):
        kind = type(model).__name__
        self._iters.labels(model=kind).inc()
        if score == score and abs(score) != float("inf"):
            self._score.labels(model=kind).set(float(score))
        else:
            self._nonfinite.labels(model=kind).inc()
        now = time.perf_counter()
        last = self._last_t.get(kind)
        if last is not None:
            self._iter_seconds.labels(model=kind).observe(now - last)
        self._last_t[kind] = now

    def on_epoch_end(self, model, epoch):
        self._epochs.labels(model=type(model).__name__).inc()


class CheckpointListener(TrainingListener):
    """Periodic rotating checkpoints with a retention policy
    (ref: org.deeplearning4j.optimize.listeners.CheckpointListener, SURVEY 5.4).

    Saves ``checkpoint_<n>_<Model>.zip`` into ``directory`` every N
    iterations / epochs / minutes, keeping the last ``keep_last`` (plus every
    ``keep_every``-th) like the reference's builder options.
    """

    def __init__(self, directory, save_every_n_iterations=None,
                 save_every_n_epochs=None, save_every_n_minutes=None,
                 keep_last=3, keep_every=None):
        import os
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.every_iters = save_every_n_iterations
        self.every_epochs = save_every_n_epochs
        self.every_secs = (save_every_n_minutes * 60.0
                           if save_every_n_minutes else None)
        self.keep_last = keep_last
        self.keep_every = keep_every
        self._count = 0
        self._saved = []          # [(count, path)]
        self._last_time = None

    def _save(self, model):
        import os

        from deeplearning4j_tpu.observability import global_registry, span
        from deeplearning4j_tpu.resilience import faults as _faults
        from deeplearning4j_tpu.utils.serialization import save_model_atomic
        self._count += 1
        name = f"checkpoint_{self._count}_{type(model).__name__}.zip"
        path = os.path.join(self.directory, name)
        t0 = time.perf_counter()
        with span("checkpoint.save", path=name):
            _faults.check("checkpoint.save")
            save_model_atomic(model, path)
        reg = global_registry()
        reg.histogram("dl4j_checkpoint_save_seconds",
                      "wall time of one checkpoint save").observe(
            time.perf_counter() - t0)
        reg.counter("dl4j_checkpoints_total",
                    "checkpoints written by CheckpointListener").inc()
        try:
            reg.counter("dl4j_checkpoint_bytes_total",
                        "bytes written to checkpoint files").inc(
                os.path.getsize(path))
        except OSError:
            pass
        self._saved.append((self._count, path))
        # retention: keep last N + every keep_every-th
        removable = self._saved[:-self.keep_last] if self.keep_last else []
        for cnt, p in list(removable):
            if self.keep_every and cnt % self.keep_every == 0:
                continue
            if os.path.exists(p):
                os.remove(p)
            self._saved.remove((cnt, p))

    def iteration_done(self, model, iteration, epoch, score):
        import time
        if self.every_iters and iteration > 0 and \
                iteration % self.every_iters == 0:
            self._save(model)
        if self.every_secs is not None:
            now = time.time()
            if self._last_time is None:
                self._last_time = now
            elif now - self._last_time >= self.every_secs:
                self._save(model)
                self._last_time = now

    def on_epoch_end(self, model, epoch):
        if self.every_epochs and (epoch + 1) % self.every_epochs == 0:
            self._save(model)

    def last_checkpoint(self):
        return self._saved[-1][1] if self._saved else None

    def available_checkpoints(self):
        return [p for _, p in self._saved]
