"""Solver + convex-style optimizers (ref:
``org.deeplearning4j.optimize.Solver`` and
``org.deeplearning4j.optimize.solvers.{BaseOptimizer,
StochasticGradientDescent,LineGradientDescent,ConjugateGradient,LBFGS}`` —
SURVEY D5).

Reference semantics: the Solver wraps an optimizer that calls
``computeGradientAndScore`` and applies updates; SGD is the practical path,
while line-search/CG/LBFGS iterate on the single FLAT param vector. TPU-first
mapping: SGD delegates to the net's donated-buffer jitted step (stack 3.1 is
already one XLA program); the second-order optimizers run their direction/
line-search logic on the flat vector on the host, with each score/gradient
evaluation a jitted device call — the same host/device split the reference
has (Java logic over native evals).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn import params as _flat


def _score_and_flat_grad(net, x, y):
    score, grads = net.computeGradientAndScore(x, y)
    return score, np.asarray(_flat.flatten_params(grads))


def _set_flat(net, vec: np.ndarray):
    net._params = _flat.unflatten_params(jnp.asarray(vec, jnp.float32),
                                         net._param_shapes)


def _get_flat(net) -> np.ndarray:
    return np.asarray(_flat.flatten_params(net._params))


class BaseOptimizer:
    """ref: solvers.BaseOptimizer — iteration loop + listener dispatch."""

    def __init__(self, net, max_iterations: int = 10):
        self.net = net
        self.max_iterations = max_iterations

    def optimize(self, x, y) -> bool:
        raise NotImplementedError

    def _iteration_done(self, score):
        net = self.net
        # drop any deferred device-side loss a prior async fit left behind —
        # a later score() must not overwrite this fresh value with it
        net._pending_score = None
        net._score = float(score)
        net._iteration += 1
        for lst in net._listeners:
            lst.iteration_done(net, net._iteration, net._epoch, net._score)


class StochasticGradientDescent(BaseOptimizer):
    """ref: solvers.StochasticGradientDescent — one updater step per call;
    delegates to the net's jitted train step (fwd+bwd+updater fused)."""

    def optimize(self, x, y) -> bool:
        self.net._fit_batch(x, y)
        return True


def _backtracking_line_search(net, x, y, p, f0, g0, alpha0=1.0, c1=1e-4,
                              shrink=0.5, max_steps=20):
    """Armijo backtracking along direction p from the current params (ref:
    solvers.BackTrackLineSearch)."""
    theta0 = _get_flat(net)
    slope = float(g0 @ p)
    alpha = alpha0
    for _ in range(max_steps):
        _set_flat(net, theta0 + alpha * p)
        score, _ = net.computeGradientAndScore(x, y)
        if score <= f0 + c1 * alpha * slope:
            return alpha, score
        alpha *= shrink
    _set_flat(net, theta0)     # no acceptable step
    return 0.0, f0


class LineGradientDescent(BaseOptimizer):
    """ref: solvers.LineGradientDescent — steepest descent + line search."""

    def optimize(self, x, y) -> bool:
        for _ in range(self.max_iterations):
            f0, g = _score_and_flat_grad(self.net, x, y)
            p = -g
            alpha, score = _backtracking_line_search(self.net, x, y, p, f0, g)
            if alpha == 0.0:
                self._iteration_done(f0)
                return False
            self._iteration_done(score)
        return True


class ConjugateGradient(BaseOptimizer):
    """ref: solvers.ConjugateGradient — Polak-Ribière nonlinear CG with
    automatic restart when the direction loses descent."""

    def optimize(self, x, y) -> bool:
        f0, g = _score_and_flat_grad(self.net, x, y)
        p = -g
        for _ in range(self.max_iterations):
            if float(g @ p) >= 0:      # not a descent direction → restart
                p = -g
            alpha, score = _backtracking_line_search(self.net, x, y, p, f0, g)
            if alpha == 0.0:
                self._iteration_done(f0)
                return False
            f1, g_new = _score_and_flat_grad(self.net, x, y)
            beta = max(0.0, float(g_new @ (g_new - g)) /
                       max(float(g @ g), 1e-12))   # PR+
            p = -g_new + beta * p
            g, f0 = g_new, f1
            self._iteration_done(score)
        return True


class LBFGS(BaseOptimizer):
    """ref: solvers.LBFGS — limited-memory BFGS (two-loop recursion, history
    ``m``) with Armijo line search on the flat vector."""

    def __init__(self, net, max_iterations: int = 10, m: int = 10):
        super().__init__(net, max_iterations)
        self.m = m

    def optimize(self, x, y) -> bool:
        s_hist, y_hist = [], []
        f0, g = _score_and_flat_grad(self.net, x, y)
        theta = _get_flat(self.net)
        for _ in range(self.max_iterations):
            # two-loop recursion
            q = g.copy()
            alphas = []
            for s, yv in reversed(list(zip(s_hist, y_hist))):
                rho = 1.0 / max(float(yv @ s), 1e-12)
                a = rho * float(s @ q)
                alphas.append((a, rho, s, yv))
                q = q - a * yv
            if y_hist:
                s, yv = s_hist[-1], y_hist[-1]
                q = q * (float(s @ yv) / max(float(yv @ yv), 1e-12))
            for a, rho, s, yv in reversed(alphas):
                b = rho * float(yv @ q)
                q = q + (a - b) * s
            p = -q
            alpha, score = _backtracking_line_search(self.net, x, y, p, f0, g)
            if alpha == 0.0:
                self._iteration_done(f0)
                return False
            theta_new = _get_flat(self.net)
            f1, g_new = _score_and_flat_grad(self.net, x, y)
            s_hist.append(theta_new - theta)
            y_hist.append(g_new - g)
            if len(s_hist) > self.m:
                s_hist.pop(0)
                y_hist.pop(0)
            theta, g, f0 = theta_new, g_new, f1
            self._iteration_done(score)
        return True


_ALGOS = {
    "sgd": StochasticGradientDescent,
    "stochastic_gradient_descent": StochasticGradientDescent,
    "line_gradient_descent": LineGradientDescent,
    "conjugate_gradient": ConjugateGradient,
    "lbfgs": LBFGS,
}


class Solver:
    """ref: org.deeplearning4j.optimize.Solver (+ .Builder): chooses the
    optimization algorithm and drives it."""

    def __init__(self, net, algorithm: str = "sgd",
                 max_iterations: int = 10):
        cls = _ALGOS[algorithm.lower()]
        self.optimizer = cls(net, max_iterations=max_iterations)

    def optimize(self, x, y) -> bool:
        return self.optimizer.optimize(x, y)

    class Builder:
        def __init__(self):
            self._net = None
            self._algo = "sgd"
            self._iters = 10

        def model(self, net):
            self._net = net
            return self

        def configure(self, algorithm: str):
            self._algo = algorithm
            return self

        def max_iterations(self, n: int):
            self._iters = n
            return self

        def build(self) -> "Solver":
            return Solver(self._net, self._algo, self._iters)
