"""Updaters (optimizers), analog of ``org.nd4j.linalg.learning.config.IUpdater``
(Sgd, Adam, AdaMax, Nadam, AMSGrad, Nesterovs, RMSProp, AdaGrad, AdaDelta,
NoOp) and their stateful ``GradientUpdater`` twins.

TPU-first redesign: each updater is a declarative config that lowers to an
optax GradientTransformation — the "stateful updater mutating a flat state
view" (ref: BaseMultiLayerUpdater/UpdaterBlock, SURVEY D6/3.2) becomes
optimizer state as a pytree carried through the jitted train step. The flat
state view survives as a *logical* contract via nn.params.FlatParams.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import optax

from deeplearning4j_tpu.optim import schedules as _sched

_UPDATERS = {}


def _register(cls):
    _UPDATERS[cls.__name__.lower()] = cls
    return cls


@dataclasses.dataclass
class Updater:
    learning_rate: object = 1e-3

    def lr_schedule(self):
        sched = _sched.resolve(self.learning_rate)
        return lambda step: sched.value_at(step)

    def to_optax(self) -> optax.GradientTransformation:
        raise NotImplementedError

    def to_dict(self):
        d = dataclasses.asdict(self)
        if isinstance(self.learning_rate, _sched.Schedule):
            d["learning_rate"] = self.learning_rate.to_dict()
        d["@updater"] = type(self).__name__
        return d

    @staticmethod
    def from_dict(d):
        d = dict(d)
        cls = _UPDATERS[d.pop("@updater").lower()]
        if isinstance(d.get("learning_rate"), dict):
            d["learning_rate"] = _sched.Schedule.from_dict(d["learning_rate"])
        return cls(**d)


@_register
@dataclasses.dataclass
class Sgd(Updater):
    learning_rate: object = 0.1

    def to_optax(self):
        return optax.sgd(self.lr_schedule())


@_register
@dataclasses.dataclass
class Nesterovs(Updater):
    learning_rate: object = 0.1
    momentum: float = 0.9

    def to_optax(self):
        return optax.sgd(self.lr_schedule(), momentum=self.momentum, nesterov=True)


@_register
@dataclasses.dataclass
class Adam(Updater):
    learning_rate: object = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adam(self.lr_schedule(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@_register
@dataclasses.dataclass
class AdamW(Updater):
    learning_rate: object = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 0.01

    def to_optax(self):
        return optax.adamw(self.lr_schedule(), b1=self.beta1, b2=self.beta2,
                           eps=self.epsilon, weight_decay=self.weight_decay)


@_register
@dataclasses.dataclass
class AdaMax(Updater):
    learning_rate: object = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adamax(self.lr_schedule(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@_register
@dataclasses.dataclass
class Nadam(Updater):
    learning_rate: object = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.nadam(self.lr_schedule(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@_register
@dataclasses.dataclass
class AMSGrad(Updater):
    learning_rate: object = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.amsgrad(self.lr_schedule(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@_register
@dataclasses.dataclass
class RmsProp(Updater):
    learning_rate: object = 1e-3
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.rmsprop(self.lr_schedule(), decay=self.rms_decay, eps=self.epsilon)


@_register
@dataclasses.dataclass
class AdaGrad(Updater):
    learning_rate: object = 1e-1
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adagrad(self.lr_schedule(), eps=self.epsilon)


@_register
@dataclasses.dataclass
class AdaDelta(Updater):
    learning_rate: object = 1.0  # unused by the rule itself (ref parity)
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adadelta(rho=self.rho, eps=self.epsilon)


@_register
@dataclasses.dataclass
class NoOp(Updater):
    learning_rate: object = 0.0

    def to_optax(self):
        return optax.set_to_zero()


def resolve(u) -> Updater:
    if isinstance(u, Updater):
        return u
    if isinstance(u, dict) and "@updater" in u:
        return Updater.from_dict(u)
    raise TypeError(f"Cannot resolve updater from {u!r}")
