"""Typed serving-control-plane errors.

The data plane speaks the resilience taxonomy (ShedError /
DeadlineExceeded / CircuitOpenError...); the rollout control plane used
to raise bare ``RuntimeError`` for lifecycle refusals, which graftlint's
typed-errors rule now forbids in ``serving/`` — callers (the front
door's admin routes, drills, operators' scripts) need to distinguish "a
rollout is already active" from a real failure.  Subclassing
``RuntimeError`` keeps every pre-existing ``except RuntimeError`` /
``pytest.raises(RuntimeError)`` caller working unchanged.

Dependency-free on purpose: both ``router`` (jax-adjacent) and
``shared_state`` (stdlib-only, multi-process) import it.
"""
from __future__ import annotations


class RolloutConflictError(RuntimeError):
    """A rollout lifecycle request was refused because of current state
    (one already active, rollouts disabled, candidate not live, lane
    has no primary) — retryable after the state changes; maps to HTTP
    409 on the front door."""


class StoreLockTimeout(RuntimeError):
    """The shared-store file lock could not be acquired within the
    bounded wait — a writer crashed or was paused (SIGSTOP) INSIDE its
    critical section.  Typed so the sync loop treats it like any other
    transient store failure (window counters merge back, the next beat
    retries) instead of the whole fleet wedging forever on ``flock``."""
