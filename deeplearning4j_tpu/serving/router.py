"""ServingRouter: the versioned ``output()`` front-end.

Composes the registry's versions with the PR-5 policies that already
live inside each ``ParallelInference`` (per-version deadlines, bounded
queues/shedding, a per-version circuit breaker) and adds the rollout
split on top:

- traffic is split **deterministically by request hash** — the same
  request (or explicit ``request_key``) always lands on the same
  version, so a client retry during a rollout cannot flap between
  models;
- the candidate path fires the ``serving.canary`` chaos point, so a
  rollout can be rehearsed under injected latency/error faults and the
  SLO gate proven to roll back;
- every routed request lands in the ``dl4j_serving_version_*`` series
  the rollout grader reads.

Kill switch ``DL4J_TPU_ROLLOUT=0`` (resolved at construction, like the
other hot-path switches): ``output()`` is a byte-identical passthrough
to the primary version's ``ParallelInference.output`` — no hashing, no
extra series, no fault point — and ``begin_rollout`` refuses.
"""
from __future__ import annotations

import hashlib
import os
import threading
import time
import weakref
from typing import Optional

import numpy as np

from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.policy import (TYPED_OUTCOMES,
                                                  ShutdownError)
from deeplearning4j_tpu.serving.errors import RolloutConflictError
from deeplearning4j_tpu.serving.metrics import serving_metrics
from deeplearning4j_tpu.serving.rollout import (CanaryRollout, RolloutPolicy,
                                                RolloutState)

#: excluded from the per-version error counters — THE shared tuple from
#: resilience.policy, so this surface cannot diverge from
#: dl4j_inference_errors_total (typed outcomes are routing results, not
#: model failures; InjectedFault and real device errors DO count)
_TYPED_OUTCOMES = TYPED_OUTCOMES


def rollout_enabled() -> bool:
    """``DL4J_TPU_ROLLOUT`` kill switch (``0`` = single-version
    passthrough, byte-identical to direct ``ParallelInference`` use)."""
    return os.environ.get("DL4J_TPU_ROLLOUT", "1") != "0"


def request_fraction(x, request_key=None) -> float:
    """Deterministic [0, 1) routing coordinate for one request: the hash
    of ``request_key`` when given, else of the request payload (a bounded
    prefix of the bytes + shape/dtype — enough that distinct requests
    spread uniformly while the same request always routes the same
    way)."""
    if request_key is not None:
        data = repr(request_key).encode()
    else:
        arr = np.asarray(x)
        data = (arr.tobytes()[:4096] + str(arr.shape).encode()
                + str(arr.dtype).encode())
    digest = hashlib.blake2b(data, digest_size=8).digest()
    return int.from_bytes(digest, "big") / 2.0 ** 64


class ServingRouter:
    """Routes ``output()`` across a registry's versions; owns at most
    one active :class:`CanaryRollout` at a time."""

    _live: "weakref.WeakSet[ServingRouter]" = weakref.WeakSet()

    def __init__(self, registry, primary: str):
        self._registry = registry
        self._primary = registry.get(primary)
        self._enabled = rollout_enabled()
        self._rollout: Optional[CanaryRollout] = None
        self._lock = threading.Lock()
        ServingRouter._live.add(self)
        if self._enabled:
            serving_metrics().traffic(self._primary.version).set(1.0)

    @property
    def primary(self):
        return self._primary

    @property
    def rollout(self) -> Optional[CanaryRollout]:
        return self._rollout

    # ------------------------------------------------------------ rollout
    def begin_rollout(self, candidate: str,
                      policy: Optional[RolloutPolicy] = None) -> CanaryRollout:
        """Start canarying ``candidate`` against the current primary."""
        if not self._enabled:
            raise RolloutConflictError(
                "rollouts are disabled (DL4J_TPU_ROLLOUT=0): deploy/retire "
                "still work, but traffic stays on the primary version")
        with self._lock:
            if self._rollout is not None and self._rollout.active:
                raise RolloutConflictError(
                    f"a rollout of {self._rollout.candidate.version!r} is "
                    "already active")
            cand = self._registry.get(candidate)
            if cand is self._primary:
                raise ValueError("candidate is already the primary")
            if cand.kind != self._primary.kind:
                # a mis-kinded rollout would fail every canary-routed
                # request with a wiring error the SLO gate never sees
                # (raised before the per-version accounting) — refuse at
                # the door instead
                raise ValueError(
                    f"candidate {candidate!r} is a {cand.kind} deploy "
                    f"but the primary {self._primary.version!r} is "
                    f"{self._primary.kind} — rollouts must not change "
                    "the serving surface")
            if not cand.admitting:
                raise RolloutConflictError(
                    f"candidate {candidate!r} is not live "
                    f"(state={cand.state})")
            self._rollout = CanaryRollout(self, self._registry,
                                          self._primary, cand,
                                          policy or RolloutPolicy())
            return self._rollout

    def _promote(self, rollout: CanaryRollout):
        """Rollout hit FULL: the candidate becomes primary and the old
        incumbent drains gracefully (in-flight requests complete)."""
        old, self._primary = self._primary, rollout.candidate
        old.drain(timeout_s=rollout.policy.drain_timeout_s)

    # ------------------------------------------------------------- output
    def output(self, x, deadline_ms: Optional[float] = None,
               request_key=None, tenant=None) -> np.ndarray:
        if not self._enabled:
            # kill switch: byte-identical single-version passthrough.
            # A kind mismatch is a wiring error (ValueError); a scoring
            # primary whose pi is gone was DRAINED — that is the typed
            # lifecycle outcome, same as _serve raises
            if self._primary.kind != "scoring":
                raise ValueError(
                    f"version {self._primary.version!r} is a "
                    f"{self._primary.kind} deploy — output() needs a "
                    "scoring deploy")
            if self._primary.pi is None:
                raise ShutdownError(
                    f"version {self._primary.version!r} is not admitting "
                    f"(state={self._primary.state})")
            return self._primary.pi.output(x, deadline_ms=deadline_ms,
                                           tenant=tenant)
        rollout = self._rollout
        if rollout is None or not rollout.active:
            return self._serve(self._primary, x, deadline_ms,
                               tenant=tenant)
        # time-mode rollouts grade on EVERY routed request, not only
        # candidate-involved ones — a low-traffic candidate must not
        # stall its own evaluation clock
        rollout.maybe_timed_evaluate()
        frac = request_fraction(x, request_key)
        candidate = rollout.candidate
        if (rollout.share > 0.0 and frac < rollout.share
                and candidate.admitting):
            try:
                return self._serve(candidate, x, deadline_ms, canary=True,
                                   tenant=tenant)
            finally:
                rollout.record_candidate_event()
        out = self._serve(self._primary, x, deadline_ms, tenant=tenant)
        if (rollout.stage == RolloutState.SHADOW and candidate.admitting
                and frac < rollout.policy.shadow_fraction):
            try:
                self._shadow_score(rollout, x, out)
            finally:
                rollout.record_candidate_event()
        return out

    # ----------------------------------------------------------- generate
    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 eos_id: Optional[int] = None,
                 deadline_ms: Optional[float] = None,
                 request_key=None, on_token=None,
                 tenant=None, session_id: Optional[str] = None) -> np.ndarray:
        """Route one generation request across the registry's
        GENERATIVE versions — same deterministic hash split, per-version
        series, canary chaos point, and SLO-graded rollout as
        :meth:`output`; shadow scoring compares the full emitted token
        sequence (any mismatch is a divergence — sampled decode shadows
        should pin greedy or share the engine seed). ``on_token``
        streams per-token at step boundaries (the HTTP/SSE surface) —
        threaded to whichever version the hash split serves; shadow
        generations never stream."""
        if not self._enabled:
            # same split as output(): kind mismatch = ValueError, a
            # drained generative primary = typed ShutdownError
            if self._primary.kind != "generative":
                raise ValueError(
                    f"version {self._primary.version!r} is a "
                    f"{self._primary.kind} deploy — generate() needs a "
                    "deploy_generative version")
            gp = self._primary.gp
            if gp is None:
                raise ShutdownError(
                    f"version {self._primary.version!r} is not admitting "
                    f"generation (state={self._primary.state})")
            return gp.generate(
                prompt, max_new_tokens=max_new_tokens, eos_id=eos_id,
                deadline_ms=deadline_ms, on_token=on_token, tenant=tenant,
                session_id=session_id,
                session_version=self._primary.version)
        rollout = self._rollout
        if rollout is None or not rollout.active:
            return self._serve_gen(self._primary, prompt, max_new_tokens,
                                   eos_id, deadline_ms, on_token=on_token,
                                   tenant=tenant, session_id=session_id)
        rollout.maybe_timed_evaluate()
        frac = request_fraction(prompt, request_key)
        candidate = rollout.candidate
        if (rollout.share > 0.0 and frac < rollout.share
                and candidate.admitting):
            try:
                return self._serve_gen(candidate, prompt, max_new_tokens,
                                       eos_id, deadline_ms, canary=True,
                                       on_token=on_token, tenant=tenant,
                                       session_id=session_id)
            finally:
                rollout.record_candidate_event()
        out = self._serve_gen(self._primary, prompt, max_new_tokens,
                              eos_id, deadline_ms, on_token=on_token,
                              tenant=tenant, session_id=session_id)
        if (rollout.stage == RolloutState.SHADOW and candidate.admitting
                and frac < rollout.policy.shadow_fraction):
            # shadow work must never affect the user's response — and a
            # full multi-token shadow GENERATION is seconds, not the one
            # extra forward the scoring shadow costs. Run it off-path;
            # the candidate event records when the shadow resolves, so
            # windows grade against metrics that exist.
            def _shadow(prompt=prompt, out=out):
                try:
                    self._shadow_generate(rollout, prompt, max_new_tokens,
                                          eos_id, out)
                finally:
                    rollout.record_candidate_event()

            threading.Thread(target=_shadow, daemon=True,
                             name="dl4j-shadow-generate").start()
        return out

    def _serve_gen(self, dv, prompt, max_new_tokens, eos_id, deadline_ms,
                   canary: bool = False, on_token=None,
                   tenant=None, session_id=None) -> np.ndarray:
        if dv.kind != "generative":
            # a wiring error, not a lifecycle state — never typed
            raise ValueError(
                f"version {dv.version!r} is a {dv.kind} deploy — "
                "generate() needs a deploy_generative version")
        gp = dv.gp
        if not dv.admitting or gp is None:
            raise ShutdownError(
                f"version {dv.version!r} is not admitting generation "
                f"(state={dv.state})")
        t0 = time.perf_counter()
        try:
            with dv.track():
                if canary and _faults.armed():
                    _faults.check("serving.canary")
                out = gp.generate(prompt, max_new_tokens=max_new_tokens,
                                  eos_id=eos_id, deadline_ms=deadline_ms,
                                  on_token=on_token, tenant=tenant,
                                  session_id=session_id,
                                  session_version=dv.version)
        except Exception as e:
            self._account(dv, t0, error=e)
            raise
        self._account(dv, t0)
        return out

    def _shadow_generate(self, rollout: CanaryRollout, prompt,
                         max_new_tokens, eos_id, incumbent_out):
        """Shadow-score one generation on the candidate (absorbed
        errors, exact-sequence divergence)."""
        dv = rollout.candidate
        obs = serving_metrics()
        gp = dv.gp
        if gp is None:
            return
        t0 = time.perf_counter()
        try:
            with dv.track():
                if _faults.armed():
                    _faults.check("serving.canary")
                out = gp.generate(prompt, max_new_tokens=max_new_tokens,
                                  eos_id=eos_id)
        # graftlint: disable=typed-errors — shadow traffic: a candidate
        # failure is SCORED (error counted per version), never allowed
        # to touch the incumbent's already-delivered response
        except Exception as e:
            self._account(dv, t0, error=e)
            obs.shadow(dv.version, "error").inc()
            return
        self._account(dv, t0)
        match = bool(np.array_equal(np.asarray(out),
                                    np.asarray(incumbent_out)))
        obs.shadow(dv.version, "match" if match else "diverged").inc()

    @staticmethod
    def _account(dv, t0: float, error: Optional[BaseException] = None):
        """One routed request's per-version accounting (success and
        every error path share it): latency + requests always, errors
        only for non-typed failures."""
        obs = serving_metrics()
        obs.latency(dv.version).observe(time.perf_counter() - t0)
        obs.requests(dv.version).inc()
        if error is not None and not isinstance(error, _TYPED_OUTCOMES):
            obs.errors(dv.version).inc()

    def _serve(self, dv, x, deadline_ms, canary: bool = False,
               tenant=None) -> np.ndarray:
        if dv.kind == "generative":
            raise ValueError(
                f"version {dv.version!r} is a generative deploy — use "
                "generate(), not output()")
        # capture the pipeline BEFORE tracking: a concurrent drain nulls
        # dv.pi after its in-flight wait — a request racing that window
        # must land on the (shut down) instance and resolve typed, not
        # explode on None
        pi = dv.pi
        if not dv.admitting or pi is None:
            raise ShutdownError(
                f"version {dv.version!r} is not admitting "
                f"(state={dv.state})")
        t0 = time.perf_counter()
        try:
            with dv.track():
                if canary and _faults.armed():
                    # the canary chaos point: latency faults stretch the
                    # measured canary latency, error faults feed its
                    # error rate — exactly what the SLO gate grades
                    _faults.check("serving.canary")
                out = pi.output(x, deadline_ms=deadline_ms,
                                tenant=tenant)
        except Exception as e:
            self._account(dv, t0, error=e)
            raise
        self._account(dv, t0)
        return out

    def _shadow_score(self, rollout: CanaryRollout, x, incumbent_out):
        """Score the same request on the candidate and compare outputs.
        Shadow work must never affect the user's response: errors are
        absorbed into the candidate's series, not raised."""
        dv = rollout.candidate
        obs = serving_metrics()
        pi = dv.pi
        if pi is None:
            return
        t0 = time.perf_counter()
        try:
            with dv.track():
                if _faults.armed():
                    _faults.check("serving.canary")
                out = pi.output(x)
        # graftlint: disable=typed-errors — shadow traffic: a candidate
        # failure is SCORED (error counted per version), never allowed
        # to touch the incumbent's already-delivered response
        except Exception as e:
            self._account(dv, t0, error=e)
            obs.shadow(dv.version, "error").inc()
            return
        self._account(dv, t0)
        policy = rollout.policy
        try:
            match = bool(np.allclose(np.asarray(out),
                                     np.asarray(incumbent_out),
                                     rtol=policy.divergence_rtol,
                                     atol=policy.divergence_atol))
        except Exception:  # graftlint: disable=typed-errors — comparison
            match = False  # failure (shape mismatch) IS a divergence score
        obs.shadow(dv.version, "match" if match else "diverged").inc()

    # ----------------------------------------------- shared-store serving
    # The multi-process front door routes by the SHARED store's stage and
    # share (every worker must agree on the split), then serves the
    # chosen version through these — the same per-version accounting,
    # drain tracking, and canary chaos point as the local rollout path,
    # without the local CanaryRollout state machine (the store's leader
    # grades the fleet-aggregated windows instead).

    def output_on(self, version: str, x,
                  deadline_ms: Optional[float] = None,
                  canary: bool = False, tenant=None) -> np.ndarray:
        """Serve one scoring request on the NAMED version."""
        return self._serve(self._registry.get(version), x, deadline_ms,
                           canary=canary, tenant=tenant)

    def generate_on(self, version: str, prompt,
                    max_new_tokens: Optional[int] = None,
                    eos_id: Optional[int] = None,
                    deadline_ms: Optional[float] = None,
                    canary: bool = False, on_token=None,
                    tenant=None,
                    session_id: Optional[str] = None) -> np.ndarray:
        """Serve one generation request on the NAMED version."""
        return self._serve_gen(self._registry.get(version), prompt,
                               max_new_tokens, eos_id, deadline_ms,
                               canary=canary, on_token=on_token,
                               tenant=tenant, session_id=session_id)

    def resume_on(self, version: str, record: dict, on_token=None,
                  deadline_ms: Optional[float] = None, tenant=None,
                  session=None) -> np.ndarray:
        """Resume an ADOPTED session record on the NAMED version
        (fleet failover: the dead worker's journal, this worker's
        slots) — the same per-version accounting and drain tracking as
        :meth:`generate_on`, entering the pipeline through
        ``GenerationPipeline.resume``."""
        dv = self._registry.get(version)
        if dv.kind != "generative":
            raise ValueError(
                f"version {dv.version!r} is a {dv.kind} deploy — "
                "resume_on() needs a deploy_generative version")
        gp = dv.gp
        if not dv.admitting or gp is None:
            raise ShutdownError(
                f"version {dv.version!r} is not admitting generation "
                f"(state={dv.state})")
        t0 = time.perf_counter()
        try:
            with dv.track():
                out = gp.resume(record, on_token=on_token,
                                deadline_ms=deadline_ms, tenant=tenant,
                                session=session)
        except Exception as e:
            self._account(dv, t0, error=e)
            raise
        self._account(dv, t0)
        return out

    def repoint(self, version: str):
        """Re-point the primary at ``version`` (shared-store promotion:
        the store's leader declared FULL; this worker adopts it and the
        caller drains the old incumbent). Refuses a non-admitting or
        mis-kinded target — the same wiring guards begin_rollout makes."""
        with self._lock:
            dv = self._registry.get(version)
            if dv is self._primary:
                return
            if dv.kind != self._primary.kind:
                raise ValueError(
                    f"version {version!r} is a {dv.kind} deploy but the "
                    f"primary {self._primary.version!r} is "
                    f"{self._primary.kind} — repoint must not change the "
                    "serving surface")
            if not dv.admitting:
                raise ShutdownError(
                    f"version {version!r} is not admitting "
                    f"(state={dv.state})")
            self._primary = dv
            if self._enabled:
                serving_metrics().traffic(dv.version).set(1.0)

    # ------------------------------------------------------------ queries
    def snapshot(self) -> dict:
        rollout = self._rollout
        return {
            "enabled": self._enabled,
            "primary": self._primary.version,
            "primary_state": self._primary.state,
            "rollout": rollout.snapshot() if rollout is not None else None,
        }
