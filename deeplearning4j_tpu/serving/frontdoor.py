"""HTTP/SSE serving front door: the network wire surface on the router.

The serving stack ended at Python call boundaries — ``ServingRouter``
had no wire surface at all, which is exactly the gap between "a serving
library" and "a server" (the reference DL4J shipped its Vert.x HTTP
serving/UI layer as a first-class product; the TensorFlow system paper,
Abadi et al. arXiv:1605.08695 §9, treats the network serving tier as
part of the system). :class:`FrontDoor` is that tier, built on the same
dependency-free ``ThreadingHTTPServer`` pattern as ``ui/server.py`` but
hardened as a traffic surface:

- ``POST /v1/classify`` — JSON in/out through the router's versioned
  ``output()`` (hash-split rollout, per-version SLOs, drains — all of
  PR 9 behind one URL).
- ``POST /v1/generate`` — KV-cache generation; with ``"stream": true``
  the response is **server-sent events, one event per token**, emitted
  at the decode step boundary that produced each token (the
  ``on_token`` plumbing through router → pipeline → decode loop). The
  streamed sequence is byte-identical to the non-streamed result for
  the same seed/version; a client that disconnects mid-stream cancels
  its request at the next step boundary — the slot frees, typed as
  ``StreamCancelled``, never leaked.
- **Typed errors map to HTTP statuses**: shed/admission → 429, circuit
  open / shutdown / disabled → 503, deadline → 504, wiring errors →
  400, unknown version → 404, everything else → 500. Per-request
  deadlines ride the body (``deadline_ms``) into the same
  ``Deadline`` machinery the in-process callers use.
- Every response carries the request's causal ``X-Dl4j-Trace-Id``
  header, so a slow HTTP request can be joined against ``/train/trace``
  spans and flight-recorder bundles.
- Admission control: a bounded in-flight gate (``max_inflight``) sheds
  with 429 before a traffic spike can pile threads onto the device
  queues; the ``http.request`` chaos point fires at the door so the
  whole surface is drivable under injected faults.
- **Multi-tenant QoS** (``resilience/qos.py``; kill switch
  ``DL4J_TPU_QOS=0``): the ``X-Dl4j-Tenant`` header names the caller
  (absent = default tenant, behavior unchanged); per-tenant request-
  rate / token-rate quotas are enforced AT the door (typed
  ``QuotaExceeded`` → 429) and the label threads through the router
  into both pipelines' weighted-fair queues. Every 429/503 shed
  response carries a ``Retry-After`` header — quota sheds derive it
  from the tenant's bucket refill time; the in-flight gate and other
  sheds reuse the same surface with a default. ``GET /debug/tenants``
  serves the live tenant table.
- **Multi-process mode**: constructed with a
  :class:`~deeplearning4j_tpu.serving.shared_state.SharedServingState`,
  routing decisions (primary, canary split, stage) come from the shared
  store — N worker processes answer as one fleet — and a background
  sync thread heartbeats, publishes SLO windows, and applies the
  leader's stage transitions (promote/drain) locally.

Observability: ``dl4j_http_*`` series on ``/metrics``, a ``/debug/
frontdoor`` endpoint (also folded into flight-recorder bundles as
``frontdoor.json`` and mirrored by the UI server), and the live kill
switch ``DL4J_TPU_FRONTDOOR=0`` (resolved per request) that answers 503
on ``/v1/*`` while keeping the debug surfaces up — the "drain this
replica at the load balancer" lever.
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from deeplearning4j_tpu.observability import current_span as _current_span
from deeplearning4j_tpu.observability import federation as _fed
from deeplearning4j_tpu.observability import global_registry, on_registry_reset
from deeplearning4j_tpu.observability import span as _span
from deeplearning4j_tpu.observability import timeseries as _tms
from deeplearning4j_tpu.observability import trace_store as _trace_store
from deeplearning4j_tpu.observability import watchtower as _watchtower
from deeplearning4j_tpu.observability.tracing import (current_context,
                                                      trace_context)
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import qos as _qos
from deeplearning4j_tpu.resilience.policy import (TYPED_OUTCOMES,
                                                  CircuitOpenError,
                                                  DeadlineExceeded,
                                                  RetryPolicy, ShedError,
                                                  ShutdownError)
from deeplearning4j_tpu.serving import idempotency as _idem
from deeplearning4j_tpu.serving import session as _sess
from deeplearning4j_tpu.serving.errors import RolloutConflictError
from deeplearning4j_tpu.serving.router import request_fraction
# ONE bind-host knob for both HTTP surfaces (the UI server owns the
# spelling) — the two servers must never drift on what the knob means
from deeplearning4j_tpu.ui.server import default_bind_host  # noqa: F401

#: request bodies above this are refused with 413 BEFORE buffering — a
#: hardened door must not let one Content-Length header OOM the process
MAX_BODY_BYTES = 16 << 20

#: the tenant-identity request header (QoS posture; absent = default
#: tenant, behavior unchanged)
TENANT_HEADER = "X-Dl4j-Tenant"

#: the durable-session id header (sessions posture): the proxy pins a
#: stream's session here so its mid-stream failover can name the
#: session a survivor must adopt; responses echo the minted id
SESSION_HEADER = "X-Dl4j-Session-Id"

#: the SSE resume header (standard EventSource semantics): the last
#: ``id:`` the client received — a re-routed stream replays/regenerates
#: everything AFTER it and nothing at or before it (exactly-once)
LAST_EVENT_ID_HEADER = "Last-Event-ID"

#: Retry-After for sheds that carry no quota refill time (the in-flight
#: gate, an open circuit): "come back shortly", not a quota schedule
DEFAULT_RETRY_AFTER_S = 1.0


def retry_after_seconds(exc: Optional[BaseException]) -> float:
    """The Retry-After a shed response should carry: a quota shed knows
    its bucket's refill time (``QuotaExceeded.retry_after_s``); every
    other 429/503 uses the default."""
    v = getattr(exc, "retry_after_s", None)
    try:
        return max(0.0, float(v)) if v is not None else \
            DEFAULT_RETRY_AFTER_S
    except (TypeError, ValueError):
        return DEFAULT_RETRY_AFTER_S


def _retry_after_header(exc: Optional[BaseException] = None):
    """RFC 7231 delta-seconds (integer, >= 1 so a client never busy-
    loops on a sub-second value rounded to 0)."""
    import math
    return ("Retry-After",
            str(max(1, math.ceil(retry_after_seconds(exc)))))


def frontdoor_enabled() -> bool:
    """``DL4J_TPU_FRONTDOOR`` kill switch, resolved LIVE (per request —
    flipping it 503s new traffic without restarting the process; the
    debug/metrics surfaces stay up)."""
    return os.environ.get("DL4J_TPU_FRONTDOOR", "1") != "0"


class BadRequest(ValueError):
    """Malformed request body/params — HTTP 400, never an error-rate
    event (client bugs are not model failures)."""


class PayloadTooLarge(ValueError):
    """Request body over :data:`MAX_BODY_BYTES` — HTTP 413, refused
    before a byte of it is buffered."""


def charges_possible(exc: BaseException) -> bool:
    """Could work that charged the tenant (or emitted tokens) have
    happened before ``exc``?  Drives the idempotency journal's
    resolve-vs-abandon split for typed outcomes: pre-charge rejections
    (quota, queue-full shed, circuit open, shutdown) are abandoned so a
    later retry gets a real attempt; anything that may carry partial
    work (preemption and stream-cancel after partial decode, deadlines,
    device errors) is resolved so a retry replays instead of
    double-charging."""
    if isinstance(exc, _qos.PreemptedError):
        return True
    if type(exc).__name__ == "StreamCancelled":
        return True          # partial tokens were streamed and charged
    if isinstance(exc, (ShedError, CircuitOpenError, ShutdownError)):
        return False
    return True


def http_status(exc: BaseException) -> int:
    """The typed-outcome → HTTP status mapping (one spelling: the JSON
    error path, the SSE error event, and the tests all read this)."""
    if isinstance(exc, (ShedError,)):          # incl. StreamCancelled
        return 429
    if isinstance(exc, DeadlineExceeded):
        return 504
    if isinstance(exc, (CircuitOpenError, ShutdownError)):
        return 503
    if isinstance(exc, KeyError):              # unknown version
        return 404
    if isinstance(exc, RolloutConflictError):  # rollout lifecycle refusal
        return 409
    if isinstance(exc, PayloadTooLarge):
        return 413
    if isinstance(exc, (BadRequest, ValueError, TypeError)):
        return 400
    return 500                                 # device errors, InjectedFault


class _HttpMetrics:
    """Label-bound ``dl4j_http_*`` instruments (registry-reset safe,
    the serving/_GenMetrics pattern)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        reg = global_registry()
        self._requests = reg.counter(
            "dl4j_http_requests_total",
            "front-door HTTP requests answered, by route and status code",
            label_names=("route", "code"))
        self._latency = reg.histogram(
            "dl4j_http_latency_seconds",
            "front-door request wall time from parse to last byte, by "
            "route (streams: until the final SSE event)",
            label_names=("route",))
        self.inflight = reg.gauge(
            "dl4j_http_inflight",
            "front-door requests currently being served (admission gate "
            "sheds above max_inflight)")
        shed = reg.counter(
            "dl4j_http_shed_total",
            "front-door requests shed at the door, by reason",
            label_names=("reason",))
        self.shed = {r: shed.labels(reason=r)
                     for r in ("inflight", "disabled")}
        self.stream_tokens = reg.counter(
            "dl4j_http_stream_tokens_total",
            "tokens emitted over SSE streams (rate = streamed tokens/s "
            "on the wire)")
        self.first_token = reg.histogram(
            "dl4j_http_first_token_seconds",
            "SSE streams: request start to the first token event on the "
            "wire (the latency streaming exists to shrink)")
        self.disconnects = reg.counter(
            "dl4j_http_disconnects_total",
            "clients that went away mid-response (streams cancel at the "
            "next step boundary, slots freed)")

    def requests(self, route: str, code: int):
        return self._requests.labels(route=route, code=str(code))

    def latency(self, route: str):
        return self._latency.labels(route=route)

    @classmethod
    def get(cls) -> "_HttpMetrics":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance


@on_registry_reset
def _drop_http_metrics():
    _HttpMetrics._instance = None


def _route_of(path: str) -> str:
    if path == "/v1/classify":
        return "classify"
    if path == "/v1/generate":
        return "generate"
    if path.startswith("/admin/"):
        return "admin"
    if path.startswith("/debug/") or path in ("/metrics", "/health",
                                              "/metrics/fleet",
                                              "/health/fleet",
                                              "/alerts/fleet"):
        return "debug"
    return "other"


class FrontDoor:
    """One worker's HTTP front door. ``router`` serves the scoring lane
    (``/v1/classify``), ``gen_router`` the generative lane
    (``/v1/generate``); either may be None (the route 404s). With
    ``shared`` set, routing state comes from the shared store (see
    module doc) and a sync thread coordinates with the fleet."""

    _live: "weakref.WeakSet[FrontDoor]" = weakref.WeakSet()

    def __init__(self, router=None, gen_router=None, *, shared=None,
                 host: Optional[str] = None, port: int = 0,
                 max_inflight: int = 64,
                 sync_interval_s: float = 0.25,
                 worker_id: Optional[str] = None,
                 reuse_port: bool = False):
        self.router = router
        self.gen_router = gen_router
        self.shared = shared
        self.worker_id = worker_id or (shared.worker_id if shared else "w0")
        self.host = host
        self.port = int(port)
        self.max_inflight = int(max_inflight)
        self.reuse_port = bool(reuse_port)
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._sync_interval = float(sync_interval_s)
        self._sync_stop = threading.Event()
        self._sync_thread: Optional[threading.Thread] = None
        self._started_at = time.time()
        self._fleet_health = None       # lazy federation.FleetHealth
        self._fleet_pub_at = 0.0        # leader rollup publish throttle
        self._fleet_watch = None        # lazy federation.FleetWatch
        self._alerts_pub_at = 0.0       # alert snapshot publish throttle
        FrontDoor._live.add(self)

    # ------------------------------------------------------------- lanes
    def _lane_router(self, lane: str):
        return self.router if lane == "scoring" else self.gen_router

    def classify(self, x, deadline_ms=None, request_key=None,
                 tenant=None):
        """One classify request through whichever routing mode is wired
        (shared store split or the local rollout machinery)."""
        if self.router is None:
            raise KeyError("no scoring deploy behind this front door")
        if self.shared is None:
            return self.router.output(x, deadline_ms=deadline_ms,
                                      request_key=request_key,
                                      tenant=tenant), None
        frac = request_fraction(x, request_key)
        version, canary = self.shared.pick("scoring", frac)
        if version is None:
            raise KeyError("scoring lane has no primary in the shared "
                           "store")
        t0 = time.perf_counter()
        try:
            out = self.router.output_on(version, x, deadline_ms=deadline_ms,
                                        canary=canary, tenant=tenant)
        except Exception as e:
            self.shared.record(version,
                               ok=isinstance(e, TYPED_OUTCOMES),
                               latency_s=time.perf_counter() - t0)
            raise
        self.shared.record(version, ok=True,
                           latency_s=time.perf_counter() - t0)
        return out, version

    def generate(self, prompt, max_new_tokens=None, eos_id=None,
                 deadline_ms=None, request_key=None, on_token=None,
                 tenant=None, session_id=None):
        if self.gen_router is None:
            raise KeyError("no generative deploy behind this front door")
        if self.shared is None:
            return self.gen_router.generate(
                prompt, max_new_tokens=max_new_tokens, eos_id=eos_id,
                deadline_ms=deadline_ms, request_key=request_key,
                on_token=on_token, tenant=tenant,
                session_id=session_id), None
        frac = request_fraction(prompt, request_key)
        version, canary = self.shared.pick("generative", frac)
        if version is None:
            raise KeyError("generative lane has no primary in the shared "
                           "store")
        t0 = time.perf_counter()
        try:
            out = self.gen_router.generate_on(
                version, prompt, max_new_tokens=max_new_tokens,
                eos_id=eos_id, deadline_ms=deadline_ms, canary=canary,
                on_token=on_token, tenant=tenant, session_id=session_id)
        except Exception as e:
            self.shared.record(version,
                               ok=isinstance(e, TYPED_OUTCOMES),
                               latency_s=time.perf_counter() - t0)
            raise
        self.shared.record(version, ok=True,
                           latency_s=time.perf_counter() - t0)
        return out, version

    def adopt_session(self, sid: str) -> dict:
        """Fence-bump ``sid``'s journaled record to THIS worker (fleet
        failover: the proxy re-routed a dead worker's stream here). The
        ``generation.adopt`` chaos point fires per attempt under the
        standard retry budget — a transient store blip costs a retry,
        not the stream. Raises ``KeyError`` when nothing durable
        exists to adopt."""
        if self.shared is None:
            raise KeyError("session adoption needs the shared store")

        def attempt():
            if _faults.armed():
                _faults.check("generation.adopt")
            return _sess.adopt(self.shared.store, sid, self.worker_id)

        if _faults.resilience_enabled():
            return RetryPolicy(max_retries=2,
                               base_delay_seconds=0.01).call(
                attempt, op="generation.adopt")
        return attempt()

    def resume(self, record: dict, on_token=None, deadline_ms=None,
               tenant=None):
        """Continue an adopted session on this worker's slots: mirror
        the record locally (the continued tokens journal under the
        bumped fence) and re-enter through the pipeline's resume path.
        Returns ``(tokens, version)`` like :meth:`generate`."""
        if self.gen_router is None:
            raise KeyError("no generative deploy behind this front door")
        sess = _sess.global_sessions().adopt_local(record)
        version = record.get("version")
        if self.shared is not None and version is None:
            version, _canary = self.shared.pick("generative", 0.0)
        if version is None:
            raise KeyError("adopted session names no generative version")
        t0 = time.perf_counter()
        try:
            out = self.gen_router.resume_on(
                version, record, on_token=on_token,
                deadline_ms=deadline_ms, tenant=tenant, session=sess)
        except KeyError:
            # the dead worker served a version this one never deployed
            # (mid-rollout death): fall back to the lane primary — the
            # in-graph seed travels in the record, so greedy output is
            # unchanged; a sampled stream continues best-effort
            if self.shared is None:
                raise
            version, _canary = self.shared.pick("generative", 0.0)
            if version is None:
                raise
            out = self.gen_router.resume_on(
                version, record, on_token=on_token,
                deadline_ms=deadline_ms, tenant=tenant, session=sess)
        except Exception as e:
            if self.shared is not None:
                self.shared.record(version,
                                   ok=isinstance(e, TYPED_OUTCOMES),
                                   latency_s=time.perf_counter() - t0)
            raise
        if self.shared is not None:
            self.shared.record(version, ok=True,
                               latency_s=time.perf_counter() - t0)
        return out, version

    # ----------------------------------------------------- shared syncing
    def _apply_event(self, event: dict):
        """Apply one leader transition locally: FULL → repoint this
        worker's lane router and gracefully drain the old incumbent;
        ROLLED_BACK → drain the local candidate. Errors are absorbed
        (a version this worker never deployed is not its transition)."""
        lane = event.get("lane")
        router = self._lane_router(lane or "")
        if router is None:
            return
        registry = router._registry
        try:
            if event.get("to") == "full":
                router.repoint(event["candidate"])
                old = event.get("old_primary")
                if old and old != event["candidate"]:
                    registry.retire(old)
            elif event.get("to") == "rolled_back":
                cand = event.get("candidate")
                if cand:
                    registry.retire(cand)
        except Exception:  # graftlint: disable=typed-errors — replaying a
            pass           # shared-store event is best-effort; no request
                           # outcome flows through this handler

    def sync_once(self):
        """One shared-store beat (the background thread's body; tests
        and single-stepped drills call it directly)."""
        if self.shared is None:
            return []
        events = self.shared.sync()
        for e in events:
            self._apply_event(e)
        return events

    def _sync_loop(self):
        while not self._sync_stop.wait(self._sync_interval):
            try:
                self.sync_once()
            # graftlint: disable=typed-errors — coordination must never
            # kill the serving process; the next beat retries
            except Exception:
                # (store contention, transient fs)
                pass
            try:
                self._fleet_obs_beat()
            # graftlint: disable=typed-errors — the observability plane
            # must never kill the serving process; the next beat retries
            except Exception:
                pass

    def _fleet_health_view(self):
        """This worker's federated health engine (lazy: built on first
        ``/health/fleet`` or leader rollup; only valid in shared mode)."""
        if self._fleet_health is None:
            self._fleet_health = _fed.FleetHealth(self.shared.store,
                                                  worker_id=self.worker_id)
        return self._fleet_health

    def _fleet_watch_view(self):
        """The LEADER's fleet-level watchtower (lazy; wraps the same
        federated health view so detectors read one scrape shape)."""
        if self._fleet_watch is None:
            self._fleet_watch = _fed.FleetWatch(self._fleet_health_view())
        return self._fleet_watch

    def _maybe_publish_alerts(self):
        """This worker's watchtower alert snapshot into the shared
        store, throttled to the health interval; the LEADER also beats
        the fleet-level detectors and publishes their rollup."""
        if not _tms.watchtower_enabled():
            return
        now = time.monotonic()
        if now - self._alerts_pub_at < _fed.health_interval_s():
            return
        self._alerts_pub_at = now
        fleet = None
        term = None
        if self.shared.is_leader:
            fw = self._fleet_watch_view()
            fw.beat()
            fleet = fw.snapshot()
            term = self.shared.leader_term
        _fed.publish_alerts(self.shared.store, self.worker_id, term,
                            _watchtower.global_watchtower().snapshot(),
                            fleet=fleet,
                            is_leader=self.shared.is_leader)

    def _fleet_obs_beat(self):
        """One beat of the fleet observability plane (rides the sync
        loop; tests single-step it directly): beat the local watchtower
        (its own live kill switch + interval throttle — a page firing
        here pins traces and dumps the bundle the incident publisher
        fans out), run the incident fan-out protocol, publish this
        worker's alert snapshot, and — on the LEADER only, throttled to
        ``DL4J_TPU_FLEET_HEALTH_INTERVAL_S`` — publish the fleet health
        rollup into the shared store so every worker's ``/debug/fleet``
        shows one consistent verdict."""
        if self.shared is None:
            return
        _watchtower.global_watchtower().beat()
        if not _fed.fleet_obs_enabled():
            return
        _fed.incident_beat(self.shared.store, self.worker_id,
                           self.shared.is_leader)
        self._maybe_publish_alerts()
        if not self.shared.is_leader:
            return
        now = time.monotonic()
        if now - self._fleet_pub_at < _fed.health_interval_s():
            return
        self._fleet_pub_at = now
        _fed.publish_rollup(self.shared.store, self.worker_id,
                            self.shared.leader_term,
                            self._fleet_health_view().evaluate())

    # -------------------------------------------------------------- serve
    def start(self) -> "FrontDoor":
        fd = self
        if self.shared is not None and _sess.sessions_enabled():
            # arm the session journal under this worker's lease: batched
            # step-boundary writes into the same shared store the fleet
            # plane rides (one daemon thread; kill switch leaves the
            # journal detached and every session surface inert)
            _sess.global_journal().attach(self.shared.store,
                                          self.worker_id)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):     # quiet, like the UI server
                pass

            # ------------------------------------------------- plumbing
            def _tid(self):
                """This request's trace id: captured inside the span
                (so ERROR replies emitted after it closed still carry
                it), falling back to any live ambient context.  The
                fallback is gated on the fleet plane — the pre-plane
                span site still opens an http_request span when the
                switch is OFF, and its ambient context must not leak a
                header onto byte-identical pre-plane responses."""
                tid = getattr(self, "_trace_id", None)
                if tid is not None:
                    return tid
                if not _fed.fleet_obs_enabled():
                    return None
                ctx = current_context()
                return ctx.trace_id if ctx is not None else None

            def _finish_idem(self, code: int, payload: dict, exc=None):
                """Journal this request's final outcome under its
                idempotency key (once): outcomes reached after execution
                began resolve (a retry replays); pre-charge rejections
                abandon (a retry gets a real attempt)."""
                key = getattr(self, "_idem_key", None)
                if key is None:
                    return
                self._idem_key = None
                journal = _idem.global_journal()
                if (getattr(self, "_idem_executing", False)
                        and (exc is None or charges_possible(exc))):
                    journal.resolve(key, code, payload)
                else:
                    journal.abandon(key)

            def _reply(self, code: int, payload: dict, route: str,
                       t0: float, extra_headers=()):
                self._finish_idem(code, payload)
                if _trace_store.trace_store_enabled():
                    # the retention rules read the ROOT span's attrs:
                    # typed errors are caught INSIDE the http_request
                    # span (it exits cleanly), so the status must ride
                    # on the span for tail-based keep/drop to see it
                    sp = _current_span()
                    if sp is not None:
                        sp.set_attr("status", code)
                        tenant = getattr(self, "_tenant", None)
                        if tenant is not None:
                            sp.set_attr("tenant", tenant)
                body = json.dumps(payload, default=str).encode()
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    tid = self._tid()
                    if tid is not None:
                        self.send_header("X-Dl4j-Trace-Id", str(tid))
                    for k, v in extra_headers:
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError, OSError):
                    _HttpMetrics.get().disconnects.inc()
                obs = _HttpMetrics.get()
                obs.requests(route, code).inc()
                obs.latency(route).observe(time.perf_counter() - t0)

            def _error(self, exc: BaseException, route: str, t0: float):
                code = http_status(exc)
                payload = {"error": type(exc).__name__,
                           "detail": str(exc)}
                if _trace_store.trace_store_enabled():
                    sp = _current_span()
                    if sp is not None:
                        sp.set_attr("error_type", type(exc).__name__)
                self._finish_idem(code, payload, exc=exc)
                headers = ()
                if code in (429, 503):
                    # every shed response tells the client when to come
                    # back: quota sheds derive it from the tenant's
                    # bucket refill time, the rest use the default
                    headers = (_retry_after_header(exc),)
                    payload["retry_after_s"] = round(
                        retry_after_seconds(exc), 3)
                self._reply(code, payload, route, t0,
                            extra_headers=headers)

            def _serve_replay(self, entry, route: str, t0: float):
                """A retried idempotency key: wait for the original's
                resolution (immediate when already done) and return THE
                original outcome — nothing executes, nothing is charged."""
                try:
                    body = self._read_json()     # drain + stream flag
                except Exception as e:
                    self._error(e, route, t0)
                    return
                outcome = _idem.global_journal().await_outcome(entry)
                if outcome is None:
                    # original still executing past the bounded wait (or
                    # the key was abandoned mid-wait): come back shortly
                    self._reply(503, {
                        "error": "IdempotentInFlight",
                        "detail": "the original request under this "
                                  "idempotency key is still executing",
                        "retry_after_s": DEFAULT_RETRY_AFTER_S},
                        route, t0,
                        extra_headers=(_retry_after_header(),))
                    return
                code, payload = outcome
                if (body.get("stream") and code == 200
                        and isinstance(payload.get("tokens"), list)):
                    self._replay_stream(payload, route, t0)
                    return
                self._reply(code, payload, route, t0,
                            extra_headers=((_idem.REPLAY_HEADER, "1"),))

            def _replay_stream(self, payload: dict, route: str,
                               t0: float):
                """Replay a journaled stream outcome as SSE: the same
                token events the original emitted, from the journal."""
                obs = _HttpMetrics.get()
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header(_idem.REPLAY_HEADER, "1")
                    tid = self._tid()
                    if tid is not None:
                        self.send_header("X-Dl4j-Trace-Id", str(tid))
                    self.end_headers()
                    for i, tok in enumerate(payload["tokens"]):
                        self.wfile.write(
                            (f"event: token\ndata: "
                             f"{json.dumps({'index': i, 'token': int(tok)})}"
                             f"\n\n").encode())
                    self.wfile.write(("event: done\ndata: "
                                      + json.dumps(payload)
                                      + "\n\n").encode())
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    obs.disconnects.inc()
                obs.requests("stream", 200).inc()
                obs.latency("stream").observe(time.perf_counter() - t0)

            def _read_json(self) -> dict:
                n = int(self.headers.get("Content-Length", 0) or 0)
                if n > MAX_BODY_BYTES:
                    raise PayloadTooLarge(
                        f"body of {n} bytes exceeds the "
                        f"{MAX_BODY_BYTES}-byte limit")
                raw = self.rfile.read(n) if n > 0 else b"{}"
                try:
                    doc = json.loads(raw or b"{}")
                except ValueError as e:
                    raise BadRequest(f"body is not JSON: {e}")
                if not isinstance(doc, dict):
                    raise BadRequest("body must be a JSON object")
                return doc

            def _send_text(self, body: bytes, route: str):
                """Plain-text 200 (the Prometheus exposition paths).
                With the fleet plane off ``self._trace_id`` is None and
                the bytes on the wire are identical to the pre-
                federation ``/metrics`` writer."""
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                tid = getattr(self, "_trace_id", None)
                if tid is not None:
                    self.send_header("X-Dl4j-Trace-Id", str(tid))
                self.end_headers()
                self.wfile.write(body)
                _HttpMetrics.get().requests(route, 200).inc()

            # --------------------------------------------------- routes
            def do_POST(self):
                path = urlparse(self.path).path
                route = _route_of(path)
                t0 = time.perf_counter()
                self._trace_id = None
                self._obs_ctx = None
                if _fed.fleet_obs_enabled():
                    # fleet plane: join the caller's trace (or pre-
                    # allocate a root id) BEFORE the early exits, so
                    # EVERY response path — 404, disabled-503, quota/
                    # inflight-429, idempotent replay — carries
                    # X-Dl4j-Trace-Id
                    self._obs_ctx = _fed.inbound_context(self.headers)
                    self._trace_id = self._obs_ctx.trace_id
                self._idem_key = None
                self._idem_executing = False
                obs = _HttpMetrics.get()
                if path not in ("/v1/classify", "/v1/generate",
                                "/admin/rollout", "/admin/rollback"):
                    self._reply(404, {"error": "NotFound", "path": path},
                                route, t0)
                    return
                if path.startswith("/v1/") and not frontdoor_enabled():
                    obs.shed["disabled"].inc()
                    self._reply(503, {"error": "FrontDoorDisabled",
                                      "detail": "DL4J_TPU_FRONTDOOR=0"},
                                route, t0,
                                extra_headers=(_retry_after_header(),))
                    return
                # idempotent retries: a known key replays its journaled
                # outcome (or attaches to the in-flight original) BEFORE
                # quota/admission — a replay executes nothing, spends no
                # quota, and charges no token debt (exactly-once per key)
                if (path in ("/v1/classify", "/v1/generate")
                        and _idem.idempotency_enabled()):
                    key = self.headers.get(_idem.IDEMPOTENCY_HEADER)
                    if key:
                        entry, state = _idem.global_journal().begin(key)
                        if entry is not None and state != _idem.NEW:
                            self._serve_replay(entry, route, t0)
                            return
                        if entry is not None:
                            self._idem_key = key
                # tenant identity + quota admission (QoS posture; the
                # kill switch leaves self._tenant None — the header is
                # inert and no tenant series are touched)
                self._tenant = None
                if path.startswith("/v1/") and _qos.qos_enabled():
                    tenants = _qos.global_tenants()
                    try:
                        self._tenant = tenants.admit(
                            self.headers.get(TENANT_HEADER))
                    except _qos.QuotaExceeded as e:
                        # quota sheds are still per-tenant traffic: the
                        # requests denominator must see a 100% -over-
                        # quota tenant, not read it as "no traffic, ok"
                        tenants.observe_request(
                            e.tenant, time.perf_counter() - t0, e)
                        self._error(e, route, t0)
                        return
                admitted = False
                if path.startswith("/v1/"):
                    with fd._inflight_lock:
                        if fd._inflight >= fd.max_inflight:
                            obs.shed["inflight"].inc()
                        else:
                            fd._inflight += 1
                            admitted = True
                            obs.inflight.set(fd._inflight)
                    if not admitted:
                        # the in-flight gate's shed reuses the same
                        # Retry-After surface as the quota path
                        self._reply(429, {
                            "error": "ShedError",
                            "detail": f"front door at max_inflight="
                                      f"{fd.max_inflight}",
                            "retry_after_s": DEFAULT_RETRY_AFTER_S},
                            route, t0,
                            extra_headers=(_retry_after_header(),))
                        return
                try:
                    # trace_context(None) is effect-free, so with the
                    # fleet plane off this line is byte-identical to the
                    # pre-federation span site; with it on, the root
                    # span's trace/parent ids are the CALLER's
                    with trace_context(self._obs_ctx), \
                            _span("http_request", route=route):
                        # capture the id while the span is OPEN: error
                        # replies run after it closes and must still
                        # carry the header (the join-to-traces contract
                        # matters MOST for failing requests).  Gated on
                        # the plane being ON — the span exists either
                        # way, but with DL4J_TPU_FLEET_OBS=0 no header
                        # may leak (byte-identical pre-plane responses)
                        if self._obs_ctx is not None:
                            ctx = current_context()
                            self._trace_id = (ctx.trace_id
                                              if ctx is not None
                                              else self._trace_id)
                        try:
                            if _faults.armed():
                                _faults.check("http.request")
                            body = self._read_json()
                            if (self._idem_key is not None
                                    and path in ("/v1/classify",
                                                 "/v1/generate")):
                                # past here, ANY outcome may carry
                                # charged work: journal it, never
                                # re-execute a retried key
                                self._idem_executing = True
                                _idem.global_journal().mark_executing(
                                    self._idem_key)
                            if path == "/v1/classify":
                                self._classify(body, route, t0)
                            elif path == "/v1/generate":
                                self._generate(body, route, t0)
                            elif path == "/admin/rollout":
                                self._rollout(body, route, t0)
                            else:
                                self._rollback(body, route, t0)
                        except Exception as e:
                            self._error(e, route, t0)
                finally:
                    if admitted:
                        with fd._inflight_lock:
                            fd._inflight -= 1
                            obs.inflight.set(fd._inflight)

            def _classify(self, body: dict, route: str, t0: float):
                if "inputs" not in body:
                    raise BadRequest("missing 'inputs'")
                try:
                    x = np.asarray(body["inputs"], dtype="f4")
                except (ValueError, TypeError) as e:
                    raise BadRequest(f"inputs not numeric: {e}")
                out, version = fd.classify(
                    x, deadline_ms=body.get("deadline_ms"),
                    request_key=body.get("request_key"),
                    tenant=self._tenant)
                payload = {"outputs": np.asarray(out).tolist(),
                           "worker": fd.worker_id}
                if version is not None:
                    payload["version"] = version
                self._reply(200, payload, route, t0)

            def _parse_generate(self, body: dict):
                if "prompt" not in body:
                    raise BadRequest("missing 'prompt'")
                try:
                    prompt = np.asarray(body["prompt"],
                                        np.int32).reshape(-1)
                except (ValueError, TypeError) as e:
                    raise BadRequest(f"prompt not integral: {e}")
                mnt = body.get("max_new_tokens")
                return prompt, (int(mnt) if mnt is not None else None)

            def _generate(self, body: dict, route: str, t0: float):
                prompt, mnt = self._parse_generate(body)
                kw = dict(max_new_tokens=mnt, eos_id=body.get("eos_id"),
                          deadline_ms=body.get("deadline_ms"),
                          request_key=body.get("request_key"),
                          tenant=self._tenant)
                sid = None
                if _sess.sessions_enabled():
                    last = self.headers.get(LAST_EVENT_ID_HEADER)
                    sid = (self.headers.get(SESSION_HEADER)
                           or body.get("session_id"))
                    if (body.get("stream") and last is not None
                            and sid and fd.shared is not None):
                        # fleet failover re-entry: the proxy re-routed a
                        # mid-stream death here with the session id and
                        # the last event id its client received
                        self._resume_stream(sid, last, kw, t0)
                        return
                    sid = sid or _sess.new_session_id()
                    kw["session_id"] = sid
                if body.get("stream"):
                    self._generate_stream(prompt, kw, t0, sid=sid)
                    return
                out, version = fd.generate(prompt, **kw)
                payload = {"tokens": np.asarray(out).tolist(),
                           "worker": fd.worker_id}
                if sid is not None:
                    payload["session"] = sid
                if version is not None:
                    payload["version"] = version
                self._reply(200, payload, route, t0)

            def _resume_stream(self, sid: str, last: str, kw: dict,
                               t0: float):
                """Adopt ``sid`` from the store (lease-fenced) and
                continue its stream from the client's ``Last-Event-ID``:
                the journal's token log replays through the same queue,
                the pipeline regenerates the rest, and the dedup window
                drops every index the client already has — exactly-once
                across the failover."""
                try:
                    last_seq = int(last)
                except (TypeError, ValueError):
                    last_seq = -1
                record = fd.adopt_session(sid)
                tenant = self._tenant or record.get("tenant")
                run_ctx = current_context()

                def runner(on_token):
                    with trace_context(run_ctx):
                        return fd.resume(
                            record, on_token=on_token,
                            deadline_ms=kw.get("deadline_ms"),
                            tenant=tenant)

                self._stream_sse(runner, t0, sid=sid, last_seq=last_seq)

            def _generate_stream(self, prompt, kw: dict, t0: float,
                                 sid=None):
                run_ctx = current_context()

                def runner(on_token):
                    # the generation runs on a worker thread: hand the
                    # HTTP request's trace context across so the
                    # pipeline's spans join the SAME trace id the
                    # response header names
                    with trace_context(run_ctx):
                        return fd.generate(prompt, on_token=on_token,
                                           **kw)

                self._stream_sse(runner, t0, sid=sid)

            def _stream_sse(self, runner, t0: float, sid=None,
                            last_seq: int = -1):
                """SSE per-token streaming. The decode thread hands each
                token to a bounded queue via ``on_token`` (never touching
                the socket); this handler thread drains it onto the wire.
                A write failure (client gone) flips ``dead`` — the next
                callback returns False and the pipeline frees the slot
                at the step boundary (typed ``StreamCancelled``).

                With a session (``sid``), every token event carries its
                sequence number as the SSE ``id:`` field — the resume
                contract — and tokens at or below ``last_seq`` are
                dropped before the queue (the failover dedup window).
                With sessions off both are inert and the bytes on the
                wire are identical to the pre-session stream."""
                obs = _HttpMetrics.get()
                q: "queue.Queue" = queue.Queue(maxsize=4096)
                dead = threading.Event()

                def on_token(tok, idx):
                    if dead.is_set():
                        return False
                    if idx <= last_seq:
                        return True        # client already has it
                    try:
                        q.put_nowait((idx, int(tok)))
                    except queue.Full:
                        return False       # pathologically slow consumer
                    return True

                result: dict = {}

                def run():
                    try:
                        out, version = runner(on_token)
                        result["tokens"] = np.asarray(out).tolist()
                        result["version"] = version
                    # graftlint: disable=typed-errors — resolved by
                    # transport: the stored error is re-raised to the
                    # HTTP caller via the SSE error event / status map
                    except BaseException as e:
                        result["error"] = e
                    finally:
                        q.put(None)

                threading.Thread(target=run, daemon=True,
                                 name="dl4j-frontdoor-gen").start()
                # block for the FIRST token (or resolution) before
                # committing to SSE: a request that dies at the door —
                # shed, expired, unknown version — answers its real
                # HTTP status, not a 200 stream with an error event
                first_item = q.get()
                if first_item is None:
                    err = result.get("error")
                    if err is not None:
                        raise err
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Cache-Control", "no-cache")
                tid = self._tid()
                if tid is not None:
                    self.send_header("X-Dl4j-Trace-Id", str(tid))
                if sid is not None:
                    self.send_header(SESSION_HEADER, str(sid))
                self.end_headers()

                def emit(text: str) -> bool:
                    if dead.is_set():
                        return False
                    try:
                        self.wfile.write(text.encode())
                        self.wfile.flush()
                        return True
                    except (BrokenPipeError, ConnectionResetError,
                            OSError):
                        dead.set()
                        obs.disconnects.inc()
                        return False

                first_at = None
                item = first_item
                while item is not None:            # None = resolution
                    if item is not False:          # False = keepalive tick
                        idx, tok = item
                        # the SSE id: field IS the seq number — an
                        # EventSource (or the proxy's failover relay)
                        # resumes with Last-Event-ID = the last id seen
                        prefix = (f"id: {idx}\n" if sid is not None
                                  else "")
                        if emit(f"{prefix}event: token\ndata: "
                                f"{json.dumps({'index': idx, 'token': tok})}"
                                f"\n\n"):
                            obs.stream_tokens.inc()
                            if first_at is None:
                                first_at = time.perf_counter()
                                obs.first_token.observe(first_at - t0)
                    try:
                        item = q.get(timeout=1.0)
                    except queue.Empty:
                        emit(": keepalive\n\n")    # forces disconnect
                        item = False               # detection when idle
                err = result.get("error")
                code = 200
                if err is not None and _trace_store.trace_store_enabled():
                    # streams bypass _reply/_error: stamp the root span
                    # here so a failed stream is tail-retained too
                    sp = _current_span()
                    if sp is not None:
                        sp.set_attr("status", http_status(err))
                        sp.set_attr("error_type", type(err).__name__)
                if err is not None:
                    err_payload = {"error": type(err).__name__,
                                   "detail": str(err),
                                   "status": http_status(err)}
                    self._finish_idem(http_status(err), err_payload,
                                      exc=err)
                    if not dead.is_set():
                        code = err_payload["status"]
                        emit("event: error\ndata: "
                             + json.dumps(err_payload) + "\n\n")
                else:
                    done = {"tokens": result.get("tokens"),
                            "n": len(result.get("tokens") or ()),
                            "worker": fd.worker_id}
                    if sid is not None:
                        done["session"] = sid
                    if result.get("version") is not None:
                        done["version"] = result["version"]
                    self._finish_idem(200, done)
                    emit("event: done\ndata: " + json.dumps(done) + "\n\n")
                obs.requests("stream", code).inc()
                obs.latency("stream").observe(time.perf_counter() - t0)

            def _rollout(self, body: dict, route: str, t0: float):
                lane = body.get("lane", "scoring")
                candidate = body.get("candidate")
                if not candidate:
                    raise BadRequest("missing 'candidate'")
                if fd.shared is not None:
                    fd.shared.begin_rollout(lane, candidate,
                                            body.get("policy"))
                    self._reply(200, fd.shared.routing(lane), route, t0)
                    return
                router = fd._lane_router(lane)
                if router is None:
                    raise KeyError(f"no {lane} router on this front door")
                from deeplearning4j_tpu.serving.rollout import RolloutPolicy
                policy = RolloutPolicy(**(body.get("policy") or {}))
                ro = router.begin_rollout(candidate, policy)
                self._reply(200, ro.snapshot(), route, t0)

            def _rollback(self, body: dict, route: str, t0: float):
                lane = body.get("lane", "scoring")
                reason = body.get("reason", "manual")
                if fd.shared is not None:
                    fd.shared.rollback(lane, reason)
                    self._reply(200, fd.shared.routing(lane), route, t0)
                    return
                router = fd._lane_router(lane)
                if router is None or router.rollout is None:
                    raise KeyError(f"no active {lane} rollout")
                router.rollout.rollback(reason)
                self._reply(200, router.snapshot(), route, t0)

            def do_GET(self):
                path = urlparse(self.path).path
                route = _route_of(path)
                t0 = time.perf_counter()
                self._trace_id = None
                fleet_on = _fed.fleet_obs_enabled()
                if fleet_on:
                    # same join-at-the-door as do_POST: a caller-
                    # supplied id echoes on every GET path too
                    self._trace_id = _fed.inbound_context(
                        self.headers).trace_id
                try:
                    if path == "/debug/frontdoor":
                        self._reply(200, fd.snapshot(), route, t0)
                    elif path == "/debug/fleet":
                        # the fleet robustness view: lease/term state,
                        # demotions, store-corruption/rebuild evidence,
                        # and the idempotency journal (the chaos drill's
                        # duplicate-execution audit surface)
                        self._reply(200, fleet_snapshot(), route, t0)
                    elif path == "/debug/tenants":
                        # tenant policies, quota bucket levels, and
                        # per-tenant lifetime counters — the multi-
                        # tenant QoS view of this worker
                        self._reply(200, _qos.snapshot(), route, t0)
                    elif path == "/debug/sessions":
                        # durable generation sessions: the in-memory
                        # ring, journal watermarks, fences — the
                        # failover drill's adoption audit surface
                        self._reply(200, _sess.snapshot(), route, t0)
                    elif path == "/metrics":
                        from deeplearning4j_tpu.observability import metrics
                        body = metrics().render_prometheus().encode()
                        self._send_text(body, route)
                    elif (path == "/metrics/fleet" and fleet_on
                          and fd.shared is not None):
                        # the federated scrape: every live worker's
                        # series with a `worker` label plus this
                        # process's own — partial (200) when a peer is
                        # unreachable, never a 500 because one died
                        body = _fed.render_fleet(
                            fd.shared.store,
                            local_worker=fd.worker_id).encode()
                        self._send_text(body, route)
                    elif (path == "/health/fleet" and fleet_on
                          and fd.shared is not None):
                        from deeplearning4j_tpu.observability.slo import (
                            FAILING)
                        report = fd._fleet_health_view().evaluate()
                        self._reply(
                            503 if report["status"] == FAILING else 200,
                            report, route, t0)
                    elif (path == "/alerts/fleet" and fleet_on
                          and fd.shared is not None):
                        self._reply(200, fd._fleet_health_view().alerts(),
                                    route, t0)
                    elif (path == "/debug/alerts"
                          and _tms.watchtower_enabled()):
                        # the unified alert surface: legacy SLO keys +
                        # watchtower lifecycle + (fleet mode) the store
                        # rollup with honest `partial` on dead workers
                        q = parse_qs(urlparse(self.path).query)
                        code, payload = _fed.handle_alerts_route(
                            path, q,
                            store=(fd.shared.store
                                   if fd.shared is not None else None),
                            local_worker=fd.worker_id,
                            fleet=fleet_on and fd.shared is not None)
                        self._reply(code, payload, route, t0)
                    elif (path == "/debug/timeseries"
                          and _tms.watchtower_enabled()):
                        # the minutes BEFORE the trip: ringed registry
                        # samples (?name=<prefix>&last=N)
                        q = parse_qs(urlparse(self.path).query)
                        self._reply(200, _tms.timeseries_payload(
                            q, local_worker=fd.worker_id), route, t0)
                    elif (path.startswith("/debug/trace")
                            and _trace_store.trace_store_enabled()):
                        # trace intelligence: retained traces with
                        # why-kept reasons, and any retained id
                        # assembled into a cross-worker waterfall
                        # (fan-out exactly like /metrics/fleet; the
                        # ?local=1 form peers scrape stays local)
                        q = parse_qs(urlparse(self.path).query)
                        code, payload = _fed.handle_trace_route(
                            path, q,
                            store=(fd.shared.store
                                   if fd.shared is not None else None),
                            local_worker=fd.worker_id,
                            fleet=fleet_on and fd.shared is not None)
                        self._reply(code, payload, route, t0)
                    elif path == "/health":
                        from deeplearning4j_tpu.observability.slo import (
                            FAILING, global_slo_engine)
                        report = global_slo_engine().evaluate()
                        self._reply(
                            503 if report["status"] == FAILING else 200,
                            {"status": report["status"],
                             "failing_rules": report["failing_rules"],
                             "degraded_rules": report["degraded_rules"],
                             "worker": fd.worker_id,
                             "uptime_seconds": round(
                                 time.time() - fd._started_at, 3)},
                            route, t0)
                    else:
                        self._reply(404, {"error": "NotFound",
                                          "path": path}, route, t0)
                except Exception as e:
                    self._error(e, route, t0)

        host = self.host if self.host is not None else default_bind_host()
        if self.reuse_port:
            # kernel-level scale-out (tools/serve.py --reuseport): every
            # worker binds the SAME port; the kernel spreads accepts
            import socket as _socket
            self._httpd = ThreadingHTTPServer((host, self.port), Handler,
                                              bind_and_activate=False)
            self._httpd.socket.setsockopt(
                _socket.SOL_SOCKET, _socket.SO_REUSEPORT, 1)
            self._httpd.server_bind()
            self._httpd.server_activate()
        else:
            self._httpd = ThreadingHTTPServer((host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.host = self._httpd.server_address[0]
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="dl4j-frontdoor-http")
        self._thread.start()
        if self.shared is not None:
            # wire this worker's flight recorder into the coordinated-
            # capture protocol (the hook itself checks the live
            # DL4J_TPU_FLEET_OBS switch, so installing is inert when off)
            _fed.install_incident_publisher(self.shared.store,
                                            self.worker_id)
            self._sync_thread = threading.Thread(
                target=self._sync_loop, daemon=True,
                name="dl4j-frontdoor-sync")
            self._sync_thread.start()
        return self

    def stop(self):
        self._sync_stop.set()
        if self._sync_thread is not None:
            self._sync_thread.join(timeout=5.0)
            self._sync_thread = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def get_address(self) -> str:
        host = self.host or "127.0.0.1"
        if host == "0.0.0.0":
            host = "127.0.0.1"
        return f"http://{host}:{self.port}"

    # ------------------------------------------------------------ queries
    def snapshot(self) -> dict:
        out = {
            "worker_id": self.worker_id,
            "address": (self.get_address()
                        if self._httpd is not None else None),
            "enabled": frontdoor_enabled(),
            "mode": "shared" if self.shared is not None else "local",
            "inflight": self._inflight,
            "max_inflight": self.max_inflight,
            "uptime_seconds": round(time.time() - self._started_at, 3),
            "scoring": (self.router.snapshot()
                        if self.router is not None else None),
            "generative": (self.gen_router.snapshot()
                           if self.gen_router is not None else None),
        }
        if self.shared is not None:
            out["shared"] = self.shared.snapshot()
        return out


def snapshot_all() -> dict:
    """Every live front door's state — the ``/debug/frontdoor`` payload
    on the UI server and ``frontdoor.json`` in flight-recorder bundles."""
    return {"enabled": frontdoor_enabled(),
            "frontdoors": [f.snapshot() for f in list(FrontDoor._live)
                           if f._httpd is not None]}


def fleet_snapshot() -> dict:
    """The ``/debug/fleet`` payload (also ``fleet.json`` in flight-
    recorder bundles): lease-fenced leadership state (term, holder,
    demotions), store corruption/rebuild evidence, and the idempotency
    journal with per-key execution counts — the fleet chaos drill's
    audit surface for "zero duplicate executions, strictly monotonic
    terms"."""
    from deeplearning4j_tpu.serving import shared_state as _ss
    doors = []
    for f in list(FrontDoor._live):
        if f._httpd is None:
            continue
        doors.append({
            "worker_id": f.worker_id,
            "address": f.get_address(),
            "shared": (f.shared.snapshot()
                       if f.shared is not None else None),
        })
    out = {
        "fence_enabled": _ss.fleet_fence_enabled(),
        "idempotency": _idem.snapshot(),
        "frontdoors": doors,
    }
    if _fed.fleet_obs_enabled():
        # the leader-published rollup and the incident ledger: ONE
        # consistent fleet verdict, whichever worker answered this GET
        for f in list(FrontDoor._live):
            if f.shared is None or f._httpd is None:
                continue
            try:
                doc = f.shared.store.read()
            # graftlint: disable=typed-errors — a torn store read must
            # not break the debug surface; the base payload stands
            except Exception:
                break
            out["fleet_health"] = doc.get("fleet_health")
            out["incidents"] = doc.get("incidents") or []
            if _tms.watchtower_enabled():
                # the published alert rollup (leader fleet verdict +
                # per-worker snapshots) — key absent with the
                # watchtower off, byte-identical to pre-watchtower
                out["alerts"] = doc.get("alerts")
            break
    return out
