"""Durable generation sessions: journaling, adoption, exactly-once resume.

An in-flight generation stream used to be the one serving artifact that
died with its worker: idempotency keys made *unary* retries exactly-once
(PR 14) and the fleet plane survives a SIGKILL (PR 15), but a worker
dying mid-SSE silently truncated every stream it carried. This module is
the durable substrate that closes that gap — and the handoff format
ROADMAP item 4 (disaggregated prefill/decode) needs:

- every admitted generation gets a :class:`Session` record — prompt (and
  its hash), sampler config + base seed, the emitted-token log, and a
  per-session monotonic token **sequence number** (``seq`` == the token's
  index in the stream);
- the in-memory :class:`SessionTable` ring is the fast path (decode-hot
  appends are a list append, nothing else); a background
  :class:`SessionJournal` thread batches dirty sessions into the PR-11
  ``SharedStore`` under the worker's lease at step-boundary granularity
  — the decode loop only pokes an ``Event``;
- resume is deterministic because sampling is in-graph seeded
  (``fold_in(base_key, step)``, PR 10): a survivor re-prefills
  ``prompt + emitted_tokens`` and continues the stream (byte-identical
  under greedy — argmax ignores the folded step);
- adoption is **lease-fenced**: :func:`adopt` bumps the record's fence
  inside one serialized ``SharedStore.update``; the previous owner's
  next journal flush sees the higher fence, drops its write, and marks
  its local copy stolen so a stalled-but-alive worker can never
  double-decode (or double-journal) an adopted stream.

Knobs (all read live):

- ``DL4J_TPU_SESSIONS`` — kill switch (``0`` restores byte-identical
  pre-session behavior: no records, no ``id:`` SSE lines, no journal);
- ``DL4J_TPU_SESSION_JOURNAL_STEPS`` — journal cadence: a live session
  flushes once it has this many unjournaled tokens (finished or
  never-written sessions flush on the next beat regardless); the cadence
  bounds how many tokens a crash can lose;
- ``DL4J_TPU_SESSION_JOURNAL_BYTES`` — this worker's byte budget for its
  journaled blob in the store (oldest finished records evict first, then
  oldest live — evictions are counted, never silent);
- ``DL4J_TPU_SESSION_RING`` — in-memory table cap (same eviction order).

Observability: ``dl4j_session_*`` series, ``/debug/sessions`` on the
front door, and ``sessions.json`` in flight-recorder bundles.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

from deeplearning4j_tpu.observability import global_registry, on_registry_reset
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.policy import ShedError

#: store records older than this are swept by the journal flush (a
#: finished session only needs to outlive the client's replay window)
FINISHED_TTL_S = 600.0

#: how often the journal thread wakes WITHOUT a step-boundary poke (the
#: poke is the normal path; this is the straggler sweep)
FLUSH_INTERVAL_S = 0.05


def sessions_enabled() -> bool:
    """``DL4J_TPU_SESSIONS`` kill switch, read live (``0`` restores
    byte-identical pre-session behavior, pinned by a test)."""
    return os.environ.get("DL4J_TPU_SESSIONS", "1") != "0"


def flush_interval_s() -> float:
    """``DL4J_TPU_SESSION_FLUSH_MS``: minimum spacing between journal
    store commits (default 250ms).  Per-token notifies coalesce into one
    batched commit per interval — a crash can lose at most this much of
    the tail, and deterministic resume regenerates exactly that suffix
    (seq dedup keeps delivery exactly-once), so staleness here trades
    only recompute, never correctness."""
    try:
        ms = float(os.environ.get("DL4J_TPU_SESSION_FLUSH_MS", "250"))
    except ValueError:
        ms = 250.0
    return max(0.01, ms / 1000.0)


def journal_cadence_steps() -> int:
    """``DL4J_TPU_SESSION_JOURNAL_STEPS``: unjournaled tokens a live
    session accumulates before the next beat flushes it."""
    try:
        return max(1, int(os.environ.get(
            "DL4J_TPU_SESSION_JOURNAL_STEPS", "8")))
    except ValueError:
        return 8


def journal_byte_budget() -> int:
    """``DL4J_TPU_SESSION_JOURNAL_BYTES``: this worker's byte budget for
    its sessions blob in the shared store."""
    try:
        return max(4096, int(os.environ.get(
            "DL4J_TPU_SESSION_JOURNAL_BYTES", str(256 * 1024))))
    except ValueError:
        return 256 * 1024


def ring_capacity() -> int:
    """``DL4J_TPU_SESSION_RING``: in-memory session table cap."""
    try:
        return max(8, int(os.environ.get("DL4J_TPU_SESSION_RING", "256")))
    except ValueError:
        return 256


def new_session_id() -> str:
    """A fresh globally-unique session id (the front door mints one per
    admitted generation unless the client/proxy supplied one)."""
    return "s-" + os.urandom(8).hex()


def prompt_hash(prompt) -> str:
    """Stable content hash of a prompt token sequence (the session
    record's identity check on resume)."""
    import numpy as np
    arr = np.asarray(prompt, np.int32).reshape(-1)
    return hashlib.blake2b(arr.tobytes(), digest_size=8).hexdigest()


class SessionLost(ShedError):
    """This worker's lease on a session was fenced off (another worker
    adopted it) — the local decode must stop; the adopter owns the
    stream now. A typed lifecycle outcome of failover (``ShedError``
    subclass), never an error-rate event."""


class _SessionMetrics:
    """Label-bound ``dl4j_session_*`` instruments (registry-reset safe,
    the _GenMetrics pattern)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        reg = global_registry()
        self.journal_writes = reg.counter(
            "dl4j_session_journal_writes_total",
            "batched session-journal commits into the shared store (one "
            "per flush beat that had dirty sessions, not per session)")
        self.journal_tokens = reg.counter(
            "dl4j_session_journal_tokens_total",
            "emitted tokens made durable by the session journal")
        self.adoptions = reg.counter(
            "dl4j_session_adoptions_total",
            "orphaned sessions this worker adopted from the store "
            "(lease-fenced; the previous owner can no longer journal)")
        self.resumes = reg.counter(
            "dl4j_session_resumes_total",
            "sessions re-entered via re-prefill of prompt + emitted "
            "tokens (local in-place fault resume + adopted failover)")
        self.lost_lease = reg.counter(
            "dl4j_session_lost_lease_total",
            "journal writes dropped because another worker fenced this "
            "one off (the local decode stops; no double-journal)")
        self.evicted = reg.counter(
            "dl4j_session_evicted_total",
            "session records evicted by the ring cap or the store byte "
            "budget, by surface",
            label_names=("surface",))
        self.live = reg.gauge(
            "dl4j_session_live",
            "sessions currently decoding on this worker")

    @classmethod
    def get(cls) -> "_SessionMetrics":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance


@on_registry_reset
def _drop_session_metrics():
    _SessionMetrics._instance = None


def session_metrics() -> "_SessionMetrics":
    """The label-bound session instruments (the pipeline's resume path
    lives in ``parallel/`` and must not reach for a private)."""
    return _SessionMetrics.get()


class Session:
    """One generation's durable record. The decode thread appends tokens
    (plain list append — CPython-atomic, no lock on the hot path); the
    journal thread snapshots a consistent prefix by reading ``len``
    first. Everything else is bookkeeping off the decode path."""

    __slots__ = ("sid", "prompt", "prompt_hash", "sampler", "seed",
                 "max_new_tokens", "eos_id", "tenant", "version",
                 "status", "tokens", "journaled", "status_journaled",
                 "fence", "stolen", "created", "updated", "resumed")

    def __init__(self, sid: str, prompt: List[int], sampler: dict,
                 seed: Optional[int], max_new_tokens: int,
                 eos_id: Optional[int], tenant: Optional[str] = None,
                 version: Optional[str] = None,
                 tokens: Optional[List[int]] = None, fence: int = 0):
        self.sid = str(sid)
        self.prompt = [int(t) for t in prompt]
        self.prompt_hash = prompt_hash(self.prompt)
        self.sampler = dict(sampler or {})
        self.seed = seed
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.tenant = tenant
        self.version = version
        self.status = "live"
        self.tokens: List[int] = [int(t) for t in (tokens or [])]
        # durable watermark: tokens[:journaled] are in the store
        self.journaled = len(self.tokens)
        self.status_journaled = "live"
        self.fence = int(fence)
        self.stolen = False
        self.created = time.time()
        self.updated = self.created
        self.resumed = 0

    def append(self, tok: int) -> int:
        """Record one emitted token; returns its sequence number."""
        self.tokens.append(int(tok))
        self.updated = time.time()
        return len(self.tokens) - 1

    def finish(self, status: str):
        """Terminal transition (idempotent — the first outcome wins, the
        same discipline as ``_Request.claim``)."""
        if self.status == "live":
            self.status = status
            self.updated = time.time()

    @property
    def seq(self) -> int:
        """Next sequence number == tokens emitted so far."""
        return len(self.tokens)

    def to_store_doc(self, n: int, owner: Optional[str]) -> dict:
        """The record as journaled (``tokens[:n]`` — a consistent prefix
        snapshot taken by the journal thread)."""
        return {
            "sid": self.sid,
            "prompt": list(self.prompt),
            "prompt_hash": self.prompt_hash,
            "sampler": dict(self.sampler),
            "seed": self.seed,
            "max_new_tokens": self.max_new_tokens,
            "eos_id": self.eos_id,
            "tenant": self.tenant,
            "version": self.version,
            "status": self.status,
            "tokens": list(self.tokens[:n]),
            "seq": int(n),
            "fence": int(self.fence),
            "owner": owner,
            "created": self.created,
            "updated": time.time(),
        }

    def summary(self) -> dict:
        return {
            "sid": self.sid,
            "status": self.status,
            "prompt_tokens": len(self.prompt),
            "prompt_hash": self.prompt_hash,
            "emitted": len(self.tokens),
            "journaled": self.journaled,
            "fence": self.fence,
            "stolen": self.stolen,
            "tenant": self.tenant,
            "version": self.version,
            "resumed": self.resumed,
            "created": self.created,
            "updated": self.updated,
        }


class SessionTable:
    """The in-memory ring of this process's sessions (the fast path).
    Bounded by ``DL4J_TPU_SESSION_RING``; finished sessions evict before
    live ones, oldest first, and every eviction is counted."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sessions: "Dict[str, Session]" = {}

    def begin(self, prompt, sampler: dict, seed, max_new_tokens: int,
              eos_id, tenant=None, version=None,
              sid: Optional[str] = None) -> Session:
        s = Session(sid or new_session_id(), list(map(int, prompt)),
                    sampler, seed, max_new_tokens, eos_id,
                    tenant=tenant, version=version)
        with self._lock:
            self._sessions[s.sid] = s
            self._evict_over_cap()
        self._publish_live()
        return s

    def adopt_local(self, record: dict) -> Session:
        """Mirror an adopted store record locally (the survivor journals
        the continued stream under the bumped fence)."""
        s = Session(record["sid"], record.get("prompt") or [],
                    record.get("sampler") or {}, record.get("seed"),
                    int(record.get("max_new_tokens") or 1),
                    record.get("eos_id"),
                    tenant=record.get("tenant"),
                    version=record.get("version"),
                    tokens=record.get("tokens") or [],
                    fence=int(record.get("fence") or 0))
        s.resumed = int(record.get("resumed") or 0) + 1
        with self._lock:
            self._sessions[s.sid] = s
            self._evict_over_cap()
        self._publish_live()
        return s

    def _evict_over_cap(self):
        # caller holds the lock
        cap = ring_capacity()
        if len(self._sessions) <= cap:
            return
        obs = _SessionMetrics.get()
        # graftlint: disable=lock-discipline — every caller already
        # holds self._lock (checker can't cross calls)
        by_age = sorted(self._sessions.values(),
                        key=lambda s: (s.status == "live", s.created))
        for s in by_age:
            if len(self._sessions) <= cap:
                break
            self._sessions.pop(s.sid, None)
            obs.evicted.labels(surface="ring").inc()

    def _publish_live(self):
        with self._lock:
            n = sum(1 for s in self._sessions.values()
                    if s.status == "live")
        _SessionMetrics.get().live.set(n)

    def get(self, sid: str) -> Optional[Session]:
        with self._lock:
            return self._sessions.get(sid)

    def items(self) -> List[Session]:
        with self._lock:
            return list(self._sessions.values())

    def dirty(self) -> List[Session]:
        """Sessions with unjournaled state: new tokens past the cadence,
        a terminal status not yet written, or never written at all."""
        cadence = journal_cadence_steps()
        out = []
        with self._lock:
            for s in self._sessions.values():
                if s.stolen:
                    continue
                n = len(s.tokens)
                if (s.journaled == 0 and s.status == "live"
                        and s.status_journaled == "live" and n == 0
                        and s.created == s.updated):
                    # brand new, no tokens yet: write the admission
                    # record so a crash before the first boundary is
                    # still resumable
                    out.append(s)
                elif n - s.journaled >= cadence:
                    out.append(s)
                elif s.status != s.status_journaled:
                    out.append(s)
                elif s.journaled == 0 and (n > 0 or s.status != "live"):
                    out.append(s)
        return out

    def clear(self):
        with self._lock:
            self._sessions.clear()
        self._publish_live()


class SessionJournal:
    """The batched store writer. ``attach(store, worker_id)`` arms it
    (one daemon thread); ``notify()`` is the decode loop's step-boundary
    poke (an ``Event.set`` — the ONLY hot-path cost). Every flush is one
    serialized ``SharedStore.update`` carrying every dirty session, with
    the fence check inside the mutate: a record whose store fence
    outruns the local one was adopted elsewhere — the write is dropped,
    the local session marked stolen, and the pipeline stops decoding it
    at the next boundary."""

    def __init__(self, table: SessionTable):
        self._table = table
        self._store = None
        self._worker_id: Optional[str] = None
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # ------------------------------------------------------------ wiring
    def attach(self, store, worker_id: str):
        """Arm journaling into ``store`` under this worker's lease.
        Idempotent; re-attach swaps the target (tests)."""
        with self._lock:
            self._store = store
            self._worker_id = str(worker_id)
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="dl4j-session-journal")
                self._thread.start()

    def detach(self):
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        with self._lock:
            self._store = None
            self._worker_id = None
            self._thread = None

    @property
    def attached(self) -> bool:
        return self._store is not None

    @property
    def worker_id(self) -> Optional[str]:
        return self._worker_id

    def notify(self):
        """Step-boundary poke from the decode loop (cheap; no-op when
        not attached)."""
        # skip the Event.set when a poke is already pending — set()
        # takes the condition lock even when redundant, and this runs
        # once per decode step on the hot path
        if self._store is not None and not self._wake.is_set():
            self._wake.set()

    # ------------------------------------------------------------- flush
    def _run(self):
        while not self._stop.is_set():
            self._wake.wait(timeout=FLUSH_INTERVAL_S)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.flush()
            # graftlint: disable=typed-errors — a store blip must never
            # kill the journal thread; the next beat retries the batch
            except Exception:
                pass
            # coalesce: per-token notifies must not become per-token
            # store commits — hold the beat closed for the flush
            # interval so the next commit batches everything that
            # accumulated (bounded staleness; resume regenerates it)
            self._stop.wait(flush_interval_s())

    def flush(self) -> int:
        """One batched commit of every dirty session. Returns the number
        of sessions written (tests call this synchronously)."""
        store, wid = self._store, self._worker_id
        if store is None or wid is None:
            return 0
        dirty = self._table.dirty()
        if not dirty:
            return 0
        # snapshot consistent prefixes OFF the mutate (the decode thread
        # keeps appending; the store write must carry a stable n)
        batch = []
        for s in dirty:
            n = len(s.tokens)
            batch.append((s, n, s.status, s.to_store_doc(n, wid)))
        stolen: List[Session] = []
        written: List[tuple] = []
        budget = journal_byte_budget()
        evicted = {"n": 0}

        def mutate(doc):
            written.clear()
            stolen.clear()
            evicted["n"] = 0
            blob = doc.setdefault("sessions", {})
            for s, n, status, rec in batch:
                cur = blob.get(s.sid)
                if cur is not None and int(cur.get("fence") or 0) > s.fence:
                    # adopted elsewhere: the fence outran us — drop the
                    # write and stop decoding locally
                    stolen.append(s)
                    continue
                blob[s.sid] = rec
                written.append((s, n, status))
            # sweep + byte budget over THIS worker's records only (other
            # workers own their slices; never touch them)
            now = time.time()
            mine = [(k, r) for k, r in blob.items()
                    if r.get("owner") == wid]
            for k, r in mine:
                if (r.get("status") != "live"
                        and now - float(r.get("updated") or 0)
                        > FINISHED_TTL_S):
                    blob.pop(k, None)
            mine = [(k, r) for k, r in blob.items()
                    if r.get("owner") == wid]
            size = sum(len(json.dumps(r, default=str)) for _, r in mine)
            if size > budget:
                # finished first, then oldest live — bounded growth is
                # a hard property, not a best effort
                order = sorted(mine, key=lambda kr: (
                    kr[1].get("status") == "live",
                    float(kr[1].get("updated") or 0)))
                for k, r in order:
                    if size <= budget:
                        break
                    size -= len(json.dumps(r, default=str))
                    blob.pop(k, None)
                    evicted["n"] += 1

        store.update(mutate)
        obs = _SessionMetrics.get()
        if written:
            obs.journal_writes.inc()
        new_tokens = 0
        for s, n, status in written:
            new_tokens += max(0, n - s.journaled)
            s.journaled = max(s.journaled, n)
            s.status_journaled = status
        if new_tokens:
            obs.journal_tokens.inc(new_tokens)
        for s in stolen:
            s.stolen = True
            obs.lost_lease.inc()
            _faults.record_event("session_lost_lease", sid=s.sid,
                                 worker=wid)
        if evicted["n"]:
            obs.evicted.labels(surface="store").inc(evicted["n"])
        self._table._publish_live()
        return len(written)


# ------------------------------------------------------------ singletons
_table = SessionTable()
_journal = SessionJournal(_table)


def global_sessions() -> SessionTable:
    return _table


def global_journal() -> SessionJournal:
    return _journal


# -------------------------------------------------------------- adoption
def adopt(store, sid: str, worker_id: str) -> dict:
    """Fence-bump ``sid``'s store record to ``worker_id`` and return it.

    Runs inside ONE serialized ``SharedStore.update`` — the adoption and
    the fence bump are atomic, so exactly one survivor wins a contested
    orphan and the loser (or the stalled previous owner) is fenced off
    on its next journal write. Raises ``KeyError`` when the session was
    never journaled (nothing durable to adopt)."""
    out = {}

    def mutate(doc):
        blob = doc.setdefault("sessions", {})
        rec = blob.get(sid)
        if rec is None:
            raise KeyError(f"session {sid!r} is not in the store "
                           "(never journaled, or already swept)")
        rec = dict(rec)
        rec["fence"] = int(rec.get("fence") or 0) + 1
        prev = rec.get("owner")
        rec["owner"] = str(worker_id)
        rec["adopted_from"] = prev
        rec["resumed"] = int(rec.get("resumed") or 0) + 1
        rec["updated"] = time.time()
        blob[sid] = rec
        out.clear()
        out.update(rec)

    store.update(mutate)
    _SessionMetrics.get().adoptions.inc()
    _faults.record_event("session_adopt", sid=sid, worker=worker_id,
                         fence=out.get("fence"),
                         adopted_from=out.get("adopted_from"))
    return out


def store_record(store, sid: str) -> Optional[dict]:
    """Read one session record from the store (no fencing — the
    adoption decision path and the debug surfaces)."""
    try:
        doc = store.read()
    # graftlint: disable=typed-errors — a torn read answers "not found";
    # the caller's adoption attempt will surface the real failure
    except Exception:
        return None
    rec = (doc.get("sessions") or {}).get(sid)
    return dict(rec) if rec is not None else None


# -------------------------------------------------------------- snapshot
def snapshot() -> dict:
    """The ``/debug/sessions`` payload (also ``sessions.json`` in
    flight-recorder bundles)."""
    return {
        "enabled": sessions_enabled(),
        "worker": _journal.worker_id,
        "journal_attached": _journal.attached,
        "cadence_steps": journal_cadence_steps(),
        "byte_budget": journal_byte_budget(),
        "ring_capacity": ring_capacity(),
        "sessions": sorted((s.summary() for s in _table.items()),
                           key=lambda d: d["created"]),
    }


def reset_for_tests():
    """Drop every in-memory session and detach the journal (test
    teardown; mirrors the registry-reset discipline)."""
    _journal.detach()
    _table.clear()
