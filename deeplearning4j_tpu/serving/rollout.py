"""Canary rollout: SLO-gated promotion state machine with auto-rollback.

A candidate version moves through::

    shadow ──▶ canary@p% ──▶ ramp ──▶ full (promoted)
       │           │           │
       └───────────┴───────────┴──▶ rolled_back (drained)

- **shadow** — the candidate takes no user traffic; a deterministic
  sample of requests is *also* scored on it and the outputs compared
  (divergence accounting). Catches wrong-answer regressions before a
  single user sees one.
- **canary** — a hash-stable ``canary_fraction`` of traffic is answered
  by the candidate.
- **ramp** — the share steps through ``ramp_fractions``.
- **full** — the candidate is promoted to primary and the incumbent is
  gracefully drained.

Grading reuses the PR-3 SLO machinery verbatim: the rollout owns an
:class:`~deeplearning4j_tpu.observability.slo.SLOEngine` whose rules
compare the candidate's live per-version series against the incumbent's
(latency-quantile ratio), against absolute bounds (error rate), and
against the shadow-comparison record (divergence). Every
``window_requests`` candidate-involved requests the engine evaluates:
``ok`` extends the healthy streak (``healthy_windows`` consecutive ok
windows advance the stage), anything else — degraded *or* failing —
rolls back immediately: traffic snaps to the incumbent, the candidate
drains (in-flight requests resolve, typed or correct, never dropped),
and ``dl4j_serving_rollbacks_total`` increments.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional, Tuple

from deeplearning4j_tpu.observability.slo import (DEGRADED, FAILING, OK,
                                                  SLOEngine, SLORule, _grade)
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.serving.metrics import serving_metrics


class RolloutState:
    SHADOW = "shadow"
    CANARY = "canary"
    RAMP = "ramp"
    FULL = "full"
    ROLLED_BACK = "rolled_back"


_STAGE_NUM = {None: 0, RolloutState.SHADOW: 1, RolloutState.CANARY: 2,
              RolloutState.RAMP: 3, RolloutState.FULL: 4,
              RolloutState.ROLLED_BACK: 5}


@dataclasses.dataclass
class RolloutPolicy:
    """Thresholds and cadence of one rollout (constructor params, same
    posture as the SLO rules: ``None`` disables a grade)."""

    shadow_fraction: float = 0.1      # sampled for shadow scoring
    canary_fraction: float = 0.05     # first real traffic share
    ramp_fractions: Tuple[float, ...] = (0.25, 0.5)
    window_requests: int = 32         # candidate samples per evaluation
    window_seconds: Optional[float] = None
    # ^ time-based evaluation mode: when set, windows close on the
    # WALL CLOCK instead of on the candidate-sample count — a
    # low-traffic (e.g. generative) version still advances or rolls
    # back promptly instead of waiting forever for window_requests
    # samples. A timed window still needs ``window_min_requests``
    # candidate samples before it grades (zero-traffic candidates must
    # not promote on elapsed time alone).
    window_min_requests: int = 1      # candidate samples a timed window
                                      # needs before it may close
    healthy_windows: int = 2          # consecutive ok windows to advance
    latency_quantile: float = 0.5
    latency_ratio_degraded: Optional[float] = 2.0
    latency_ratio_failing: Optional[float] = 4.0
    min_latency_count: int = 16
    error_rate_degraded: Optional[float] = 0.02
    error_rate_failing: Optional[float] = 0.10
    min_requests: int = 16
    divergence_degraded: Optional[float] = 0.01
    divergence_failing: Optional[float] = 0.05
    min_shadow: int = 8
    divergence_rtol: float = 1e-4
    divergence_atol: float = 1e-5
    drain_timeout_s: float = 5.0
    start_stage: str = RolloutState.SHADOW


def _version_child(registry, metric: str, version: str):
    """A live labeled child without creating one (rules never create
    series — the same contract as the PR-3 rules)."""
    inst = registry.get(metric)
    if inst is None:
        return None
    for lvals, child in inst.series():
        if lvals == (version,):
            return child
    return None


def _child_value(registry, metric: str, version: str) -> float:
    child = _version_child(registry, metric, version)
    return float(child.value) if child is not None else 0.0


def _child_count(registry, metric: str, version: str) -> int:
    child = _version_child(registry, metric, version)
    return int(child.count) if child is not None else 0


class CanaryLatencyRatioRule(SLORule):
    """Candidate latency quantile / incumbent latency quantile — the
    per-version comparison the global p99 rule cannot make.

    ``base_counts`` are the per-version sample counts at rollout start:
    the rule refuses to grade until ``min_count`` NEW samples landed on
    both sides, so a redeployed version's earlier life cannot trip it
    on stale data alone. (The quantile itself is reservoir-lifetime —
    the honest limit the PR-3 latency rule also documents.)"""

    def __init__(self, candidate: str, incumbent: str, quantile: float,
                 degraded: Optional[float], failing: Optional[float],
                 min_count: int, base_counts=(0, 0)):
        super().__init__(
            "canary_latency_ratio",
            f"p{int(quantile * 100)} latency of {candidate!r} vs "
            f"{incumbent!r}")
        self.candidate, self.incumbent = candidate, incumbent
        self.quantile = quantile
        self.degraded, self.failing = degraded, failing
        self.min_count = min_count
        self.base_counts = base_counts

    def _evaluate(self, registry) -> dict:
        metric = "dl4j_serving_version_latency_seconds"
        cand = _version_child(registry, metric, self.candidate)
        inc = _version_child(registry, metric, self.incumbent)
        if cand is None or inc is None or min(
                cand.count - self.base_counts[0],
                inc.count - self.base_counts[1]) < self.min_count:
            return {"status": OK, "detail": f"<{self.min_count} samples"}
        cq = cand.quantile(self.quantile)
        iq = inc.quantile(self.quantile)
        if not (cq == cq and iq == iq and iq > 0):
            return {"status": OK, "detail": "quantiles unavailable"}
        ratio = cq / iq
        return {"status": _grade(ratio, self.degraded, self.failing),
                "value": ratio, "quantile": self.quantile,
                "candidate_seconds": cq, "incumbent_seconds": iq,
                "degraded_above": self.degraded,
                "failing_above": self.failing}


class CanaryErrorRateRule(SLORule):
    """Candidate errors / candidate requests (typed lifecycle outcomes
    already excluded at the counting site). Graded on the DELTA since
    rollout start (``base``): the per-version counters are
    process-lifetime, and a redeployed version must not inherit a
    previous attempt's errors."""

    def __init__(self, candidate: str, degraded: Optional[float],
                 failing: Optional[float], min_requests: int,
                 base=(0.0, 0.0)):
        super().__init__("canary_error_rate",
                         f"error rate of candidate {candidate!r}")
        self.candidate = candidate
        self.degraded, self.failing = degraded, failing
        self.min_requests = min_requests
        self.base = base          # (requests_at_start, errors_at_start)

    def _evaluate(self, registry) -> dict:
        requests = _child_value(
            registry, "dl4j_serving_version_requests_total",
            self.candidate) - self.base[0]
        if requests < self.min_requests:
            return {"status": OK,
                    "detail": f"<{self.min_requests} requests"}
        errors = _child_value(
            registry, "dl4j_serving_version_errors_total",
            self.candidate) - self.base[1]
        rate = max(0.0, errors) / requests
        return {"status": _grade(rate, self.degraded, self.failing),
                "value": rate, "requests": requests,
                "degraded_above": self.degraded,
                "failing_above": self.failing}


class ShadowDivergenceRule(SLORule):
    """Fraction of shadow-scored comparisons whose outputs diverged from
    the incumbent's (or errored) — wrong answers eject before traffic."""

    def __init__(self, candidate: str, degraded: Optional[float],
                 failing: Optional[float], min_shadow: int,
                 base=None):
        super().__init__("canary_shadow_divergence",
                         f"shadow divergence of candidate {candidate!r}")
        self.candidate = candidate
        self.degraded, self.failing = degraded, failing
        self.min_shadow = min_shadow
        # outcome -> count at rollout start (delta grading, same reason
        # as CanaryErrorRateRule)
        self.base = dict(base or {})

    def _evaluate(self, registry) -> dict:
        inst = registry.get("dl4j_serving_shadow_total")
        if inst is None:
            return {"status": OK, "detail": "no data"}
        counts = {"match": 0.0, "diverged": 0.0, "error": 0.0}
        for lvals, child in inst.series():
            if lvals[0] == self.candidate and lvals[1] in counts:
                counts[lvals[1]] = max(
                    0.0, child.value - self.base.get(lvals[1], 0.0))
        total = sum(counts.values())
        if total < self.min_shadow:
            return {"status": OK,
                    "detail": f"<{self.min_shadow} shadow comparisons"}
        rate = (counts["diverged"] + counts["error"]) / total
        return {"status": _grade(rate, self.degraded, self.failing),
                "value": rate, "comparisons": total,
                "degraded_above": self.degraded,
                "failing_above": self.failing}


class CanaryRollout:
    """See module doc. Constructed by
    :meth:`~deeplearning4j_tpu.serving.router.ServingRouter.begin_rollout`."""

    def __init__(self, router, registry, incumbent, candidate,
                 policy: RolloutPolicy):
        self._router = router
        self._registry = registry
        self.incumbent = incumbent
        self.candidate = candidate
        self.policy = policy
        # baseline the per-version series at rollout start: the counters
        # are process-lifetime, and a redeployed version (or a second
        # rollout attempt) must be graded on what happens DURING this
        # rollout, not on a previous attempt's record
        from deeplearning4j_tpu.observability import global_registry
        reg = global_registry()
        lat = "dl4j_serving_version_latency_seconds"
        shadow_base = {}
        inst = reg.get("dl4j_serving_shadow_total")
        if inst is not None:
            for lvals, child in inst.series():
                if lvals[0] == candidate.version:
                    shadow_base[lvals[1]] = float(child.value)
        self.engine = SLOEngine(rules=[
            CanaryLatencyRatioRule(
                candidate.version, incumbent.version,
                policy.latency_quantile, policy.latency_ratio_degraded,
                policy.latency_ratio_failing, policy.min_latency_count,
                base_counts=(_child_count(reg, lat, candidate.version),
                             _child_count(reg, lat, incumbent.version))),
            CanaryErrorRateRule(
                candidate.version, policy.error_rate_degraded,
                policy.error_rate_failing, policy.min_requests,
                base=(_child_value(
                          reg, "dl4j_serving_version_requests_total",
                          candidate.version),
                      _child_value(
                          reg, "dl4j_serving_version_errors_total",
                          candidate.version))),
            ShadowDivergenceRule(
                candidate.version, policy.divergence_degraded,
                policy.divergence_failing, policy.min_shadow,
                base=shadow_base),
        ])
        self._lock = threading.RLock()
        self._window_samples = 0
        self._window_started = time.monotonic()
        self._healthy_streak = 0
        self._ramp_idx = -1
        self.active = True
        self.rollback_reason: Optional[str] = None
        self.history: List[dict] = []
        self.last_report: Optional[dict] = None
        if policy.start_stage not in (RolloutState.SHADOW,
                                      RolloutState.CANARY):
            raise ValueError("start_stage must be 'shadow' or 'canary', "
                             f"got {policy.start_stage!r}")
        self.stage = policy.start_stage
        self.share = (0.0 if self.stage == RolloutState.SHADOW
                      else policy.canary_fraction)
        self._note_stage(None, self.stage)

    # ----------------------------------------------------------- plumbing
    def _note_stage(self, prev: Optional[str], new: str,
                    reason: Optional[str] = None):
        obs = serving_metrics()
        obs.stage.set(_STAGE_NUM[new])
        obs.traffic(self.candidate.version).set(self.share)
        obs.traffic(self.incumbent.version).set(1.0 - self.share)
        event = {"at": time.time(), "from": prev, "to": new,
                 "share": self.share}
        if reason:
            event["reason"] = reason
        self.history.append(event)
        _faults.record_event("rollout_stage", candidate=self.candidate.version,
                             from_stage=prev, to_stage=new, share=self.share,
                             **({"reason": reason} if reason else {}))

    # ---------------------------------------------------------- recording
    def record_candidate_event(self):
        """One candidate-involved request (canary-served or shadow-scored)
        completed. Request-count mode: every ``window_requests`` of them
        the SLO engine grades the canary. Time mode
        (``window_seconds`` set): the window closes on the wall clock
        instead — checked here AND on every routed request
        (:meth:`maybe_timed_evaluate`), so grading never needs the
        candidate to be busy."""
        with self._lock:
            if not self.active:
                return
            self._window_samples += 1
            if self.policy.window_seconds is not None:
                if not self._timed_window_closed_locked():
                    return
            else:
                if self._window_samples < self.policy.window_requests:
                    return
                self._window_samples = 0
        self.evaluate()

    def _timed_window_closed_locked(self) -> bool:
        """Time-mode window close check (caller holds the lock): enough
        wall time elapsed AND enough candidate samples landed. Resets
        the window on close."""
        p = self.policy
        if time.monotonic() - self._window_started < p.window_seconds:
            return False
        if self._window_samples < max(1, p.window_min_requests):
            return False
        self._window_started = time.monotonic()
        self._window_samples = 0
        return True

    def maybe_timed_evaluate(self):
        """Time-mode grading tick, called by the router on EVERY routed
        request while this rollout is active (cheap: one monotonic read
        under the lock). No-op in request-count mode."""
        if self.policy.window_seconds is None:
            return
        with self._lock:
            if not self.active or not self._timed_window_closed_locked():
                return
        self.evaluate()

    # --------------------------------------------------------- evaluation
    def evaluate(self) -> dict:
        """Grade the canary now: ok extends the healthy streak (and may
        advance the stage); degraded/failing rolls back. Returns the
        engine report. State bookkeeping happens under the lock; the
        drain/promotion itself runs AFTER it releases — a drain can wait
        ``drain_timeout_s`` and must not block every other
        candidate-path request (or ``/debug/deploy``) on the lock for
        that long."""
        with self._lock:
            if not self.active:
                return self.last_report or {"status": OK, "rules": []}
            report = self.engine.evaluate()
            self.last_report = report
            if report["status"] in (DEGRADED, FAILING):
                bad = (report["failing_rules"] or report["degraded_rules"])
                action = self._rollback_locked(
                    f"slo:{','.join(bad)} ({report['status']})")
            else:
                action = None
                self._healthy_streak += 1
                if self._healthy_streak >= self.policy.healthy_windows:
                    self._healthy_streak = 0
                    action = self._advance_locked()
        self._run_action(action)
        return report

    def _run_action(self, action: Optional[str]):
        """The post-transition work that must run WITHOUT the lock. New
        traffic is already steered by the (lock-free) share/stage reads,
        so nothing routes to a version between bookkeeping and drain."""
        if action == "rollback":
            # graceful drain: the candidate stops admitting, in-flight
            # requests resolve (typed or correct), executables release
            self.candidate.drain(timeout_s=self.policy.drain_timeout_s)
        elif action == "promote":
            # the router re-points primary, then gracefully drains the
            # old incumbent
            self._router._promote(self)

    def _advance_locked(self) -> Optional[str]:
        prev = self.stage
        if self.stage == RolloutState.SHADOW:
            self.stage = RolloutState.CANARY
            self.share = self.policy.canary_fraction
        elif self.stage in (RolloutState.CANARY, RolloutState.RAMP):
            self._ramp_idx += 1
            if self._ramp_idx < len(self.policy.ramp_fractions):
                self.stage = RolloutState.RAMP
                self.share = self.policy.ramp_fractions[self._ramp_idx]
            else:
                self.stage = RolloutState.FULL
                self.share = 1.0
                self.active = False
                self._note_stage(prev, self.stage)
                return "promote"
        self._note_stage(prev, self.stage)
        return None

    # ----------------------------------------------------------- rollback
    def rollback(self, reason: str = "manual"):
        with self._lock:
            action = self._rollback_locked(reason)
        self._run_action(action)

    def _rollback_locked(self, reason: str) -> Optional[str]:
        if not self.active:
            return None
        prev = self.stage
        self.stage = RolloutState.ROLLED_BACK
        self.share = 0.0
        self.active = False
        self.rollback_reason = reason
        serving_metrics().rollbacks.inc()
        self._note_stage(prev, self.stage, reason=reason)
        return "rollback"

    # ------------------------------------------------------------ queries
    def snapshot(self) -> dict:
        with self._lock:
            return {
                "candidate": self.candidate.version,
                "incumbent": self.incumbent.version,
                "stage": self.stage,
                "share": self.share,
                "active": self.active,
                "healthy_streak": self._healthy_streak,
                "window_samples": self._window_samples,
                "window_mode": ("time" if self.policy.window_seconds
                                is not None else "requests"),
                "window_seconds": self.policy.window_seconds,
                "rollback_reason": self.rollback_reason,
                "history": list(self.history),
                "last_report": self.last_report,
            }
