"""Zero-downtime serving: versioned deploys, canary rollout, drain.

Production model serving is not ``ParallelInference`` alone — it is the
lifecycle around it: a new version must be **warmed before it sees
traffic** (whole-program XLA compiles on the first request are exactly
the cold-start the AOT-everything posture of Fishman et al.
arXiv:1810.09868 exists to kill), promoted **gradually** under measured
SLOs, and **rolled back automatically** when it grades worse than the
incumbent — with in-flight requests drained, never dropped. The DL4J
heritage here is the model-zoo/serving layer (PAPER.md); the SLO gating
reuses the PR-3 rule engine and the PR-5 typed-failure machinery.

Three modules:

- :mod:`~deeplearning4j_tpu.serving.registry` — :class:`ModelRegistry`:
  ``deploy(version, net)`` builds a ``ParallelInference`` per version and
  AOT-warms every shape-bucket executable before the version is eligible
  for traffic (persistent compile cache under ``DL4J_TPU_COMPILE_CACHE``
  makes re-deploys and restarts skip compilation entirely);
  ``deploy_generative(version, engine)`` does the same for a generative
  decode version — a ``GenerationPipeline`` whose prefill, slot-insert,
  and decode-step executables all warm before traffic;
  ``retire(version)`` goes through graceful drain.
- :mod:`~deeplearning4j_tpu.serving.rollout` — :class:`CanaryRollout`:
  the shadow → canary → ramp → full / rolled-back state machine, graded
  by per-version SLO rules (latency-quantile ratio, error rate, shadow
  divergence) evaluated through a PR-3 :class:`SLOEngine`.
- :mod:`~deeplearning4j_tpu.serving.router` — :class:`ServingRouter`:
  the ``output()`` front-end that splits traffic deterministically by
  request hash, records ``dl4j_serving_version_*`` metrics, fires the
  ``serving.canary`` chaos point on the canary path, and under
  ``DL4J_TPU_ROLLOUT=0`` degrades to a byte-identical single-version
  passthrough.

Two further modules grow this into a *network* serving tier (the HTTP
front door PR):

- :mod:`~deeplearning4j_tpu.serving.frontdoor` — :class:`FrontDoor`:
  the HTTP/SSE wire surface (``POST /v1/classify``, ``POST /v1/generate``
  with per-token streaming, typed-error → status mapping, admission
  control, the ``http.request`` chaos point, ``dl4j_http_*`` metrics).
- :mod:`~deeplearning4j_tpu.serving.shared_state` — :class:`SharedStore`
  + :class:`SharedServingState`: the file-backed CAS store N worker
  processes coordinate through (one version set, consistent canary
  splits, fleet-aggregated SLO windows, shared drains) — with
  **lease-fenced leadership** (monotonic leader terms; a stale leader's
  write loses at write time, ``DL4J_TPU_FLEET_FENCE``), digest-validated
  reads with corruption quarantine + mirror-replay rebuild, and
  negative-clock-delta clamping throughout.
- :mod:`~deeplearning4j_tpu.serving.idempotency` — :class:`ResultJournal`:
  the front door's bounded, TTL'd ``X-Dl4j-Idempotency-Key`` → outcome
  journal (``DL4J_TPU_IDEMPOTENCY``): a retried key replays the original
  outcome without re-executing, so QoS token debt is charged exactly
  once per key — the safety the fleet proxy's connect-failover rides.
- :mod:`~deeplearning4j_tpu.serving.session` — :class:`Session` +
  :class:`SessionJournal`: the durable generation-session layer
  (``DL4J_TPU_SESSIONS``): every admitted generation journals its
  prompt hash, sampler seed and emitted-token log into the shared
  store at step boundaries, so a survivor worker can **adopt** an
  orphaned stream (lease-fenced), re-prefill ``prompt + emitted`` and
  continue the identical token sequence — mid-stream crash failover
  with exactly-once delivery, byte-identical under greedy.

Surfaces: ``UIServer GET /debug/deploy`` and ``deploy.json`` in
flight-recorder bundles both serve :func:`snapshot`;
``GET /debug/fleet`` and ``fleet.json`` serve
:func:`~deeplearning4j_tpu.serving.frontdoor.fleet_snapshot` (fence
state, corruption/rebuild evidence, the idempotency journal).
"""
from deeplearning4j_tpu.serving.errors import (RolloutConflictError,
                                               StoreLockTimeout)
from deeplearning4j_tpu.serving.frontdoor import (FrontDoor, fleet_snapshot,
                                                  frontdoor_enabled)
from deeplearning4j_tpu.serving.idempotency import (IDEMPOTENCY_HEADER,
                                                    ResultJournal,
                                                    idempotency_enabled)
from deeplearning4j_tpu.serving.registry import DeployedVersion, ModelRegistry
from deeplearning4j_tpu.serving.rollout import (CanaryRollout, RolloutPolicy,
                                                RolloutState)
from deeplearning4j_tpu.serving.router import ServingRouter, rollout_enabled
from deeplearning4j_tpu.serving.session import (Session, SessionJournal,
                                                SessionLost,
                                                sessions_enabled)
from deeplearning4j_tpu.serving.shared_state import (SharedServingState,
                                                     SharedStore,
                                                     fleet_fence_enabled)

__all__ = [
    "ModelRegistry", "DeployedVersion", "CanaryRollout", "RolloutPolicy",
    "RolloutState", "ServingRouter", "rollout_enabled", "snapshot",
    "FrontDoor", "frontdoor_enabled", "SharedStore", "SharedServingState",
    "RolloutConflictError", "StoreLockTimeout", "fleet_fence_enabled",
    "fleet_snapshot", "ResultJournal", "IDEMPOTENCY_HEADER",
    "idempotency_enabled", "Session", "SessionJournal", "SessionLost",
    "sessions_enabled",
]


def snapshot() -> dict:
    """The ``/debug/deploy`` + bundle ``deploy.json`` payload: every live
    registry's versions (state, warmup, traffic) and every live router's
    rollout state machine."""
    return {
        "rollout_enabled": rollout_enabled(),
        "registries": [r.snapshot() for r in list(ModelRegistry._live)],
        "routers": [r.snapshot() for r in list(ServingRouter._live)],
    }
