"""Label-bound serving-rollout instruments (one home, registry-reset safe).

Per-version series are labeled ``{version}`` — cardinality is bounded by
the number of versions a process ever deploys (a handful), the same
tradeoff the circuit-breaker ``{op}`` gauge makes.
"""
from __future__ import annotations

import threading

from deeplearning4j_tpu.observability import global_registry, on_registry_reset


class _ServingRolloutMetrics:
    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        reg = global_registry()
        self._requests = reg.counter(
            "dl4j_serving_version_requests_total",
            "ServingRouter requests routed, by model version",
            label_names=("version",))
        self._errors = reg.counter(
            "dl4j_serving_version_errors_total",
            "ServingRouter requests that raised a non-typed error, by "
            "model version (typed shed/deadline/shutdown outcomes "
            "excluded, matching the inference error-rate SLO)",
            label_names=("version",))
        self._latency = reg.histogram(
            "dl4j_serving_version_latency_seconds",
            "end-to-end routed request latency, by model version (the "
            "canary grader's latency-ratio numerator/denominator)",
            label_names=("version",))
        self._traffic = reg.gauge(
            "dl4j_serving_version_traffic_ratio",
            "configured traffic share per model version (1.0 = all "
            "traffic; the rollout state machine moves this)",
            label_names=("version",))
        self._warmup = reg.gauge(
            "dl4j_serving_version_warmup_seconds",
            "AOT warmup wall time the version paid at deploy, before "
            "becoming eligible for traffic", label_names=("version",))
        self._shadow = reg.counter(
            "dl4j_serving_shadow_total",
            "shadow-scored canary comparisons against the incumbent, by "
            "version and outcome (match / diverged / error)",
            label_names=("version", "outcome"))
        self.rollbacks = reg.counter(
            "dl4j_serving_rollbacks_total",
            "canary rollouts auto-rolled-back by the SLO gate (or rolled "
            "back explicitly)")
        self.stage = reg.gauge(
            "dl4j_serving_rollout_stage",
            "active rollout stage: 0 none, 1 shadow, 2 canary, 3 ramp, "
            "4 full, 5 rolled_back")

    def requests(self, version):
        return self._requests.labels(version=version)

    def errors(self, version):
        return self._errors.labels(version=version)

    def latency(self, version):
        return self._latency.labels(version=version)

    def traffic(self, version):
        return self._traffic.labels(version=version)

    def warmup_seconds(self, version):
        return self._warmup.labels(version=version)

    def shadow(self, version, outcome):
        return self._shadow.labels(version=version, outcome=outcome)

    @classmethod
    def get(cls) -> "_ServingRolloutMetrics":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance


def serving_metrics() -> _ServingRolloutMetrics:
    return _ServingRolloutMetrics.get()


@on_registry_reset
def _drop():
    _ServingRolloutMetrics._instance = None
