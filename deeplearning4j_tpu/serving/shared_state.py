"""Shared rollout state for multi-process serving: one version set, N workers.

One Python process used to own the whole deploy story (the PR-9 gap):
registry, rollout stage, and drain state all lived in process memory, so
a second server process could disagree with the first about which
version was primary, where the canary split sat, or whether a drain had
finished. This module moves that state to a **file-backed shared store**
so ``tools/serve.py --workers N`` processes serve ONE consistent version
set:

- :class:`SharedStore` — a single JSON document with an atomic
  compare-and-swap write path: every commit goes through
  tmp + ``os.replace`` + fsync (the ``utils/serialization`` atomic-write
  discipline) under an ``fcntl`` file lock, and carries a monotonically
  increasing ``rev`` stamp plus a **content digest**. Readers never lock
  (rename is atomic — a read sees a complete document or the previous
  one, never a torn one); writers CAS on ``rev``
  (:meth:`SharedStore.try_replace`) or serialize through
  :meth:`SharedStore.update`. The lock is crash-safe: flock releases
  when a SIGKILLed worker's fd closes — and the lock wait is BOUNDED
  (:data:`STORE_LOCK_TIMEOUT_S`, typed
  :class:`~deeplearning4j_tpu.serving.errors.StoreLockTimeout`), so a
  writer paused INSIDE its critical section cannot wedge the fleet.
- **Corruption recovery** — every read validates schema + digest; a
  corrupt/garbage document is **quarantined aside** (renamed next to the
  store, never deleted — it is postmortem evidence), counted
  (``dl4j_fleet_store_corruptions_total``), and the fleet document is
  **rebuilt** from worker re-registration plus each worker's local
  mirror of the sequenced history (the replay result of every
  transition it applied). Chaos drills drive this through the
  ``store.read`` / ``store.write`` fault points.
- :class:`SharedServingState` — the coordination layer the front door
  rides: worker registration + heartbeats + **lease-fenced leader
  election**, two serving *lanes* (``scoring`` / ``generative``) with a
  shared rollout state machine, deterministic hash-split routing every
  worker computes identically, and fleet-aggregated SLO windows the
  leader closes over aggregate deltas.

Lease-fenced leadership (``DL4J_TPU_FLEET_FENCE``, default on)
--------------------------------------------------------------
Heartbeat-only election is trusting: a SIGSTOP'd / GC-paused leader that
wakes after its TTL still believes ``is_leader`` and could close SLO
windows or move the rollout against a stale view. Under the fence the
store carries a ``leader`` record ``{worker, term, since}`` with a
**monotonically increasing term**:

- leadership changes ONLY when the holder's lease (its heartbeat)
  expires; the lowest-id alive worker then acquires with ``term + 1``
  (no lowest-id flap-back when a paused ex-leader wakes);
- every leader-only write (window close, stage advance, auto-rollback,
  promote) happens inside the serialized ``update`` transaction and is
  **fenced on the writer's term**: the transaction re-reads the leader
  record and a stale term means the write LOSES (the lane evaluation is
  skipped) instead of landing;
- demotion is detected at write time, counted
  (``dl4j_fleet_demotions_total``), and ringed;
- stage transitions are **monotonicity-guarded**: the stage can never
  move backward (canary ← ramp ← full) except via an explicit,
  history-sequenced rollback; every history event carries the writer's
  ``term`` so a drill can audit that terms are strictly monotonic with
  no interleaved fenced writes from two terms.

``DL4J_TPU_FLEET_FENCE=0`` restores the pre-fence lowest-alive-id
election byte-identically: no ``leader`` record, no term stamps, no
``dl4j_fleet_*`` leadership series.

Clock discipline: every heartbeat/window age is computed through
:func:`_age`, which clamps negative deltas to 0 — a wall-clock backward
jump reads as "fresh", never as instant leader death or an instantly
closed window.

A SIGKILLed worker's already-published window counters keep counting
toward the current window (its traffic happened); a respawned worker
reads the store at startup and **rejoins the same rollout stage** — the
kill/respawn drill in ``benchmarks/http_load.py`` pins both properties,
and ``--fleet-chaos`` adds the SIGSTOP-past-TTL + store-corruption
drill on top.
"""
from __future__ import annotations

import copy
import hashlib
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

try:
    import fcntl
except ImportError:                      # pragma: no cover - POSIX only
    fcntl = None

from deeplearning4j_tpu.observability.slo import DEGRADED, FAILING, OK, _grade
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.serving.errors import (RolloutConflictError,
                                               StoreLockTimeout)

#: the two serving surfaces a fleet coordinates (a lane = one primary +
#: at most one rollout; classify rides scoring, generate rides generative)
LANES = ("scoring", "generative")

#: shared-rollout stages (the store's state machine starts at canary —
#: shadow scoring needs request-level output comparison, which is a
#: single-process concern the local CanaryRollout already owns)
CANARY, RAMP, FULL, ROLLED_BACK = "canary", "ramp", "full", "rolled_back"

#: forward-only stage order (the monotonicity guard; ROLLED_BACK is the
#: one sanctioned backward move and it is always history-sequenced)
_STAGE_RANK = {CANARY: 1, RAMP: 2, FULL: 3}

#: grading policy of one shared rollout (stored IN the document so every
#: worker — including one spawned mid-rollout — grades from the same
#: thresholds; ``None`` disables a grade, like the local RolloutPolicy)
DEFAULT_POLICY = {
    "canary_fraction": 0.05,
    "ramp_fractions": (0.25, 0.5),
    "window_seconds": 0.5,          # wall-clock window the leader closes
    "window_min_requests": 8,       # candidate samples a window needs
    "healthy_windows": 2,           # consecutive ok windows to advance
    "error_rate_degraded": 0.02,
    "error_rate_failing": 0.10,
    "latency_ratio_degraded": 2.0,  # candidate mean / primary mean
    "latency_ratio_failing": 4.0,
    "min_latency_n": 8,             # samples BOTH sides need for the ratio
}

#: heartbeats older than this mark a worker dead (leader re-election);
#: sized generously above the front door's sync cadence
WORKER_TTL_S = 3.0

#: bounded file-lock wait — a writer SIGSTOPped inside its critical
#: section must not wedge every other worker's sync beat forever
STORE_LOCK_TIMEOUT_S = 10.0

_HISTORY_CAP = 128


def fleet_fence_enabled() -> bool:
    """``DL4J_TPU_FLEET_FENCE`` kill switch (read live): ``0`` restores
    the pre-fence lowest-alive-id leadership byte-identically — no
    leader record, no terms, no demotion series."""
    return os.environ.get("DL4J_TPU_FLEET_FENCE", "1") != "0"


def _now() -> float:
    """Wall-clock read, one spelling — tests mock THIS to simulate a
    regressing clock without patching the global ``time`` module."""
    return time.time()


def _age(now: float, then) -> float:
    """Age of a timestamp with negative deltas clamped to 0: a backward
    wall-clock jump must read as "fresh", never as instant leader death
    or an instantly-closed window."""
    try:
        return max(0.0, float(now) - float(then or 0.0))
    except (TypeError, ValueError):
        return float("inf")


# ------------------------------------------------------- fleet metrics
def _fleet_counter(name: str, help_text: str):
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(name, help_text)
    return _faults.cached_metric_handle(("fleet", name), make)


def _demotions_total():
    return _fleet_counter(
        "dl4j_fleet_demotions_total",
        "leaders demoted at write time: the worker believed it held the "
        "lease but the store's term had moved on — its fenced write "
        "lost instead of landing")


def _corruptions_total():
    return _fleet_counter(
        "dl4j_fleet_store_corruptions_total",
        "shared-store documents that failed schema/digest validation "
        "and were quarantined aside (never deleted)")


def _failovers_total():
    return _fleet_counter(
        "dl4j_fleet_failovers_total",
        "connect/first-byte failovers the fleet proxy performed onto "
        "another live worker (forwarding the idempotency key, so each "
        "retry was safe by construction); re-exported from the shared "
        "store's proxy record")


def _leader_term_gauge():
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().gauge(
            "dl4j_fleet_leader_term",
            "the shared store's current leader term (monotonically "
            "increasing; a bump means the previous lease expired)")
    return _faults.cached_metric_handle(("fleet", "leader_term"), make)


class SharedStore:
    """One JSON document, atomically replaced, rev-stamped, digest-
    validated. See module doc."""

    def __init__(self, path: str,
                 lock_timeout_s: float = STORE_LOCK_TIMEOUT_S):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self.lock_timeout_s = float(lock_timeout_s)
        self._file = os.path.join(path, "state.json")
        self._lockfile = os.path.join(path, ".state.lock")

    # -------------------------------------------------------------- read
    def read(self, _retries: int = 4) -> dict:
        """Lock-free validated read of the current document
        (``{"rev": 0}`` before the first commit). ``os.replace`` is
        atomic, so a reader racing a writer sees the old complete
        document, never a torn one. A document that parses but fails
        schema/digest validation is CORRUPT: quarantined aside and
        reported as empty — the fleet rebuilds it (see
        ``SharedServingState``)."""
        if _faults.armed():
            _faults.check("store.read")
        try:
            with open(self._file, encoding="utf-8") as f:
                ino = os.fstat(f.fileno()).st_ino
                raw = f.read()
        except OSError:
            return {"rev": 0}           # no document yet — a clean state
        try:
            doc = json.loads(raw)
        except ValueError:
            return self._quarantine("unparseable JSON", ino, _retries)
        problem = self._validate(doc)
        if problem is not None:
            return self._quarantine(problem, ino, _retries)
        return doc

    @staticmethod
    def _validate(doc) -> Optional[str]:
        """Schema + content-digest validation; None = good document."""
        if not isinstance(doc, dict):
            return f"document is {type(doc).__name__}, not an object"
        try:
            int(doc.get("rev", 0))
            int(doc.get("hseq", 0))
        except (TypeError, ValueError):
            return "rev/hseq not integral"
        for key in ("workers", "lanes", "windows", "leader"):
            if key in doc and not isinstance(doc[key], dict):
                return f"{key!r} is {type(doc[key]).__name__}, not an object"
        if "history" in doc and not isinstance(doc["history"], list):
            return "'history' is not a list"
        digest = doc.get("digest")
        if digest is not None and digest != _content_digest(doc):
            return "content digest mismatch (bit rot or a partial edit)"
        return None

    def _quarantine(self, problem: str, ino: int, retries: int) -> dict:
        """Move the corrupt document ASIDE (never delete — it is
        postmortem evidence), count it, and report the store empty so
        the fleet's rebuild path takes over. Racing readers both try
        the rename; exactly one wins, the loser finds nothing left.

        Readers are lock-free, so between our read and this rename a
        serialized writer may have COMMITTED a fresh good document —
        renaming that aside would throw away the fleet's latest state
        and count a phantom corruption. The inode check narrows the
        race to the stat→rename window (a committed doc is a NEW inode
        via tmp+``os.replace``): a moved-on inode means the corruption
        we read is already gone — re-read the current document
        instead."""
        try:
            if os.stat(self._file).st_ino != ino:
                if retries > 0:
                    return self.read(_retries=retries - 1)
                return {"rev": 0}       # doc keeps churning: stay empty
        except OSError:
            return {"rev": 0}           # already quarantined/removed
        aside = f"{self._file}.corrupt.{time.time_ns()}.{os.getpid()}"
        try:
            os.replace(self._file, aside)
        except OSError:
            aside = None                # another reader quarantined first
        if aside is not None:
            _corruptions_total().inc()
            _faults.record_event("store_corruption", problem=problem,
                                 quarantined=os.path.basename(aside))
        return {"rev": 0}

    # ------------------------------------------------------------- write
    @contextmanager
    def _locked(self, timeout_s: Optional[float] = None):
        if timeout_s is None:
            timeout_s = self.lock_timeout_s
        fd = os.open(self._lockfile, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            if fcntl is not None:
                deadline = time.monotonic() + timeout_s
                while True:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                        break
                    except OSError:
                        if time.monotonic() >= deadline:
                            raise StoreLockTimeout(
                                f"shared-store lock not acquired within "
                                f"{timeout_s:.1f}s — a writer died or "
                                "was paused inside its critical section")
                        time.sleep(0.01)
            yield
        finally:
            if fcntl is not None:
                try:
                    fcntl.flock(fd, fcntl.LOCK_UN)
                except OSError:         # pragma: no cover - defensive
                    pass
            os.close(fd)

    def _write(self, doc: dict):
        """tmp + fsync + atomic rename + directory fsync — a torn
        ``state.json`` must be impossible, even through a power cut
        (the ``utils/serialization`` atomic-write discipline). Stamps
        the content digest read() validates."""
        if _faults.armed():
            _faults.check("store.write")
        doc["digest"] = _content_digest(doc)
        tmp = f"{self._file}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._file)
        dirfd = os.open(os.path.dirname(self._file) or ".", os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)

    def try_replace(self, doc: dict, expected_rev: int) -> bool:
        """Compare-and-swap: commit ``doc`` only if the store is still at
        ``expected_rev``. Returns False (and writes nothing) on a lost
        race — the caller re-reads and retries."""
        with self._locked():
            cur = self.read()
            if int(cur.get("rev", 0)) != int(expected_rev):
                return False
            out = dict(doc)
            out["rev"] = int(expected_rev) + 1
            out["stamp"] = time.time()
            self._write(out)
            return True

    def update(self, mutate: Callable[[dict], Optional[dict]]) -> dict:
        """Serialized read-modify-write: run ``mutate(doc)`` (edit in
        place or return a replacement) under the file lock and commit
        with a bumped ``rev``. A raising ``mutate`` commits nothing."""
        with self._locked():
            doc = self.read()
            rev = int(doc.get("rev", 0))
            out = mutate(doc)
            if out is None:
                out = doc
            out["rev"] = rev + 1
            out["stamp"] = time.time()
            self._write(out)
            return out


def _content_digest(doc: dict) -> str:
    """Canonical digest over everything except the digest field itself
    (sorted keys, so writer dict order never matters)."""
    body = {k: v for k, v in doc.items() if k != "digest"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True, default=str).encode()
    ).hexdigest()[:24]


def _zero() -> dict:
    return {"n": 0, "err": 0, "lat_sum": 0.0, "lat_n": 0}


def _agg(windows: dict, version: str) -> dict:
    """Sum one version's cumulative counters across every worker that
    ever published (dead workers included — their traffic happened)."""
    out = _zero()
    for per_worker in windows.values():
        w = per_worker.get(version)
        if not isinstance(w, dict):
            continue
        out["n"] += int(w.get("n", 0))
        out["err"] += int(w.get("err", 0))
        out["lat_sum"] += float(w.get("lat_sum", 0.0))
        out["lat_n"] += int(w.get("lat_n", 0))
    return out


def _delta(cur: dict, base: Optional[dict]) -> dict:
    base = base or _zero()
    return {k: max(0, cur[k] - base.get(k, 0)) if k != "lat_sum"
            else max(0.0, cur[k] - base.get(k, 0.0)) for k in cur}


class SharedServingState:
    """One worker's handle on the shared store. See module doc."""

    def __init__(self, store: SharedStore, worker_id: str,
                 routing_ttl_s: float = 0.2):
        self.store = store
        self.worker_id = str(worker_id)
        self._lock = threading.Lock()
        self._pending: Dict[str, dict] = {}       # version -> delta counters
        self._routing_ttl = float(routing_ttl_s)
        self._routing_cache: Tuple[float, dict] = (0.0, {})
        # the last routing view computed from a GOOD document — never
        # invalidated (only replaced), so a store blip or the one-beat
        # quarantine blackout can always serve stale-but-available
        self._last_good_view: dict = {}
        # history watermark starts at the store's CURRENT head: a fresh
        # handle (respawned worker) must adopt the present state, never
        # replay transitions it wasn't alive for (register() re-anchors
        # it too, but the sync thread may beat register in a race)
        try:
            self._applied_seq = int(store.read().get("hseq", 0))
        # graftlint: disable=typed-errors — a store blip (injected
        # store.read fault, transient fs) at construction must not kill
        # the worker; register() re-anchors the watermark right after
        except Exception:
            self._applied_seq = 0
        self._is_leader = False
        # the lease term this worker believes it leads under (None =
        # follower); compared against the store INSIDE every serialized
        # write — the fence
        self._term: Optional[int] = None
        self._demotions = 0
        self._rebuilds = 0
        self._failovers_seen = 0
        # this worker's own announcement (pid, port): re-applied on
        # every beat whose doc lacks it — the "worker re-registration"
        # half of the corruption-rebuild story
        self._reg: Optional[Tuple[int, int]] = None
        # local mirror of the durable fleet facts (lanes after every
        # applied transition + the sequenced history + leader term):
        # the rebuild source when the store doc is quarantined
        self._mirror: Optional[dict] = None

    # ------------------------------------------------------- registration
    def register(self, pid: int, port: int):
        """Announce this worker (called once at startup; the respawn
        drill re-registers under the same worker id and inherits the
        store's current stage — nothing here resets rollout state)."""
        wid = self.worker_id
        self._reg = (int(pid), int(port))

        def mutate(doc):
            self._maybe_rebuild(doc)
            workers = doc.setdefault("workers", {})
            workers[wid] = {"pid": int(pid), "port": int(port),
                            "heartbeat": _now(),
                            "started": _now()}
            doc.setdefault("lanes", {})
            doc.setdefault("windows", {}).setdefault(wid, {})
            doc.setdefault("history", [])
            doc.setdefault("hseq", 0)
        out = self.store.update(mutate)
        self._remember(out)
        # a (re)registered worker must not re-apply the fleet's past
        # transitions — its local deploys already reflect store state
        self._applied_seq = int(out.get("hseq", 0))

    def ensure_lane(self, lane: str, primary: str):
        """Set the lane's primary IF the lane is new — a respawned
        worker must adopt the fleet's current primary, not reset it."""
        if lane not in LANES:
            raise ValueError(f"unknown lane {lane!r}; one of {LANES}")

        def mutate(doc):
            self._maybe_rebuild(doc)
            lanes = doc.setdefault("lanes", {})
            lanes.setdefault(lane, {"primary": primary, "rollout": None})
        self._remember(self.store.update(mutate))

    # ------------------------------------------------------------ routing
    def routing(self, lane: str) -> dict:
        """The lane's live routing view (cached ``routing_ttl_s`` so the
        hot path reads the store a few times a second, not per request):
        ``{"primary", "candidate", "stage", "share", "active"}``.
        A failing store READ (injected ``store.read`` fault, transient
        fs) serves the last cached view — stale-but-available beats
        failing live traffic over a coordination-plane blip."""
        now = time.monotonic()
        with self._lock:
            at, cache = self._routing_cache
            if now - at < self._routing_ttl and lane in cache:
                return cache[lane]
        try:
            doc = self.store.read()
        # graftlint: disable=typed-errors — availability policy: a store
        # read blip must not fail live requests; the stale cached view
        # answers and the next beat refreshes it
        except Exception:
            _faults.record_event("store_read_fallback", lane=lane)
            return self._fallback_view(lane)
        if not doc.get("lanes"):
            with self._lock:
                have_good = bool(self._last_good_view)
            if have_good:
                # an empty document while we remember lanes = the doc
                # was just quarantined (corruption) and the rebuild
                # beat hasn't landed yet — a one-beat blackout must not
                # 404 live traffic; serve the last good view
                _faults.record_event("store_read_fallback", lane=lane,
                                     reason="empty_doc")
                return self._fallback_view(lane)
        view = {}
        for ln, st in (doc.get("lanes") or {}).items():
            ro = st.get("rollout") or {}
            view[ln] = {
                "primary": st.get("primary"),
                "candidate": ro.get("candidate"),
                "stage": ro.get("stage"),
                "share": float(ro.get("share", 0.0)),
                "active": bool(ro.get("active")),
            }
        with self._lock:
            self._routing_cache = (now, view)
            if view:
                self._last_good_view = view
        return view.get(lane, {"primary": None, "candidate": None,
                               "stage": None, "share": 0.0,
                               "active": False})

    def _fallback_view(self, lane: str) -> dict:
        """Stale-but-available routing: the TTL cache if it has the
        lane, else the last view computed from a good document."""
        with self._lock:
            _, cache = self._routing_cache
            view = cache.get(lane) or self._last_good_view.get(lane)
        return view or {"primary": None, "candidate": None,
                        "stage": None, "share": 0.0, "active": False}

    def pick(self, lane: str, frac: float) -> Tuple[Optional[str], bool]:
        """Deterministic hash-split: ``(version, is_canary)`` for one
        request's routing coordinate — every worker computes the same
        answer for the same request because both inputs (content hash,
        store share) are shared."""
        r = self.routing(lane)
        if (r["active"] and r["share"] > 0.0 and r["candidate"]
                and frac < r["share"]):
            return r["candidate"], True
        return r["primary"], False

    # ---------------------------------------------------------- recording
    def record(self, version: str, ok: bool, latency_s: float):
        """Accumulate one served request locally (flushed to the store by
        :meth:`sync` — per-request store writes would serialize the
        fleet on the file lock)."""
        with self._lock:
            w = self._pending.setdefault(version, _zero())
            w["n"] += 1
            if not ok:
                w["err"] += 1
            w["lat_sum"] += float(latency_s)
            w["lat_n"] += 1

    # ------------------------------------------------------------ rollout
    def begin_rollout(self, lane: str, candidate: str,
                      policy: Optional[dict] = None) -> dict:
        """Start a shared rollout of ``candidate`` on ``lane`` (refused
        while one is active — same contract as the local router)."""
        pol = dict(DEFAULT_POLICY)
        pol.update(policy or {})
        pol["ramp_fractions"] = list(pol["ramp_fractions"])

        def mutate(doc):
            self._maybe_rebuild(doc)
            st = (doc.setdefault("lanes", {})
                  .setdefault(lane, {"primary": None, "rollout": None}))
            ro = st.get("rollout")
            if ro and ro.get("active"):
                raise RolloutConflictError(
                    f"a shared rollout of {ro.get('candidate')!r} is "
                    f"already active on lane {lane!r}")
            if not st.get("primary"):
                raise RolloutConflictError(
                    f"lane {lane!r} has no primary to canary against "
                    "(ensure_lane first)")
            if st.get("primary") == candidate:
                raise ValueError("candidate is already the primary")
            windows = doc.get("windows") or {}
            st["rollout"] = {
                "candidate": candidate,
                "stage": CANARY,
                "share": float(pol["canary_fraction"]),
                "ramp_idx": -1,
                "healthy_streak": 0,
                "active": True,
                "reason": None,
                "policy": pol,
                "started": _now(),
                "window_started": _now(),
                # (a NEW rollout legally starts back at canary: the
                # monotonicity guard reads THIS dict, which replaces
                # the previous rollout's wholesale)
                # baseline at start: the fleet's lifetime counters must
                # not grade this rollout (the delta discipline the local
                # canary rules follow)
                "window_base": {
                    candidate: _agg(windows, candidate),
                    st.get("primary"): _agg(windows, st.get("primary")),
                },
            }
            self._note(doc, lane, None, CANARY,
                       share=pol["canary_fraction"],
                       **self._writer_stamp(doc, manual=True))
        out = self.store.update(mutate)
        self._remember(out)
        self._invalidate()
        return out

    def rollback(self, lane: str, reason: str = "manual") -> dict:
        """Explicit rollback — the ONE sanctioned backward stage move,
        always history-sequenced (the monotonicity guard's escape
        hatch)."""
        def mutate(doc):
            self._maybe_rebuild(doc)
            st = (doc.get("lanes") or {}).get(lane) or {}
            ro = st.get("rollout")
            if not ro or not ro.get("active"):
                return
            prev = ro["stage"]
            ro.update(stage=ROLLED_BACK, share=0.0, active=False,
                      reason=reason)
            self._note(doc, lane, prev, ROLLED_BACK, share=0.0,
                       reason=reason,
                       **self._writer_stamp(doc, manual=True))
        out = self.store.update(mutate)
        self._remember(out)
        self._invalidate()
        return out

    def _writer_stamp(self, doc: dict, manual: bool = False) -> dict:
        """The term stamp a history event carries under the fence (the
        drill's strict-monotonicity audit reads it). With the fence OFF
        events stay byte-identical to the pre-fence format — no new
        keys."""
        if not fleet_fence_enabled():
            return {}
        led = doc.get("leader") or {}
        out = {"term": int(led.get("term", 0))}
        if manual:
            out["manual"] = True
        return out

    @staticmethod
    def _note(doc: dict, lane: str, prev: Optional[str], new: str,
              **attrs):
        doc["hseq"] = int(doc.get("hseq", 0)) + 1
        event = {"seq": doc["hseq"], "at": _now(), "lane": lane,
                 "from": prev, "to": new}
        ro = ((doc.get("lanes") or {}).get(lane) or {}).get("rollout") or {}
        event["candidate"] = ro.get("candidate")
        event["primary"] = ((doc.get("lanes") or {}).get(lane)
                            or {}).get("primary")
        event.update(attrs)
        history = doc.setdefault("history", [])
        history.append(event)
        del history[:-_HISTORY_CAP]

    # ---------------------------------------------------------------- sync
    def sync(self) -> List[dict]:
        """One coordination beat (the front door's background thread
        calls this a few times a second): flush locally-accumulated
        window counters, heartbeat, maintain the leader lease, and —
        when this worker HOLDS the lease — close due windows over the
        FLEET aggregate and advance/roll back the shared stage, fenced
        on the lease term (see module doc). Returns the history events
        this worker has not yet applied locally (promotions/rollbacks →
        the caller repoints and drains its local deploys)."""
        with self._lock:
            pending, self._pending = self._pending, {}
        wid = self.worker_id

        def mutate(doc):
            self._maybe_rebuild(doc)
            workers = doc.setdefault("workers", {})
            me = workers.setdefault(wid, {"pid": os.getpid(), "port": 0,
                                          "started": _now()})
            if self._reg is not None and not me.get("port"):
                # re-registration: a rebuilt/reset doc lost this
                # worker's announcement — restore it or the proxy never
                # routes to this worker again
                me["pid"], me["port"] = self._reg
            me["heartbeat"] = _now()
            mine = doc.setdefault("windows", {}).setdefault(wid, {})
            for version, d in pending.items():
                w = mine.setdefault(version, _zero())
                w["n"] += d["n"]
                w["err"] += d["err"]
                w["lat_sum"] += d["lat_sum"]
                w["lat_n"] += d["lat_n"]
            now = _now()
            alive = sorted(
                w for w, rec in workers.items()
                if _age(now, rec.get("heartbeat", 0)) <= WORKER_TTL_S)
            if fleet_fence_enabled():
                self._fenced_leadership(doc, alive, now)
            else:
                # pre-fence semantics, byte-identical: lowest alive id
                # leads, no terms, no demotion accounting
                self._is_leader = bool(alive) and min(alive) == wid
                self._term = None
            if self._is_leader:
                for lane, st in (doc.get("lanes") or {}).items():
                    self._evaluate_lane(doc, lane, st)
        try:
            doc = self.store.update(mutate)
        except BaseException:
            # a failed store write must not LOSE the popped window
            # counters — merge them back so the next beat flushes them
            # (dropped samples would let the leader grade a window that
            # silently undercounts a failing candidate's errors)
            with self._lock:
                for version, d in pending.items():
                    w = self._pending.setdefault(version, _zero())
                    for k in d:
                        w[k] += d[k]
            raise
        self._remember(doc)
        self._invalidate()
        # re-export the proxy's failover count as a scrapeable worker
        # series (the proxy process itself has no /metrics surface);
        # the series only exists once a failover actually happened
        prox = doc.get("proxy") or {}
        try:
            fo = int(prox.get("failovers", 0))
        except (TypeError, ValueError):
            fo = 0
        if fo > self._failovers_seen:
            _failovers_total().inc(fo - self._failovers_seen)
            self._failovers_seen = fo
        elif fo < self._failovers_seen:
            self._failovers_seen = fo        # proxy restarted / rebuilt
        events = [e for e in doc.get("history", [])
                  if int(e.get("seq", 0)) > self._applied_seq]
        if events:
            self._applied_seq = max(int(e["seq"]) for e in events)
        return events

    # ------------------------------------------------------- leadership
    def _fenced_leadership(self, doc: dict, alive: List[str], now: float):
        """Lease maintenance + the write-time fence (runs INSIDE the
        serialized update — atomic with any leader-only write this beat
        performs). Leadership moves ONLY when the holder's lease (its
        heartbeat) expires; the successor bumps the term."""
        wid = self.worker_id
        led = doc.get("leader") or {}
        holder = led.get("worker")
        holder_rec = (doc.get("workers") or {}).get(holder) \
            if holder else None
        holder_alive = (
            holder_rec is not None
            and _age(now, holder_rec.get("heartbeat", 0)) <= WORKER_TTL_S)
        if not holder_alive and alive and min(alive) == wid:
            term = int(led.get("term", 0)) + 1
            doc["leader"] = {"worker": wid, "term": term, "since": now}
            _faults.record_event("leader_acquired", worker=wid, term=term,
                                 previous=holder)
        led = doc.get("leader") or {}
        cur_term = int(led.get("term", 0))
        i_lead = led.get("worker") == wid
        if (self._is_leader and self._term is not None
                and (not i_lead or cur_term != self._term)):
            # the fence caught a stale leader AT WRITE TIME: this worker
            # believed it held term N but the store moved on — its
            # leader-only writes this beat lose (skipped), counted
            self._demotions += 1
            _demotions_total().inc()
            _faults.record_event("leader_demoted", worker=wid,
                                 stale_term=self._term,
                                 current_term=cur_term,
                                 current_leader=led.get("worker"))
        self._is_leader = i_lead
        self._term = cur_term if i_lead else None
        _leader_term_gauge().set(float(cur_term))

    def _guard_stage(self, doc: dict, lane: str, ro: dict,
                     new_stage: str, new_idx: Optional[int] = None) -> bool:
        """Monotonicity guard: the stage can never move backward — and
        within RAMP the ramp index can never decrease — except via the
        explicit, history-sequenced ROLLED_BACK transition. A blocked
        move is ringed, never applied."""
        if new_stage == ROLLED_BACK:
            return True
        cur = ro.get("stage")
        backward = (_STAGE_RANK.get(new_stage, 0) < _STAGE_RANK.get(cur, 0)
                    or (new_stage == RAMP and cur == RAMP
                        and new_idx is not None
                        and new_idx < int(ro.get("ramp_idx", -1))))
        if backward:
            _faults.record_event("stage_regression_blocked", lane=lane,
                                 worker=self.worker_id,
                                 current=cur, attempted=new_stage,
                                 term=self._term)
            return False
        return True

    def _evaluate_lane(self, doc: dict, lane: str, st: dict):
        """Leader-only, fenced by the caller: close the lane's window if
        due and grade the fleet-aggregated deltas (error rate +
        latency-mean ratio; any non-ok grade rolls back, ok streaks
        advance — the local CanaryRollout's promotion discipline over
        shared counters)."""
        ro = st.get("rollout")
        if not ro or not ro.get("active"):
            return
        pol = ro.get("policy") or DEFAULT_POLICY
        now = _now()
        if _age(now, ro.get("window_started", now)) \
                < float(pol["window_seconds"]):
            return
        windows = doc.get("windows") or {}
        cand, prim = ro["candidate"], st.get("primary")
        base = ro.get("window_base") or {}
        cand_cur = _agg(windows, cand)
        prim_cur = _agg(windows, prim)
        d_cand = _delta(cand_cur, base.get(cand))
        d_prim = _delta(prim_cur, base.get(prim))
        if d_cand["n"] < int(pol["window_min_requests"]):
            return          # window stays open until samples arrive
        status = OK
        detail = {}
        rate = d_cand["err"] / d_cand["n"]
        detail["error_rate"] = rate
        status = _worst(status, _grade(rate, pol["error_rate_degraded"],
                                       pol["error_rate_failing"]))
        if (d_cand["lat_n"] >= int(pol["min_latency_n"])
                and d_prim["lat_n"] >= int(pol["min_latency_n"])
                and d_prim["lat_sum"] > 0):
            ratio = ((d_cand["lat_sum"] / d_cand["lat_n"])
                     / (d_prim["lat_sum"] / d_prim["lat_n"]))
            detail["latency_ratio"] = ratio
            status = _worst(status, _grade(
                ratio, pol["latency_ratio_degraded"],
                pol["latency_ratio_failing"]))
        ro["window_started"] = now
        ro["window_base"] = {cand: cand_cur, prim: prim_cur}
        ro["last_report"] = dict(detail, status=status,
                                 window_requests=d_cand["n"],
                                 **self._writer_stamp(doc))
        if status in (DEGRADED, FAILING):
            prev = ro["stage"]
            ro.update(stage=ROLLED_BACK, share=0.0, active=False,
                      reason=f"slo:{status} {detail}")
            self._note(doc, lane, prev, ROLLED_BACK, share=0.0,
                       reason=ro["reason"], **self._writer_stamp(doc))
            return
        ro["healthy_streak"] = int(ro.get("healthy_streak", 0)) + 1
        if ro["healthy_streak"] < int(pol["healthy_windows"]):
            return
        ro["healthy_streak"] = 0
        prev = ro["stage"]
        ramp = list(pol.get("ramp_fractions") or ())
        idx = int(ro.get("ramp_idx", -1)) + 1
        if idx < len(ramp):
            if not self._guard_stage(doc, lane, ro, RAMP, idx):
                return
            ro.update(stage=RAMP, share=float(ramp[idx]), ramp_idx=idx)
            self._note(doc, lane, prev, RAMP, share=ro["share"],
                       **self._writer_stamp(doc))
        else:
            if not self._guard_stage(doc, lane, ro, FULL):
                return
            old_primary = st.get("primary")
            ro.update(stage=FULL, share=1.0, active=False)
            st["primary"] = ro["candidate"]
            self._note(doc, lane, prev, FULL, share=1.0,
                       old_primary=old_primary, **self._writer_stamp(doc))

    # ------------------------------------------------ corruption rebuild
    def _remember(self, doc: dict):
        """Mirror the durable fleet facts this worker just observed in a
        COMMITTED document — the rebuild source after a quarantine."""
        try:
            lanes = copy.deepcopy(doc.get("lanes") or {})
        # graftlint: disable=typed-errors — the mirror is best-effort
        # redundancy; an uncopyable doc just skips one refresh
        except Exception:
            return
        with self._lock:
            self._mirror = {
                "rev": int(doc.get("rev", 0)),
                "hseq": int(doc.get("hseq", 0)),
                "lanes": lanes,
                "history": list(doc.get("history") or ()),
                "leader_term": int((doc.get("leader") or {})
                                   .get("term", 0)),
            }

    def _maybe_rebuild(self, doc: dict):
        """Inside a serialized write: when the document's rev regressed
        below this worker's mirror (a corrupt doc was quarantined and
        the store restarted empty), rebuild the fleet state — lanes
        restored to the replay result of every applied history event,
        the history itself re-seeded, the leader term carried forward
        (monotonicity survives the rebuild), and the active rollout's
        window re-baselined (its old aggregates died with the doc).
        Workers merge additively: the first rebuilder seeds, later ones
        only add lanes/history the seed lacked."""
        with self._lock:
            m = dict(self._mirror) if self._mirror else None
        if m is None or int(doc.get("rev", 0)) >= m["rev"]:
            return
        lanes = doc.setdefault("lanes", {})
        for lane, st in (m["lanes"] or {}).items():
            if lane in lanes:
                continue
            restored = copy.deepcopy(st)
            ro = restored.get("rollout")
            if ro and ro.get("active"):
                # the fleet's window counters died with the doc: an old
                # baseline would hold every delta at zero until the new
                # counters caught up — re-baseline at zero instead
                ro["window_base"] = {}
                ro["window_started"] = _now()
            lanes[lane] = restored
        if int(doc.get("hseq", 0)) < m["hseq"]:
            doc["hseq"] = m["hseq"]
            doc["history"] = list(m["history"])
        led = doc.get("leader") or {}
        if int(led.get("term", 0)) < m["leader_term"]:
            # term continuity: the next acquisition must bump PAST every
            # term ever granted, or the strict-monotonicity audit breaks
            doc["leader"] = {"worker": None, "term": m["leader_term"],
                             "since": _now()}
        doc["rebuilt"] = {"at": _now(), "by": self.worker_id,
                          "hseq": m["hseq"],
                          "n": int((doc.get("rebuilt") or {})
                                   .get("n", 0)) + 1}
        self._rebuilds += 1
        _faults.record_event("store_rebuilt", worker=self.worker_id,
                             hseq=m["hseq"], from_rev=m["rev"])

    def _invalidate(self):
        with self._lock:
            self._routing_cache = (0.0, {})

    # ------------------------------------------------------------ queries
    @property
    def is_leader(self) -> bool:
        return self._is_leader

    @property
    def leader_term(self) -> Optional[int]:
        """The term this worker currently leads under (None = follower)."""
        return self._term

    def alive_workers(self, ttl_s: float = WORKER_TTL_S) -> Dict[str, dict]:
        now = _now()
        return {w: rec for w, rec
                in (self.store.read().get("workers") or {}).items()
                if _age(now, rec.get("heartbeat", 0)) <= ttl_s}

    def snapshot(self) -> dict:
        doc = self.store.read()
        now = _now()
        workers = {
            w: dict(rec, alive=(_age(now, rec.get("heartbeat", 0))
                                <= WORKER_TTL_S))
            for w, rec in (doc.get("workers") or {}).items()}
        return {
            "path": self.store.path,
            "rev": doc.get("rev", 0),
            "worker_id": self.worker_id,
            "is_leader": self._is_leader,
            "fence": {
                "enabled": fleet_fence_enabled(),
                "leader": doc.get("leader"),
                "term": self._term,
                "demotions": self._demotions,
                "rebuilds": self._rebuilds,
            },
            "rebuilt": doc.get("rebuilt"),
            "proxy": doc.get("proxy"),
            "lanes": doc.get("lanes", {}),
            "workers": workers,
            "history": doc.get("history", [])[-16:],
        }


_SEVERITY = {OK: 0, DEGRADED: 1, FAILING: 2}


def _worst(a: str, b: str) -> str:
    return a if _SEVERITY[a] >= _SEVERITY[b] else b
