"""Shared rollout state for multi-process serving: one version set, N workers.

One Python process used to own the whole deploy story (the PR-9 gap):
registry, rollout stage, and drain state all lived in process memory, so
a second server process could disagree with the first about which
version was primary, where the canary split sat, or whether a drain had
finished. This module moves that state to a **file-backed shared store**
so ``tools/serve.py --workers N`` processes serve ONE consistent version
set:

- :class:`SharedStore` — a single JSON document with an atomic
  compare-and-swap write path: every commit goes through
  tmp + ``os.replace`` + fsync (the ``utils/serialization`` atomic-write
  discipline) under an ``fcntl`` file lock, and carries a monotonically
  increasing ``rev`` stamp. Readers never lock (rename is atomic — a
  read sees a complete document or the previous one, never a torn one);
  writers CAS on ``rev`` (:meth:`SharedStore.try_replace`) or serialize
  through :meth:`SharedStore.update`. The lock is crash-safe: flock
  releases when a SIGKILLed worker's fd closes.
- :class:`SharedServingState` — the coordination layer the front door
  rides: worker registration + heartbeats + leader election (lowest
  alive worker id), two serving *lanes* (``scoring`` / ``generative``)
  each with a primary and an optional shared rollout, deterministic
  hash-split routing every worker computes identically
  (``request_fraction`` is content-hashed, the share comes from the
  store — so the same request canaries on every worker or on none), and
  **fleet-aggregated SLO windows**: every worker publishes its
  per-version request/error/latency counters into the store; the leader
  closes time windows over the *aggregate* deltas and advances or rolls
  back the shared stage. Transitions land in a sequenced history each
  worker applies locally (promote → repoint + drain the old incumbent;
  rolled_back → drain the candidate) — graceful drains happen in every
  process, driven by one decision.

A SIGKILLed worker's already-published window counters keep counting
toward the current window (its traffic happened); a respawned worker
reads the store at startup and **rejoins the same rollout stage** — the
kill/respawn drill in ``benchmarks/http_load.py`` pins both properties.
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

try:
    import fcntl
except ImportError:                      # pragma: no cover - POSIX only
    fcntl = None

from deeplearning4j_tpu.observability.slo import DEGRADED, FAILING, OK, _grade
from deeplearning4j_tpu.serving.errors import RolloutConflictError

#: the two serving surfaces a fleet coordinates (a lane = one primary +
#: at most one rollout; classify rides scoring, generate rides generative)
LANES = ("scoring", "generative")

#: shared-rollout stages (the store's state machine starts at canary —
#: shadow scoring needs request-level output comparison, which is a
#: single-process concern the local CanaryRollout already owns)
CANARY, RAMP, FULL, ROLLED_BACK = "canary", "ramp", "full", "rolled_back"

#: grading policy of one shared rollout (stored IN the document so every
#: worker — including one spawned mid-rollout — grades from the same
#: thresholds; ``None`` disables a grade, like the local RolloutPolicy)
DEFAULT_POLICY = {
    "canary_fraction": 0.05,
    "ramp_fractions": (0.25, 0.5),
    "window_seconds": 0.5,          # wall-clock window the leader closes
    "window_min_requests": 8,       # candidate samples a window needs
    "healthy_windows": 2,           # consecutive ok windows to advance
    "error_rate_degraded": 0.02,
    "error_rate_failing": 0.10,
    "latency_ratio_degraded": 2.0,  # candidate mean / primary mean
    "latency_ratio_failing": 4.0,
    "min_latency_n": 8,             # samples BOTH sides need for the ratio
}

#: heartbeats older than this mark a worker dead (leader re-election);
#: sized generously above the front door's sync cadence
WORKER_TTL_S = 3.0

_HISTORY_CAP = 128


class SharedStore:
    """One JSON document, atomically replaced, rev-stamped. See module doc."""

    def __init__(self, path: str):
        os.makedirs(path, exist_ok=True)
        self.path = path
        self._file = os.path.join(path, "state.json")
        self._lockfile = os.path.join(path, ".state.lock")

    # -------------------------------------------------------------- read
    def read(self) -> dict:
        """Lock-free read of the current document (``{"rev": 0}`` before
        the first commit). ``os.replace`` is atomic, so a reader racing
        a writer sees the old complete document, never a torn one."""
        try:
            with open(self._file, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return {"rev": 0}
        return doc if isinstance(doc, dict) else {"rev": 0}

    # ------------------------------------------------------------- write
    @contextmanager
    def _locked(self):
        fd = os.open(self._lockfile, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            if fcntl is not None:
                fcntl.flock(fd, fcntl.LOCK_EX)
            yield
        finally:
            if fcntl is not None:
                fcntl.flock(fd, fcntl.LOCK_UN)
            os.close(fd)

    def _write(self, doc: dict):
        """tmp + fsync + atomic rename + directory fsync — a torn
        ``state.json`` must be impossible, even through a power cut
        (the ``utils/serialization`` atomic-write discipline)."""
        tmp = f"{self._file}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._file)
        dirfd = os.open(os.path.dirname(self._file) or ".", os.O_RDONLY)
        try:
            os.fsync(dirfd)
        finally:
            os.close(dirfd)

    def try_replace(self, doc: dict, expected_rev: int) -> bool:
        """Compare-and-swap: commit ``doc`` only if the store is still at
        ``expected_rev``. Returns False (and writes nothing) on a lost
        race — the caller re-reads and retries."""
        with self._locked():
            cur = self.read()
            if int(cur.get("rev", 0)) != int(expected_rev):
                return False
            out = dict(doc)
            out["rev"] = int(expected_rev) + 1
            out["stamp"] = time.time()
            self._write(out)
            return True

    def update(self, mutate: Callable[[dict], Optional[dict]]) -> dict:
        """Serialized read-modify-write: run ``mutate(doc)`` (edit in
        place or return a replacement) under the file lock and commit
        with a bumped ``rev``. A raising ``mutate`` commits nothing."""
        with self._locked():
            doc = self.read()
            rev = int(doc.get("rev", 0))
            out = mutate(doc)
            if out is None:
                out = doc
            out["rev"] = rev + 1
            out["stamp"] = time.time()
            self._write(out)
            return out


def _zero() -> dict:
    return {"n": 0, "err": 0, "lat_sum": 0.0, "lat_n": 0}


def _agg(windows: dict, version: str) -> dict:
    """Sum one version's cumulative counters across every worker that
    ever published (dead workers included — their traffic happened)."""
    out = _zero()
    for per_worker in windows.values():
        w = per_worker.get(version)
        if not isinstance(w, dict):
            continue
        out["n"] += int(w.get("n", 0))
        out["err"] += int(w.get("err", 0))
        out["lat_sum"] += float(w.get("lat_sum", 0.0))
        out["lat_n"] += int(w.get("lat_n", 0))
    return out


def _delta(cur: dict, base: Optional[dict]) -> dict:
    base = base or _zero()
    return {k: max(0, cur[k] - base.get(k, 0)) if k != "lat_sum"
            else max(0.0, cur[k] - base.get(k, 0.0)) for k in cur}


class SharedServingState:
    """One worker's handle on the shared store. See module doc."""

    def __init__(self, store: SharedStore, worker_id: str,
                 routing_ttl_s: float = 0.2):
        self.store = store
        self.worker_id = str(worker_id)
        self._lock = threading.Lock()
        self._pending: Dict[str, dict] = {}       # version -> delta counters
        self._routing_ttl = float(routing_ttl_s)
        self._routing_cache: Tuple[float, dict] = (0.0, {})
        # history watermark starts at the store's CURRENT head: a fresh
        # handle (respawned worker) must adopt the present state, never
        # replay transitions it wasn't alive for (register() re-anchors
        # it too, but the sync thread may beat register in a race)
        self._applied_seq = int(store.read().get("hseq", 0))
        self._is_leader = False

    # ------------------------------------------------------- registration
    def register(self, pid: int, port: int):
        """Announce this worker (called once at startup; the respawn
        drill re-registers under the same worker id and inherits the
        store's current stage — nothing here resets rollout state)."""
        wid = self.worker_id

        def mutate(doc):
            workers = doc.setdefault("workers", {})
            workers[wid] = {"pid": int(pid), "port": int(port),
                            "heartbeat": time.time(),
                            "started": time.time()}
            doc.setdefault("lanes", {})
            doc.setdefault("windows", {}).setdefault(wid, {})
            doc.setdefault("history", [])
            doc.setdefault("hseq", 0)
        self.store.update(mutate)
        # a (re)registered worker must not re-apply the fleet's past
        # transitions — its local deploys already reflect store state
        self._applied_seq = int(self.store.read().get("hseq", 0))

    def ensure_lane(self, lane: str, primary: str):
        """Set the lane's primary IF the lane is new — a respawned
        worker must adopt the fleet's current primary, not reset it."""
        if lane not in LANES:
            raise ValueError(f"unknown lane {lane!r}; one of {LANES}")

        def mutate(doc):
            lanes = doc.setdefault("lanes", {})
            lanes.setdefault(lane, {"primary": primary, "rollout": None})
        self.store.update(mutate)

    # ------------------------------------------------------------ routing
    def routing(self, lane: str) -> dict:
        """The lane's live routing view (cached ``routing_ttl_s`` so the
        hot path reads the store a few times a second, not per request):
        ``{"primary", "candidate", "stage", "share", "active"}``."""
        now = time.monotonic()
        with self._lock:
            at, cache = self._routing_cache
            if now - at < self._routing_ttl and lane in cache:
                return cache[lane]
        doc = self.store.read()
        view = {}
        for ln, st in (doc.get("lanes") or {}).items():
            ro = st.get("rollout") or {}
            view[ln] = {
                "primary": st.get("primary"),
                "candidate": ro.get("candidate"),
                "stage": ro.get("stage"),
                "share": float(ro.get("share", 0.0)),
                "active": bool(ro.get("active")),
            }
        with self._lock:
            self._routing_cache = (now, view)
        return view.get(lane, {"primary": None, "candidate": None,
                               "stage": None, "share": 0.0,
                               "active": False})

    def pick(self, lane: str, frac: float) -> Tuple[Optional[str], bool]:
        """Deterministic hash-split: ``(version, is_canary)`` for one
        request's routing coordinate — every worker computes the same
        answer for the same request because both inputs (content hash,
        store share) are shared."""
        r = self.routing(lane)
        if (r["active"] and r["share"] > 0.0 and r["candidate"]
                and frac < r["share"]):
            return r["candidate"], True
        return r["primary"], False

    # ---------------------------------------------------------- recording
    def record(self, version: str, ok: bool, latency_s: float):
        """Accumulate one served request locally (flushed to the store by
        :meth:`sync` — per-request store writes would serialize the
        fleet on the file lock)."""
        with self._lock:
            w = self._pending.setdefault(version, _zero())
            w["n"] += 1
            if not ok:
                w["err"] += 1
            w["lat_sum"] += float(latency_s)
            w["lat_n"] += 1

    # ------------------------------------------------------------ rollout
    def begin_rollout(self, lane: str, candidate: str,
                      policy: Optional[dict] = None) -> dict:
        """Start a shared rollout of ``candidate`` on ``lane`` (refused
        while one is active — same contract as the local router)."""
        pol = dict(DEFAULT_POLICY)
        pol.update(policy or {})
        pol["ramp_fractions"] = list(pol["ramp_fractions"])

        def mutate(doc):
            st = (doc.setdefault("lanes", {})
                  .setdefault(lane, {"primary": None, "rollout": None}))
            ro = st.get("rollout")
            if ro and ro.get("active"):
                raise RolloutConflictError(
                    f"a shared rollout of {ro.get('candidate')!r} is "
                    f"already active on lane {lane!r}")
            if not st.get("primary"):
                raise RolloutConflictError(
                    f"lane {lane!r} has no primary to canary against "
                    "(ensure_lane first)")
            if st.get("primary") == candidate:
                raise ValueError("candidate is already the primary")
            windows = doc.get("windows") or {}
            st["rollout"] = {
                "candidate": candidate,
                "stage": CANARY,
                "share": float(pol["canary_fraction"]),
                "ramp_idx": -1,
                "healthy_streak": 0,
                "active": True,
                "reason": None,
                "policy": pol,
                "started": time.time(),
                "window_started": time.time(),
                # baseline at start: the fleet's lifetime counters must
                # not grade this rollout (the delta discipline the local
                # canary rules follow)
                "window_base": {
                    candidate: _agg(windows, candidate),
                    st.get("primary"): _agg(windows, st.get("primary")),
                },
            }
            self._note(doc, lane, None, CANARY, share=pol["canary_fraction"])
        out = self.store.update(mutate)
        self._invalidate()
        return out

    def rollback(self, lane: str, reason: str = "manual") -> dict:
        def mutate(doc):
            st = (doc.get("lanes") or {}).get(lane) or {}
            ro = st.get("rollout")
            if not ro or not ro.get("active"):
                return
            prev = ro["stage"]
            ro.update(stage=ROLLED_BACK, share=0.0, active=False,
                      reason=reason)
            self._note(doc, lane, prev, ROLLED_BACK, share=0.0,
                       reason=reason)
        out = self.store.update(mutate)
        self._invalidate()
        return out

    @staticmethod
    def _note(doc: dict, lane: str, prev: Optional[str], new: str,
              **attrs):
        doc["hseq"] = int(doc.get("hseq", 0)) + 1
        event = {"seq": doc["hseq"], "at": time.time(), "lane": lane,
                 "from": prev, "to": new}
        ro = ((doc.get("lanes") or {}).get(lane) or {}).get("rollout") or {}
        event["candidate"] = ro.get("candidate")
        event["primary"] = ((doc.get("lanes") or {}).get(lane)
                            or {}).get("primary")
        event.update(attrs)
        history = doc.setdefault("history", [])
        history.append(event)
        del history[:-_HISTORY_CAP]

    # ---------------------------------------------------------------- sync
    def sync(self) -> List[dict]:
        """One coordination beat (the front door's background thread
        calls this a few times a second): flush locally-accumulated
        window counters, heartbeat, and — when this worker is the leader
        — close due windows over the FLEET aggregate and advance/roll
        back the shared stage. Returns the history events this worker
        has not yet applied locally (promotions/rollbacks → the caller
        repoints and drains its local deploys)."""
        with self._lock:
            pending, self._pending = self._pending, {}
        wid = self.worker_id

        def mutate(doc):
            workers = doc.setdefault("workers", {})
            me = workers.setdefault(wid, {"pid": os.getpid(), "port": 0,
                                          "started": time.time()})
            me["heartbeat"] = time.time()
            mine = doc.setdefault("windows", {}).setdefault(wid, {})
            for version, d in pending.items():
                w = mine.setdefault(version, _zero())
                w["n"] += d["n"]
                w["err"] += d["err"]
                w["lat_sum"] += d["lat_sum"]
                w["lat_n"] += d["lat_n"]
            alive = [w for w, rec in workers.items()
                     if time.time() - float(rec.get("heartbeat", 0))
                     <= WORKER_TTL_S]
            self._is_leader = bool(alive) and min(alive) == wid
            if self._is_leader:
                for lane, st in (doc.get("lanes") or {}).items():
                    self._evaluate_lane(doc, lane, st)
        try:
            doc = self.store.update(mutate)
        except BaseException:
            # a failed store write must not LOSE the popped window
            # counters — merge them back so the next beat flushes them
            # (dropped samples would let the leader grade a window that
            # silently undercounts a failing candidate's errors)
            with self._lock:
                for version, d in pending.items():
                    w = self._pending.setdefault(version, _zero())
                    for k in d:
                        w[k] += d[k]
            raise
        self._invalidate()
        events = [e for e in doc.get("history", [])
                  if int(e.get("seq", 0)) > self._applied_seq]
        if events:
            self._applied_seq = max(int(e["seq"]) for e in events)
        return events

    def _evaluate_lane(self, doc: dict, lane: str, st: dict):
        """Leader-only: close the lane's window if due and grade the
        fleet-aggregated deltas (error rate + latency-mean ratio; any
        non-ok grade rolls back, ok streaks advance — the local
        CanaryRollout's promotion discipline over shared counters)."""
        ro = st.get("rollout")
        if not ro or not ro.get("active"):
            return
        pol = ro.get("policy") or DEFAULT_POLICY
        now = time.time()
        if now - float(ro.get("window_started", now)) \
                < float(pol["window_seconds"]):
            return
        windows = doc.get("windows") or {}
        cand, prim = ro["candidate"], st.get("primary")
        base = ro.get("window_base") or {}
        cand_cur = _agg(windows, cand)
        prim_cur = _agg(windows, prim)
        d_cand = _delta(cand_cur, base.get(cand))
        d_prim = _delta(prim_cur, base.get(prim))
        if d_cand["n"] < int(pol["window_min_requests"]):
            return          # window stays open until samples arrive
        status = OK
        detail = {}
        rate = d_cand["err"] / d_cand["n"]
        detail["error_rate"] = rate
        status = _worst(status, _grade(rate, pol["error_rate_degraded"],
                                       pol["error_rate_failing"]))
        if (d_cand["lat_n"] >= int(pol["min_latency_n"])
                and d_prim["lat_n"] >= int(pol["min_latency_n"])
                and d_prim["lat_sum"] > 0):
            ratio = ((d_cand["lat_sum"] / d_cand["lat_n"])
                     / (d_prim["lat_sum"] / d_prim["lat_n"]))
            detail["latency_ratio"] = ratio
            status = _worst(status, _grade(
                ratio, pol["latency_ratio_degraded"],
                pol["latency_ratio_failing"]))
        ro["window_started"] = now
        ro["window_base"] = {cand: cand_cur, prim: prim_cur}
        ro["last_report"] = dict(detail, status=status,
                                 window_requests=d_cand["n"])
        if status in (DEGRADED, FAILING):
            prev = ro["stage"]
            ro.update(stage=ROLLED_BACK, share=0.0, active=False,
                      reason=f"slo:{status} {detail}")
            self._note(doc, lane, prev, ROLLED_BACK, share=0.0,
                       reason=ro["reason"])
            return
        ro["healthy_streak"] = int(ro.get("healthy_streak", 0)) + 1
        if ro["healthy_streak"] < int(pol["healthy_windows"]):
            return
        ro["healthy_streak"] = 0
        prev = ro["stage"]
        ramp = list(pol.get("ramp_fractions") or ())
        idx = int(ro.get("ramp_idx", -1)) + 1
        if idx < len(ramp):
            ro.update(stage=RAMP, share=float(ramp[idx]), ramp_idx=idx)
            self._note(doc, lane, prev, RAMP, share=ro["share"])
        else:
            old_primary = st.get("primary")
            ro.update(stage=FULL, share=1.0, active=False)
            st["primary"] = ro["candidate"]
            self._note(doc, lane, prev, FULL, share=1.0,
                       old_primary=old_primary)

    def _invalidate(self):
        with self._lock:
            self._routing_cache = (0.0, {})

    # ------------------------------------------------------------ queries
    @property
    def is_leader(self) -> bool:
        return self._is_leader

    def alive_workers(self, ttl_s: float = WORKER_TTL_S) -> Dict[str, dict]:
        now = time.time()
        return {w: rec for w, rec
                in (self.store.read().get("workers") or {}).items()
                if now - float(rec.get("heartbeat", 0)) <= ttl_s}

    def snapshot(self) -> dict:
        doc = self.store.read()
        now = time.time()
        workers = {
            w: dict(rec, alive=(now - float(rec.get("heartbeat", 0))
                                <= WORKER_TTL_S))
            for w, rec in (doc.get("workers") or {}).items()}
        return {
            "path": self.store.path,
            "rev": doc.get("rev", 0),
            "worker_id": self.worker_id,
            "is_leader": self._is_leader,
            "lanes": doc.get("lanes", {}),
            "workers": workers,
            "history": doc.get("history", [])[-16:],
        }


_SEVERITY = {OK: 0, DEGRADED: 1, FAILING: 2}


def _worst(a: str, b: str) -> str:
    return a if _SEVERITY[a] >= _SEVERITY[b] else b
