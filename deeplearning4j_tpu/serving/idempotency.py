"""Idempotent request retries: the front door's result journal.

A client (or the fleet proxy's connect-failover) that retries after a
worker death cannot know whether the original request executed — and a
re-executed generation double-charges PR-12 tenant token debt and
double-spends device work. The fix is the standard idempotency-key
contract: the caller stamps ``X-Dl4j-Idempotency-Key`` on
``/v1/classify`` / ``/v1/generate``; the door journals one outcome per
key and a retried key **returns the original outcome** (or attaches to
the still-in-flight request) without re-executing — so QoS token debt is
charged exactly once per key, by construction.

Journal policy (who gets remembered):

- an outcome reached AFTER execution began — success, deadline, stream
  cancel, device error — is **resolved** into the journal: partial work
  may have been charged, so a retry must replay, never re-run;
- a rejection BEFORE execution (quota 429 at the door, the in-flight
  gate, the disabled switch) **abandons** the key: nothing ran, nothing
  was charged, and a later retry deserves a real attempt;
- a retry arriving while the original is still executing **attaches**:
  it waits (bounded) for the in-flight resolution and returns it.

The journal is bounded two ways: resolved entries expire after
``DL4J_TPU_IDEMPOTENCY_TTL_S`` (default 600 s — longer than any sane
client retry horizon) and the table caps at
``DL4J_TPU_IDEMPOTENCY_MAX`` entries (default 4096; oldest RESOLVED
entries evicted first, in-flight entries never). Keys above the cap are
served untracked (at-least-once, counted) rather than refused —
availability over bookkeeping.

Every replay served is counted (``dl4j_fleet_idempotent_replays_total``)
and the per-key execution counts are exported on ``/debug/fleet`` /
``fleet.json`` — the fleet chaos drill audits "zero duplicate
executions" directly from this table.

Kill switch ``DL4J_TPU_IDEMPOTENCY=0`` (read live): the header is inert,
no journal exists, no new metric series — byte-identical pre-journal
behavior.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from deeplearning4j_tpu.resilience import faults as _faults

#: the idempotency-key request header (absent = no journal interaction)
IDEMPOTENCY_HEADER = "X-Dl4j-Idempotency-Key"

#: the response header a replayed/attached outcome carries
REPLAY_HEADER = "X-Dl4j-Idempotent-Replay"

NEW, INFLIGHT, DONE = "new", "inflight", "done"


def idempotency_enabled() -> bool:
    """``DL4J_TPU_IDEMPOTENCY`` kill switch (read live, per request)."""
    return os.environ.get("DL4J_TPU_IDEMPOTENCY", "1") != "0"


def journal_ttl_s() -> float:
    """``DL4J_TPU_IDEMPOTENCY_TTL_S``: how long a resolved outcome
    stays replayable."""
    try:
        return max(1.0, float(
            os.environ.get("DL4J_TPU_IDEMPOTENCY_TTL_S", 600.0)))
    except (TypeError, ValueError):
        return 600.0


def journal_max_entries() -> int:
    """``DL4J_TPU_IDEMPOTENCY_MAX``: journal table cap."""
    try:
        return max(16, int(os.environ.get("DL4J_TPU_IDEMPOTENCY_MAX",
                                          4096)))
    except (TypeError, ValueError):
        return 4096


def _replays_total():
    def make():
        from deeplearning4j_tpu.observability import global_registry
        return global_registry().counter(
            "dl4j_fleet_idempotent_replays_total",
            "retried idempotency keys served from the result journal "
            "(or attached to the in-flight original) WITHOUT "
            "re-executing — each one is a prevented duplicate "
            "execution / double charge")
    return _faults.cached_metric_handle(("fleet", "idem_replays"), make)


class _Entry:
    __slots__ = ("key", "state", "code", "payload", "event", "created",
                 "resolved_at", "executions", "replays")

    def __init__(self, key: str):
        self.key = key
        self.state = INFLIGHT
        self.code: Optional[int] = None
        self.payload: Optional[dict] = None
        self.event = threading.Event()
        self.created = time.monotonic()
        self.resolved_at: Optional[float] = None
        self.executions = 0
        self.replays = 0


class ResultJournal:
    """Bounded, TTL'd key → outcome table. One per process (the
    journal's exactly-once scope is the worker — a cross-process retry
    that lands on a DIFFERENT worker only re-executes when the original
    worker died with its un-charged work, which is the safe case)."""

    def __init__(self, ttl_s: Optional[float] = None,
                 max_entries: Optional[int] = None):
        self._ttl = ttl_s
        self._max = max_entries
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._untracked = 0     # keys served at-least-once past the cap

    def _ttl_s(self) -> float:
        return self._ttl if self._ttl is not None else journal_ttl_s()

    def _cap(self) -> int:
        return self._max if self._max is not None else journal_max_entries()

    # ------------------------------------------------------------ begin
    def begin(self, key: str) -> Tuple[Optional[_Entry], str]:
        """First sight of ``key`` → a fresh INFLIGHT entry + ``"new"``
        (the caller executes and must resolve/abandon). A known key →
        its entry + ``"inflight"``/``"done"`` (the caller replays).
        ``(None, "new")`` = the table is saturated with in-flight work:
        the request is served untracked rather than refused."""
        key = str(key)[:256]
        now = time.monotonic()
        with self._lock:
            self._purge_locked(now)
            entry = self._entries.get(key)
            if entry is not None:
                return entry, entry.state
            if len(self._entries) >= self._cap():
                if not self._evict_locked():
                    self._untracked += 1
                    return None, NEW
            entry = self._entries[key] = _Entry(key)
            return entry, NEW

    def _purge_locked(self, now: float):
        # graftlint: disable=lock-discipline — *_locked contract: every
        # caller holds self._lock around this helper
        stale = [k for k, e in self._entries.items()
                 if e.resolved_at is not None
                 and now - e.resolved_at > self._ttl_s()]
        for k in stale:
            del self._entries[k]

    def _evict_locked(self) -> bool:
        """Drop the oldest RESOLVED entry; in-flight entries are never
        evicted (evicting one would detach its eventual resolution)."""
        # graftlint: disable=lock-discipline — *_locked contract: every
        # caller holds self._lock around this helper
        for k, e in self._entries.items():
            if e.state == DONE:
                del self._entries[k]
                return True
        return False

    # ------------------------------------------------------- resolution
    def mark_executing(self, key: str):
        """Execution actually began under ``key`` — from here on, ANY
        outcome (success, deadline, cancel, device error) must be
        resolved, never abandoned: partial work may have been charged."""
        with self._lock:
            entry = self._entries.get(str(key)[:256])
            if entry is not None:
                entry.executions += 1

    def resolve(self, key: str, code: int, payload: dict):
        with self._lock:
            entry = self._entries.get(str(key)[:256])
            if entry is None or entry.state == DONE:
                return
            entry.code = int(code)
            entry.payload = dict(payload or {})
            entry.state = DONE
            entry.resolved_at = time.monotonic()
        entry.event.set()

    def abandon(self, key: str):
        """A pre-execution rejection: forget the key so a later retry
        gets a real attempt (waiters re-drive through begin())."""
        with self._lock:
            entry = self._entries.pop(str(key)[:256], None)
        if entry is not None:
            entry.event.set()

    # ----------------------------------------------------------- replay
    def await_outcome(self, entry: _Entry,
                      timeout_s: float = 30.0) -> Optional[Tuple[int, dict]]:
        """Wait for the entry's resolution (immediate when DONE) and
        count the replay. None = the original is still executing past
        the wait (caller answers retry-later) or the key was abandoned
        mid-wait (caller may re-begin)."""
        if not entry.event.wait(timeout=max(0.0, timeout_s)):
            return None
        if entry.state != DONE:
            return None                   # abandoned: key forgotten
        with self._lock:
            entry.replays += 1
        _replays_total().inc()
        _faults.record_event("idempotent_replay", key=entry.key,
                             code=entry.code)
        return entry.code, dict(entry.payload or {})

    # ---------------------------------------------------------- queries
    def snapshot(self) -> dict:
        """``/debug/fleet`` / ``fleet.json`` payload — per-key execution
        counts are the drill's duplicate-execution audit surface."""
        with self._lock:
            entries = {
                k: {"state": e.state, "code": e.code,
                    "executions": e.executions, "replays": e.replays,
                    "age_s": round(time.monotonic() - e.created, 3)}
                for k, e in self._entries.items()}
            untracked = self._untracked
        return {
            "enabled": idempotency_enabled(),
            "ttl_s": self._ttl_s(),
            "max_entries": self._cap(),
            "size": len(entries),
            "untracked": untracked,
            "replays": sum(e["replays"] for e in entries.values()),
            "duplicate_executions": sum(
                max(0, e["executions"] - 1) for e in entries.values()),
            "entries": entries,
        }


# ------------------------------------------------------ process wiring
_journal: Optional[ResultJournal] = None
_journal_lock = threading.Lock()


def global_journal() -> ResultJournal:
    global _journal
    if _journal is None:
        with _journal_lock:
            if _journal is None:
                _journal = ResultJournal()
    return _journal


def reset_global_journal() -> ResultJournal:
    global _journal
    with _journal_lock:
        _journal = ResultJournal()
    return _journal


def snapshot() -> dict:
    """Never constructs the journal: a process that saw no idempotency
    keys reports an empty table."""
    if _journal is None:
        return {"enabled": idempotency_enabled(), "size": 0,
                "untracked": 0, "replays": 0, "duplicate_executions": 0,
                "entries": {}}
    return global_journal().snapshot()
