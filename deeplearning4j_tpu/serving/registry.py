"""Versioned model registry: deploy with AOT warmup, retire with drain.

A deploy used to mean cold-starting a fresh ``ParallelInference`` and
eating one whole-program XLA compile per shape bucket on live traffic.
:meth:`ModelRegistry.deploy` moves that cost to deploy time: every
configured bucket executable is compiled (and its cost accounted) by
executing a zero batch through the real jitted entry point *before* the
version is marked eligible — the first real request on any bucket shape
is a pure cache hit, zero new traces, zero backend compiles.

Why execute instead of AOT ``lower().compile()``: on this jax an AOT
compile seeds the tracing cache but NOT the executable dispatch cache —
the first real call would skip the retrace yet still backend-compile a
second time. Executing the zero batch seeds both. The warmup traces are
still accounted honestly by compile_watch (cause ``serving_warmup``,
the same best-effort attribution the bucket-miss path uses); the
``suppress_probes()`` spelling is reserved for lowerings that compile
nothing (cost_model), which warmup is not.

Persistent compile cache: when ``DL4J_TPU_COMPILE_CACHE`` names a
directory, deploy wires jax's persistent compilation cache at it first
(:func:`async_runtime.configure_compile_cache`), so a re-deploy of a
known version — or a process restart — retrieves every bucket executable
from disk instead of compiling (asserted by the tier-1 cache test via
jax's ``compilation_cache/cache_hits`` event).

Retire goes through **graceful drain**: the version stops admitting, the
router's in-flight requests complete (bounded wait on the version's
in-flight count), any stragglers resolve with the typed
``ShutdownError`` via ``ParallelInference.shutdown`` — never dropped,
never double-resolved (the PR-5 ``claim()`` machinery) — and only then
do the serve threads, breaker, and executables release.
"""
from __future__ import annotations

import contextlib
import threading
import time
import weakref
from typing import Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import async_runtime as _async
from deeplearning4j_tpu.observability import compile_watch as _cw
from deeplearning4j_tpu.observability import cost_model as _cost
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.policy import CircuitBreaker
from deeplearning4j_tpu.serving.metrics import serving_metrics

#: version lifecycle states, in order
WARMING, LIVE, DRAINING, RETIRED = "warming", "live", "draining", "retired"


class DeployedVersion:
    """One live model version: its ``ParallelInference`` (scoring) or
    ``GenerationPipeline`` (generative decode), lifecycle state, warmup
    record, and the in-flight count graceful drain waits on. The router
    enters :meth:`track` around every request it sends here."""

    def __init__(self, version: str, net, pi: Optional[ParallelInference],
                 gp=None):
        self.version = version
        self.net = net
        self.pi = pi
        self.gp = gp
        self.kind = "generative" if gp is not None else "scoring"
        self.state = WARMING
        self.admitting = False
        self.deployed_at = time.time()
        self.warmup_seconds: Optional[float] = None
        self.warmed_buckets: List[int] = []
        self._cond = threading.Condition()
        self._inflight = 0
        self._drain_done = threading.Event()

    @contextlib.contextmanager
    def track(self):
        """Count one request in flight on this version (drain barrier)."""
        with self._cond:
            self._inflight += 1
        try:
            yield
        finally:
            with self._cond:
                self._inflight -= 1
                self._cond.notify_all()

    def inflight(self) -> int:
        with self._cond:
            return self._inflight

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Stop admitting, wait for in-flight requests to resolve, then
        release the serve pipeline. Returns True when the drain emptied
        cleanly; on timeout the shutdown still resolves every straggler
        with the typed ``ShutdownError`` (claimed exactly once). A
        second caller racing an in-progress drain (a retire() landing
        during a rollback) WAITS for that drain to finish instead of
        reporting success while requests are still in flight."""
        self.admitting = False
        with self._cond:
            if self.state == RETIRED:
                return True
            if self.state == DRAINING:
                owner = False
            else:
                self.state = DRAINING
                owner = True
        if not owner:
            self._drain_done.wait(max(0.0, timeout_s) + 10.0)
            return self.state == RETIRED
        _faults.record_event("serving_drain", version=self.version,
                             inflight=self.inflight())
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            drained = self._inflight == 0
        if self.pi is not None:
            self.pi.shutdown()
        if self.gp is not None:
            self.gp.shutdown()
        with self._cond:
            self.state = RETIRED
        # release the strong refs so the executables and device buffers
        # (including a generative version's KV-cache pages) can go with
        # the version (callers keep their own net reference)
        self.pi = None
        self.gp = None
        self.net = None
        self._drain_done.set()
        return drained

    def snapshot(self) -> dict:
        return {
            "version": self.version,
            "kind": self.kind,
            "state": self.state,
            "admitting": self.admitting,
            "deployed_at": self.deployed_at,
            "warmup_seconds": self.warmup_seconds,
            "warmed_buckets": list(self.warmed_buckets),
            "inflight": self.inflight(),
        }


class ModelRegistry:
    """Holds N live versions; ``deploy`` warms, ``retire`` drains."""

    _live: "weakref.WeakSet[ModelRegistry]" = weakref.WeakSet()

    def __init__(self):
        self._versions: Dict[str, DeployedVersion] = {}
        self._reserving: set = set()    # names mid-deploy (TOCTOU guard)
        self._lock = threading.Lock()
        ModelRegistry._live.add(self)

    # ------------------------------------------------------------- deploy
    def _deploy_scaffold(self, version: str, build, warm) -> DeployedVersion:
        """The shared deploy lifecycle both deploy kinds run: one atomic
        name reservation (a concurrent deploy of the same name must fail
        HERE, not both build a pipeline and silently orphan one), the
        persistent compile cache (the warmup compiles are exactly what a
        restart should retrieve from disk), registration, warmup with
        cleanup-on-failure (a version that failed to warm must not
        linger in WARMING with live serve threads, nor block a redeploy
        of its name), and the LIVE/admitting flip. ``build()`` returns
        the :class:`DeployedVersion`; ``warm(dv)`` returns the
        warmed-bucket list."""
        with self._lock:
            existing = self._versions.get(version)
            if (version in self._reserving
                    or (existing is not None
                        and existing.state != RETIRED)):
                state = ("deploying" if version in self._reserving
                         else existing.state)
                raise ValueError(f"version {version!r} already deployed "
                                 f"(state={state})")
            self._reserving.add(version)
        try:
            _async.configure_compile_cache()
            dv = build()
            with self._lock:
                self._versions[version] = dv
            t0 = time.perf_counter()
            try:
                dv.warmed_buckets = warm(dv)
            except Exception:
                dv.drain(timeout_s=0.0)
                with self._lock:
                    self._versions.pop(version, None)
                raise
            dv.warmup_seconds = time.perf_counter() - t0
        finally:
            with self._lock:
                self._reserving.discard(version)
        serving_metrics().warmup_seconds(version).set(dv.warmup_seconds)
        dv.state = LIVE
        dv.admitting = True
        return dv

    def deploy(self, version: str, net, sample_input=None,
               warmup: bool = True, **pi_kwargs) -> DeployedVersion:
        """Build a ``ParallelInference`` over ``net`` and (with a
        ``sample_input`` example to take shapes/dtype from) AOT-warm
        every shape-bucket executable before marking the version
        eligible for traffic. ``pi_kwargs`` pass through to the
        ``ParallelInference`` constructor; a per-version circuit breaker
        is installed unless the caller provides one."""
        def build():
            pi_kwargs.setdefault(
                "breaker",
                CircuitBreaker(f"inference.device_execute:{version}"))
            return DeployedVersion(version, net,
                                   ParallelInference(net, **pi_kwargs))

        def warm(dv):
            if warmup and sample_input is not None:
                return self._warmup(dv, np.asarray(sample_input))
            return []

        dv = self._deploy_scaffold(version, build, warm)
        _faults.record_event("serving_deploy", version=version,
                            warmup_seconds=round(dv.warmup_seconds, 4),
                            buckets=len(dv.warmed_buckets))
        return dv

    # -------------------------------------------------- generative deploy
    def deploy_generative(self, version: str, engine, warmup: bool = True,
                          **gp_kwargs) -> DeployedVersion:
        """Deploy a generative version: a
        :class:`~deeplearning4j_tpu.parallel.generation.GenerationPipeline`
        over ``engine`` (a ``DecodeEngine``), AOT-warming every prefill
        length-bucket executable, the slot-insert executables, and the
        decode-step executable before the version admits traffic — the
        first real ``generate`` request triggers zero new traces, the
        same contract scoring deploys make. A speculative engine (built
        with a ``draft=``) warms the PAIR: the draft's prefill/insert
        set, the fused k-token propose executable, and the windowed
        verify executable all compile here, and retire's drain releases
        draft and target together (the engine owns both). The int8 KV
        numerics gate also runs here (first cache build), so a
        quant fallback is decided before traffic, never under it.
        ``gp_kwargs`` pass through to the pipeline constructor
        (``cache_pages=`` sizes the paged admission pool); a
        per-version circuit breaker is installed unless the caller
        provides one."""
        from deeplearning4j_tpu.parallel.generation import GenerationPipeline

        def build():
            gp_kwargs.setdefault(
                "breaker", CircuitBreaker(f"generation.step:{version}"))
            gp = GenerationPipeline(engine, **gp_kwargs)
            return DeployedVersion(version, engine.model, None, gp=gp)

        def warm(dv):
            if warmup:
                return self._warmup_generative(engine, dv.gp.slots)
            return []

        dv = self._deploy_scaffold(version, build, warm)
        _faults.record_event("serving_deploy", version=version,
                             generative=True,
                             warmup_seconds=round(dv.warmup_seconds, 4),
                             buckets=len(dv.warmed_buckets))
        return dv

    @staticmethod
    def _warmup_generative(engine, slots: int) -> List[int]:
        """Compile the whole generative executable set off the traffic
        path (``DecodeEngine.warm`` — one spelling with the decode
        benchmark); each compile it provokes is claimed as a warmup so
        /debug/compiles names the deploy behind it."""
        return engine.warm(
            slots, note=lambda **a: _cw.note_cause("serving_warmup", **a))

    @staticmethod
    def _warmup(dv: DeployedVersion, sample: np.ndarray) -> List[int]:
        """Execute a zero batch per configured bucket through the serve
        path's forward, blocking on each result — every bucket executable
        is compiled and dispatch-cached before real traffic arrives.
        ``sample`` is one example (or a batch; the leading axis is
        replaced by the bucket size)."""
        pi, net = dv.pi, dv.net
        trailing = sample.shape[1:] if sample.ndim > 1 else sample.shape
        warmed: List[int] = []
        for bucket in pi.bucket_sizes:
            x = np.zeros((bucket,) + tuple(trailing), sample.dtype)
            # the compile this provokes is claimed as a warmup, not a
            # bucket miss — /debug/compiles names the deploy behind it
            _cw.note_cause("serving_warmup", version=dv.version,
                           bucket=bucket)
            np.asarray(pi._forward(x))     # execute + block: cache seeded
            # bucket bookkeeping: the serve loop must read these shapes
            # as hits (they ARE compiled for this instance), and no
            # bucket_miss cause may dangle on the first real batch
            pi._seen_buckets.add((bucket,))
            net.__dict__.setdefault("_cw_seen_buckets", set()).add((bucket,))
            _cost.maybe_account_bucket(net, bucket, x)
            warmed.append(bucket)
        return warmed

    # ------------------------------------------------------------ queries
    def get(self, version: str) -> DeployedVersion:
        with self._lock:
            dv = self._versions.get(version)
        if dv is None:
            raise KeyError(f"no deployed version {version!r}")
        return dv

    def versions(self) -> List[str]:
        with self._lock:
            return sorted(self._versions)

    def live_versions(self) -> List[str]:
        with self._lock:
            return sorted(v for v, dv in self._versions.items()
                          if dv.state == LIVE)

    # ------------------------------------------------------------- retire
    def retire(self, version: str, drain_timeout_s: float = 5.0) -> bool:
        """Graceful removal: drain (see :meth:`DeployedVersion.drain`)
        and forget the version. Returns True when the drain emptied
        before the timeout."""
        dv = self.get(version)
        drained = dv.drain(timeout_s=drain_timeout_s)
        with self._lock:
            self._versions.pop(version, None)
        _faults.record_event("serving_retire", version=version,
                             drained=drained)
        return drained

    def shutdown(self, drain_timeout_s: float = 5.0):
        """Retire every version (test teardown / process exit)."""
        for version in self.versions():
            try:
                self.retire(version, drain_timeout_s=drain_timeout_s)
            except KeyError:
                pass

    def snapshot(self) -> dict:
        with self._lock:
            versions = [dv.snapshot() for _, dv in sorted(
                self._versions.items())]
        return {"versions": versions,
                "compile_cache_dir": _async.compile_cache_dir()}
